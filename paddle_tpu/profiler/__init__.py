"""paddle_tpu.profiler — profiling/tracing.

Reference parity: paddle.profiler.Profiler with scheduler windows,
RecordEvent spans, export_chrome_tracing, summary tables, throughput timer
(upstream python/paddle/profiler/ + C++ host/CUPTI tracers — unverified,
see SURVEY.md §5.1).

TPU-native: device timeline comes from `jax.profiler` (XPlane → perfetto/
TensorBoard — the CUPTI-equivalent); host spans from
jax.profiler.TraceAnnotation + a lightweight in-process event table that
powers `summary()`.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict
from enum import Enum

import jax

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing",
           "load_profiler_result", "events_dropped"]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Returns fn(step)->ProfilerState over cyclic windows."""
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


# The host event table is shared across threads (the serving engine's
# concurrent loop threads all emit spans): appends take _events_lock,
# the nesting stack is THREAD-LOCAL (a span begun on thread A must
# never be popped by thread B), and each event carries its emitting
# thread's ident as the chrome `tid` so concurrent timelines render as
# separate lanes instead of colliding on tid 0.  The table is bounded
# (PADDLE_TPU_PROFILE_MAX_EVENTS, default 1e6): overflow is counted,
# not stored — a runaway span loop degrades the profile, never memory.
_events: list[dict] = []
_events_lock = threading.Lock()
_events_dropped = 0
_tls = threading.local()

_MAX_EVENTS_ENV = "PADDLE_TPU_PROFILE_MAX_EVENTS"


def _max_events():
    try:
        return max(1, int(os.environ.get(_MAX_EVENTS_ENV, "1000000")))
    except ValueError:
        return 1000000


def _thread_stack():
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def events_dropped():
    """Spans shed by the event-table cap since the last start()."""
    return _events_dropped


class RecordEvent:
    """Host-side span; nests (per thread); feeds summary() and chrome
    export.  Safe to begin/end concurrently from several threads."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._ann = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        _thread_stack().append(self)

    def end(self):
        global _events_dropped
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        stack = _thread_stack()
        if stack and stack[-1] is self:
            stack.pop()
        ev = {"name": self.name, "ts": self._t0 / 1e3,
              "dur": (t1 - self._t0) / 1e3, "ph": "X",
              "pid": os.getpid(), "tid": threading.get_ident()}
        with _events_lock:
            if len(_events) >= _max_events():
                _events_dropped += 1
            else:
                _events.append(ev)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()
        return False


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0],
                           closed=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else
            (lambda step: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._jax_tracing = False
        self._logdir = None
        self._timer_only = timer_only
        self._step_times: list[float] = []
        self._t_last = None

    def start(self):
        global _events_dropped
        with _events_lock:
            _events.clear()
            _events_dropped = 0
        self._state = self._scheduler(self._step)
        self._maybe_toggle()
        self._t_last = time.perf_counter()

    def stop(self):
        if self._jax_tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_tracing = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t_last is not None:
            self._step_times.append(now - self._t_last)
        self._t_last = now
        self._step += 1
        new_state = self._scheduler(self._step)
        if new_state != self._state:
            self._state = new_state
            self._maybe_toggle()

    def _maybe_toggle(self):
        want = self._state in (ProfilerState.RECORD,
                               ProfilerState.RECORD_AND_RETURN)
        if want and not self._jax_tracing and not self._timer_only:
            self._logdir = os.environ.get("PADDLE_TPU_PROFILE_DIR",
                                          "/tmp/paddle_tpu_profile")
            try:
                jax.profiler.start_trace(self._logdir)
                self._jax_tracing = True
            except Exception:
                pass
        elif not want and self._jax_tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_tracing = False

    def export_chrome_tracing(self, dir_name, worker_name=None):
        os.makedirs(dir_name, exist_ok=True)
        path = os.path.join(dir_name,
                            (worker_name or "worker") + ".json")
        with _events_lock:
            snapshot = list(_events)
        with open(path, "w") as f:
            json.dump({"traceEvents": snapshot}, f)
        return path

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        agg = defaultdict(lambda: [0.0, 0])
        with _events_lock:
            snapshot = list(_events)
        for e in snapshot:
            agg[e["name"]][0] += e["dur"] / 1e3
            agg[e["name"]][1] += 1
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}",
                 "-" * 72]
        for name, (total, calls) in sorted(agg.items(),
                                           key=lambda kv: -kv[1][0]):
            lines.append(f"{name:<40}{calls:>8}{total:>12.3f}"
                         f"{total / calls:>12.3f}")
        if self._step_times:
            avg = sum(self._step_times) / len(self._step_times)
            lines.append(f"steps: {len(self._step_times)}  avg "
                         f"{avg * 1e3:.2f} ms  ips {1.0 / avg:.2f}")
        out = "\n".join(lines)
        print(out)
        return out

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof.export_chrome_tracing(dir_name, worker_name)
    return handler


def load_profiler_result(filename):
    with open(filename) as f:
        return json.load(f)
