"""Wire format for KV page migration — the ``/v1/_pages`` payload.

The disaggregated serving tier moves a sequence's K/V page chain
between replicas (prefill → decode handoff).  In-process replicas hand
the numpy arrays over directly; HTTP replicas ship this format: a
fixed magic, a length-prefixed JSON header (cache geometry + sequence
meta + the generation-continuation request), then the raw page bytes
of every layer's K then V arrays, concatenated in header order.

The fleet prefix cache (round 18) rides the SAME format: a prefix-ship
payload carries ``meta["kind"] == "prefix"`` (radix-tree pages with no
live sequence behind them) and no continuation request — the
``/v1/_pages/prefix`` endpoints answer with JSON rather than an SSE
stream.  Everything below is payload-kind agnostic by design; the
allocator's importers re-validate geometry either way.

Deserialization is strict: magic, header shape, declared dtype/shape
versus the actual byte count are all checked here, and the allocator
re-checks geometry against itself at import
(:meth:`PagedKVCache.check_geometry`) — a malformed or mis-shaped
payload can never scatter into the device buffers.

The format is host-order binary (little-endian length prefix); both
ends of a migration run the same stack, and the JSON header carries
the dtype string so an endianness or dtype skew is caught, not
mis-read.

Round 20 (hierarchical KV tiers): the header carries an OPTIONAL
``crc32`` field (zlib CRC over the concatenated array bytes).  The
serializer always writes it; the deserializer verifies it only when
present, so payloads produced by older writers keep deserializing.
Spilled pages parked in the host/disk tiers sit at rest far longer
than a live migration transfer — the CRC is what turns silent
bit-rot (or a chaos-corrupted payload) into a detected
:class:`WireFormatError` the tier degrades to a recompute.
"""
from __future__ import annotations

import json
import struct
import zlib

import numpy as np

__all__ = ["MAGIC", "serialize_pages", "deserialize_pages",
           "WireFormatError"]

MAGIC = b"PTKV1\n"
_LEN = struct.Struct("<Q")
# a page payload is bounded by the source cache size; anything past
# this is a protocol error, not a transfer (guards the HTTP handler
# against unbounded reads)
MAX_PAYLOAD_BYTES = 1 << 31


class WireFormatError(ValueError):
    """The byte stream is not a valid page-migration payload."""


def serialize_pages(meta, k_arrays, v_arrays, request=None):
    """Pack ``(meta, k, v)`` — the :meth:`PagedKVCache.export_pages`
    result — plus an optional ``request`` continuation dict into one
    ``bytes`` payload."""
    arrays = list(k_arrays) + list(v_arrays)
    body = [np.ascontiguousarray(a).tobytes() for a in arrays]
    crc = 0
    for b in body:
        crc = zlib.crc32(b, crc)
    header = {
        "meta": dict(meta),
        "request": dict(request) if request is not None else None,
        "arrays": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                   for a in arrays],
        "n_layers_k": len(k_arrays),
        "crc32": crc,
    }
    hdr = json.dumps(header).encode()
    return b"".join([MAGIC, _LEN.pack(len(hdr)), hdr] + body)


def deserialize_pages(buf):
    """Unpack a payload into ``(meta, k_arrays, v_arrays, request)``.
    Raises :class:`WireFormatError` on any structural mismatch."""
    if not buf.startswith(MAGIC):
        raise WireFormatError("bad magic: not a KV page payload")
    off = len(MAGIC)
    if len(buf) < off + _LEN.size:
        raise WireFormatError("truncated header length")
    (hlen,) = _LEN.unpack_from(buf, off)
    off += _LEN.size
    if hlen > MAX_PAYLOAD_BYTES or len(buf) < off + hlen:
        raise WireFormatError("truncated header")
    try:
        header = json.loads(buf[off:off + hlen])
    except ValueError as e:
        raise WireFormatError(f"header is not JSON: {e}") from e
    off += hlen
    try:
        meta = dict(header["meta"])
        specs = header["arrays"]
        n_k = int(header["n_layers_k"])
        request = header.get("request")
        crc = header.get("crc32")
    except (KeyError, TypeError, ValueError) as e:
        raise WireFormatError(f"malformed header: {e}") from e
    data_start = off
    if not 0 <= n_k <= len(specs):
        raise WireFormatError(
            f"n_layers_k={n_k} outside the {len(specs)} declared arrays")
    arrays = []
    for spec in specs:
        try:
            shape = tuple(int(d) for d in spec["shape"])
            dtype = np.dtype(spec["dtype"])
        except (KeyError, TypeError, ValueError) as e:
            raise WireFormatError(f"malformed array spec: {e}") from e
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if nbytes < 0 or len(buf) < off + nbytes:
            raise WireFormatError(
                f"truncated array payload: declared {shape} {dtype} "
                f"needs {nbytes} byte(s), {len(buf) - off} left")
        arrays.append(np.frombuffer(
            buf, dtype=dtype, count=int(np.prod(shape, dtype=np.int64)),
            offset=off).reshape(shape))
        off += nbytes
    if off != len(buf):
        raise WireFormatError(
            f"{len(buf) - off} trailing byte(s) after the declared "
            "arrays")
    if crc is not None and zlib.crc32(buf[data_start:]) != int(crc):
        # at-rest corruption (host/disk tier bit-rot, chaos
        # tier_corrupt_payload): the arrays parsed shape-wise but the
        # bytes are not what the writer stored
        raise WireFormatError("payload CRC mismatch: corrupt page bytes")
    return meta, arrays[:n_k], arrays[n_k:], request
