"""Continuous-batching scheduler (reference capability: vLLM's
scheduler / Paddle FastDeploy serving loop; see PAPERS.md Gemma-on-TPU
serving comparison — continuous batching is the throughput lever).

Policy, per iteration (``schedule(now)``):

1. **Deadline sweep** — requests past their absolute deadline are
   evicted gracefully: pages are the ENGINE's to free; the scheduler
   marks them finished with reason ``"deadline"`` and surfaces partial
   output.
2. **Decode priority** — every fully-prefilled running request decodes
   one token this iteration (they form one fixed-shape batched step).
3. **Prefill chunking** — at most ONE prefill chunk per iteration (the
   head of the admitted-but-unprefilled queue) rides along, so admission
   never starves decode latency and compile shapes stay at two classes.
4. **Admission by free-page watermark** — a waiting request is admitted
   only when the available pages (free list + reclaimable cached pages)
   cover its FULL token history plus a reserved watermark (head-room
   that keeps running decodes from thrashing the preemption path on
   every page boundary). With the prefix cache on, admission first runs
   a longest-prefix match (``cache.acquire_prefix``) so the page need —
   and the committed-page accounting — counts only UNCACHED pages, and
   ``prefill_pos`` starts past the cached tokens (the engine
   chunk-prefills only the tail).

Preemption by page pressure is engine-initiated (the allocator raises
OutOfPages mid-step): ``pick_victim`` chooses the NEWEST live request
(LIFO — the vLLM recompute policy; the oldest request is never chosen,
which is what makes the no-starvation property hold), and ``preempt``
requeues it at the FRONT of the waiting queue with its generated tokens
kept, so recompute-prefill reproduces its logits bit-for-bit.
"""
from __future__ import annotations

import itertools
import math
from collections import deque
from dataclasses import dataclass, field

import numpy as np

__all__ = ["Request", "RequestState", "Scheduler", "SchedulerOutput"]

_req_ids = itertools.count()


class RequestState:
    WAITING = "waiting"        # queued, no pages held
    PREFILLING = "prefilling"  # admitted, chunked prefill in flight
    RUNNING = "running"        # decoding
    FINISHED = "finished"


@dataclass(eq=False)  # identity semantics: the prompt array would make
class Request:        # field-wise __eq__ broadcast inside `in` checks
    prompt: np.ndarray                 # int32 [S0]
    max_new_tokens: int
    arrival: float = 0.0               # engine clock (seconds)
    deadline: float | None = None      # ABSOLUTE engine-clock deadline
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int | None = None
    n: int = 1                         # parallel samples (copy-on-fork)
    logprobs: bool = False             # emit per-token logprob in events
    request_id: str | None = None      # client/router trace id (X-Request-Id)
    speculative: bool | None = None    # None=engine default, False=opt out
    prefill_only: bool = False         # disagg: stop before decode step 1
    held: bool = False                 # finished "prefilled", pages kept
    adopted: bool = False              # entered via KV page migration
    device_seed: int = 0               # counter-RNG seed (device sampling)
    cached_pages: int = 0              # prefix-cache pages at last acquire
    prefix_counted: bool = False       # hit/miss stats recorded this pass
    req_id: int = field(default_factory=lambda: next(_req_ids))
    state: str = RequestState.WAITING
    out_tokens: list = field(default_factory=list)
    prefill_pos: int = 0               # history tokens already prefilled
    finish_reason: str | None = None
    preemptions: int = 0
    # engine bookkeeping
    first_token_at: float | None = None
    last_token_at: float | None = None
    parent_id: int | None = None       # set on forked children

    @property
    def seq_id(self):
        return self.req_id

    def token_history(self):
        """prompt + sampled tokens = the sequence whose K/V the cache
        must hold. The LAST element (once out_tokens is non-empty) has
        not been fed through the model yet."""
        if not self.out_tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.out_tokens, np.int32)])

    def reset_for_recompute(self):
        """Preemption: drop cache state, keep generated tokens — the
        recompute prefill replays prompt+out_tokens so the next sampled
        token is exactly what the uninterrupted run would produce."""
        self.prefill_pos = 0
        self.state = RequestState.WAITING
        self.preemptions += 1
        self.prefix_counted = False    # the recompute prefill is a new
        self.cached_pages = 0          # cache pass; stats count it too

    def remaining_new_tokens(self):
        return self.max_new_tokens - len(self.out_tokens)


@dataclass
class SchedulerOutput:
    decode: list                       # Requests decoding this iteration
    prefill: tuple | None              # (Request, start, end) or None
    expired: list                      # deadline-evicted this iteration


class Scheduler:
    def __init__(self, cache, *, max_batch=8, prefill_chunk=32,
                 watermark_frac=0.05, spec_reserve_tokens=0):
        self.cache = cache
        self.max_batch = int(max_batch)
        self.prefill_chunk = int(prefill_chunk)
        # speculative decoding: one verify burst appends up to
        # spec_reserve_tokens+1 slots per running lane, so admission
        # charges every request's worst-case ROUND growth (not just +1)
        # — a verify burst must never preempt a running decode
        self.spec_reserve_tokens = int(spec_reserve_tokens)
        self.watermark_pages = max(
            1, math.ceil(watermark_frac * cache.allocatable_pages))
        self.waiting: deque[Request] = deque()
        self.prefill_queue: deque[Request] = deque()
        self.running: list[Request] = []
        # admission order among LIVE (page-holding) requests — the LIFO
        # preemption victim list
        self._admit_order: list[Request] = []

    # -- queue ops ---------------------------------------------------------
    def add(self, req: Request):
        self.waiting.append(req)

    def requeue_front(self, req: Request):
        """Preempted request: front of the queue, so it re-admits before
        anything younger."""
        self.waiting.appendleft(req)

    def register_fork(self, child: Request):
        """A fork created at prefill completion enters RUNNING directly
        (its pages are shared with the parent until copy-on-write)."""
        child.state = RequestState.RUNNING
        self.running.append(child)
        self._admit_order.append(child)

    def register_adopted(self, req: Request):
        """A migrated-in request (KV pages imported from a prefill
        replica) enters RUNNING directly: its history's K/V is already
        resident, so it never queues for prefill. Preemption treats it
        like any running request — recompute-prefill from the full
        token history reproduces the stream exactly."""
        req.state = RequestState.RUNNING
        req.prefill_pos = len(req.token_history())
        self.running.append(req)
        self._admit_order.append(req)

    def live_requests(self):
        return list(self.prefill_queue) + list(self.running)

    def queue_depth(self):
        return len(self.waiting)

    # -- main policy -------------------------------------------------------
    def schedule(self, now) -> SchedulerOutput:
        expired = self._sweep_deadlines(now)
        self._admit(now)
        decode = [r for r in self.running
                  if r.state == RequestState.RUNNING][:self.max_batch]
        prefill = None
        if self.prefill_queue:
            req = self.prefill_queue[0]
            self._refresh_prefix(req)
            hist = req.token_history()
            if self.cache.prefix_cache_enabled \
                    and not req.prefix_counted:
                # this request's prefill starts now: its hit/miss
                # split is final (one count per prefill pass)
                self.cache.record_prefix_stats(
                    req.prompt, len(hist), req.cached_pages)
                req.prefix_counted = True
            end = min(req.prefill_pos + self.prefill_chunk, len(hist))
            prefill = (req, req.prefill_pos, end)
        return SchedulerOutput(decode=decode, prefill=prefill,
                               expired=expired)

    def _refresh_prefix(self, req):
        """Re-run the longest-prefix match the moment ``req`` reaches
        the head of the prefill queue, while it has written no K/V of
        its own (every held page is still a pinned cache page). The
        tree may have grown since the request was pinned — in a burst
        of shared-prefix requests, the FIRST one commits the prefix
        while the rest sit queued; without this refresh they would all
        redundantly prefill it (thundering herd)."""
        if not self.cache.prefix_cache_enabled:
            return
        sid = req.seq_id
        if not self.cache.has_seq(sid) \
                or self.cache.pages_held(sid) != req.cached_pages:
            return  # already prefilling its own pages: too late
        hist = req.token_history()
        if self.cache.probe_prefix(req.prompt, len(hist)) \
                <= req.cached_pages:
            return
        self.cache.free_seq(sid)
        req.cached_pages = self.cache.acquire_prefix(
            sid, req.prompt, len(hist))
        req.prefill_pos = self.cache.seq_len(sid)

    def _sweep_deadlines(self, now):
        expired = []
        for q in (self.waiting, self.prefill_queue):
            for r in list(q):
                if r.deadline is not None and now > r.deadline:
                    q.remove(r)
                    expired.append(r)
        for r in list(self.running):
            if r.deadline is not None and now > r.deadline:
                self.running.remove(r)
                expired.append(r)
        for r in expired:
            if r in self._admit_order:
                self._admit_order.remove(r)
            r.state = RequestState.FINISHED
            r.finish_reason = "deadline"
        return expired

    def worst_case_need(self, req):
        """Uncached pages ``req`` needs to cover its history plus one
        full decode round (1 token, or 1+spec_reserve_tokens with
        speculative decoding on) — the admission unit."""
        need = self.cache.pages_for(len(req.token_history()) + 1
                                    + self.spec_reserve_tokens)
        return max(0, need - self.cache.pages_held(req.seq_id))

    def _committed_pages(self):
        """Pages PROMISED to admitted requests but not yet pulled from
        the free list (their prefill chunks haven't run) — without this,
        back-to-back admissions in one iteration would all see the same
        free count and oversubscribe the pool. With speculative decoding
        on, RUNNING lanes also reserve their next verify burst's
        worst-case growth, so an admission can never eat the pages a
        running decode is about to append into."""
        total = 0
        for r in self.prefill_queue:
            total += self.worst_case_need(r)
        if self.spec_reserve_tokens:
            for r in self.running:
                total += self.worst_case_need(r)
        return total

    def _admit(self, now):
        committed = self._committed_pages()
        while self.waiting:
            req = self.waiting[0]
            slots = len(self.prefill_queue) + len(self.running)
            if slots + req.n > self.max_batch:
                break
            hist = req.token_history()
            if self.cache.prefix_cache_enabled \
                    and not self.cache.has_seq(req.seq_id):
                # longest-prefix match (recompute path re-matches here;
                # fresh submissions were pinned at add_request)
                req.cached_pages = self.cache.acquire_prefix(
                    req.seq_id, req.prompt, len(hist))
            # count only UNCACHED pages: the matched prefix is already
            # held by the sequence (pages_held), so it neither gates
            # admission nor inflates the committed-page reservation
            need = self.worst_case_need(req)
            if self.cache.available_pages - committed \
                    < need + self.watermark_pages:
                break  # FIFO head-of-line: younger requests must wait too
            self.waiting.popleft()
            req.state = RequestState.PREFILLING
            if self.cache.has_seq(req.seq_id):
                # skip cached tokens: chunk-prefill only the tail
                req.prefill_pos = self.cache.seq_len(req.seq_id)
            self.prefill_queue.append(req)
            self._admit_order.append(req)
            committed += need

    def remove(self, req: Request):
        """Purge a request from EVERY queue (cancellation path) without
        touching its state — the engine owns the state transition and
        the page release, mirroring the deadline-eviction split."""
        if req in self.waiting:
            self.waiting.remove(req)
        if req in self.prefill_queue:
            self.prefill_queue.remove(req)
        if req in self.running:
            self.running.remove(req)
        if req in self._admit_order:
            self._admit_order.remove(req)

    # -- state transitions driven by the engine ----------------------------
    def prefill_advanced(self, req: Request, new_pos: int):
        req.prefill_pos = new_pos
        if new_pos >= len(req.token_history()):
            self.prefill_queue.remove(req)
            req.state = RequestState.RUNNING
            self.running.append(req)

    def finish(self, req: Request, reason: str):
        req.state = RequestState.FINISHED
        req.finish_reason = reason
        if req in self.running:
            self.running.remove(req)
        if req in self.prefill_queue:
            self.prefill_queue.remove(req)
        if req in self._admit_order:
            self._admit_order.remove(req)

    # -- preemption --------------------------------------------------------
    def pick_victim(self, exclude=()):
        """Newest live request not excluded (LIFO recompute policy)."""
        for r in reversed(self._admit_order):
            if r not in exclude:
                return r
        return None

    def preempt(self, victim: Request):
        """Drop the victim's pages-holding state and requeue it (front)
        for recompute. The ENGINE frees the cache sequence."""
        if victim in self.running:
            self.running.remove(victim)
        if victim in self.prefill_queue:
            self.prefill_queue.remove(victim)
        if victim in self._admit_order:
            self._admit_order.remove(victim)
        victim.reset_for_recompute()
        self.requeue_front(victim)

    def all_done(self):
        return not (self.waiting or self.prefill_queue or self.running)
