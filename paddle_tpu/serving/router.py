"""Multi-replica serving tier: cache-aware routing, mid-stream
failover, rolling drain.

``ServingRouter`` fronts N replicas (:mod:`.replica`) behind the SAME
surface a :class:`~paddle_tpu.serving.frontend.ServingFrontend`
presents (``submit``/``cancel``/``health``/``prometheus``/``drain``),
so a :class:`~paddle_tpu.serving.server.ServingServer` can serve a
whole fleet through one OpenAI-shaped endpoint — the production TPU
topology (one engine per chip/slice, PAPERS.md Gemma-on-TPU) with the
paged KV cache as per-replica state that routing exploits.

**Routing policies** (``policy=`` / ``PADDLE_TPU_SERVING_ROUTER_POLICY``):

- ``round_robin`` — rotate over routable replicas.
- ``least_loaded`` — ascending outstanding page reservations
  (``frontend.load()`` in-process, ``/healthz reserved_pages`` remote).
- ``cache_aware`` — a router-side APPROXIMATE radix tree of recently
  routed prompt prefixes (page-granularity token chains, like the
  engine's tree but host-only and lossy): a request whose prefix was
  recently routed to replica R goes back to R, where the engine-level
  prefix cache holds the pages hot. A LOAD CAP keeps a hot prefix from
  starving the fleet: when the sticky replica's load exceeds
  ``cache_load_cap`` pages AND someone else is lighter, the request
  spills to the least-loaded replica (which then also learns the
  prefix). Unmatched prompts fall back to least-loaded.

**Mid-stream failover** — the design centerpiece: PR 3 made token ``t``
of a request a pure function of ``(weights, history, seed, t)``, so a
request resubmitted on a surviving replica reproduces the identical
stream and the router can SPLICE: skip the ``k`` tokens the client
already received and forward the rest, one seamless SSE stream.
Failure signals: an ``error`` event from the in-process loop
(``RuntimeError``), :class:`~paddle_tpu.serving.replica.ReplicaFailed`
from an HTTP replica (transport break / truncated SSE), or a
health-check flip at submit time. The router assigns an explicit seed
to sampled requests that arrived without one, so the retried stream is
token-exact in BOTH greedy and sampled modes.

**Aggregated admission** — a submission is tried on every routable
replica in policy order; only when EVERY healthy replica sheds does the
router raise ``Rejected`` (429), with ``retry_after`` = max over the
replicas' own Retry-After hints.

**Rolling drain** — ``drain_replica(i)`` routes new work away, finishes
in-flight requests via the frontend's ``start_drain()``/``drain()``,
and ``readmit_replica(i, reload=fn)`` re-admits after a weight reload
(prefix caches flushed; the router forgets the replica's prefix
affinity) — the zero-downtime model-update primitive.

**Background health prober** (round 12) — ``probe_interval_s=`` /
``PADDLE_TPU_SERVING_PROBE_S``: a daemon thread periodically re-probes
DOWN replicas (bounded interval) and auto-readmits any that report
``"ok"`` again — a restarted remote ``ServingServer`` behind an
``HTTPReplica`` rejoins the fleet without a manual ``readmit_replica``
call. The recovered replica's prefix affinity is forgotten (its cache
is cold after a restart); in-process replicas whose loop FAILED report
``"failed"`` and are never auto-readmitted (they need an operator
``readmit_replica(reload=...)``). ``probe_now()`` runs one probe pass
synchronously (tests/operators).

**Circuit breaker + chaos (round 17)** — each replica carries a
:class:`~paddle_tpu.serving.chaos.CircuitBreaker`: repeated failures
(placement, failover, probe) open it and the replica drops out of
routing until the cooldown's half-open trial; the state rides
``/healthz`` (``breaker``) and ``/metrics`` (``breaker_opens_total``,
``replica_breaker_open``), and an OPEN dumps the router flight ring to
the structured log.  Router-side chaos fault points (``crash_drain``/
``crash_readmit``/``crash_shrink``, plus the migration points in the
disagg subclass) ride the ``chaos=`` config.

**Fleet-wide prefix cache (round 18)** — with ``prefix_fleet=True`` /
``PADDLE_TPU_SERVING_PREFIX_FLEET=1`` the affinity tree is promoted
from a steering hint to a KV-page TRANSFER INDEX: before a request
lands on the policy's chosen replica, the router checks whether any
OTHER replica owns the prompt's cached prefix and, on a worthwhile
delta (``PADDLE_TPU_SERVING_PREFIX_SHIP_MIN_PAGES``), ships the pages
over the pagewire path (``export_prefix``/``import_prefix`` — the same
suffix-only machinery disagg migration uses) so the target
chunk-prefills only the uncovered suffix.  The ship is strictly
best-effort: donor death, eviction races (``PrefixDrift`` bounce with
bounded re-export retries), dtype skew (guarded UP FRONT via the
``/healthz``-advertised ``cache_dtype``), torn payloads and capacity
sheds all fall back to the recompute the engine would have done
anyway.  ``PADDLE_TPU_SERVING_PREFIX_MAX_OWNERS`` adds router-driven
eviction pressure: surplus owners of a hot prefix are asked to
``drop_prefix`` their unpinned copy, so the fleet keeps ship-reachable
coverage without every replica pinning its own pages.

Env knobs: ``PADDLE_TPU_SERVING_ROUTER_POLICY``,
``PADDLE_TPU_SERVING_ROUTER_LOAD_CAP`` (pages),
``PADDLE_TPU_SERVING_PROBE_S`` (seconds; 0/unset disables the prober),
``PADDLE_TPU_SERVING_ROUTER_KILL="<replica>:<after_tokens>"`` (fault
injection: kill replica *i* once it has delivered that many tokens
through the router — the failover drill used by bench/tests; aliases
into ``ChaosConfig``), ``PADDLE_TPU_SERVING_BREAKER_N`` /
``_BREAKER_COOLDOWN_S``, ``PADDLE_TPU_SERVING_RETRY_*`` (backoff),
``PADDLE_TPU_SERVING_CHAOS`` (the unified fault schedule),
``PADDLE_TPU_SERVING_PREFIX_FLEET`` / ``_PREFIX_SHIP_MIN_PAGES`` /
``_PREFIX_MAX_OWNERS`` (the fleet prefix cache above).
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time

import numpy as np

from .chaos import ChaosConfig, ChaosInjector, CircuitBreaker
from .frontend import Rejected, Unavailable
from .metrics import (Counter, Gauge, LabeledCounter, merge_prometheus)
from .replica import ReplicaFailed
from .trace import ServingTrace

__all__ = ["RouterStream", "ServingRouter"]

_log = logging.getLogger("paddle_tpu.serving")

POLICIES = ("round_robin", "least_loaded", "cache_aware")


class _Node:
    """One page of prompt tokens in the router's affinity tree.
    ``owners`` maps replica index -> last-routed clock."""

    __slots__ = ("key", "parent", "children", "owners", "clock")

    def __init__(self, key, parent):
        self.key = key
        self.parent = parent
        self.children = {}
        self.owners = {}
        self.clock = 0


class RouterMetrics:
    """Router-level counters/gauges; replica-labelled where the fleet
    dimension matters. Families render under
    ``paddle_tpu_serving_router_*`` and merge into the fleet /metrics."""

    def __init__(self):
        self.routed_total = LabeledCounter("policy", "replica")
        self.failovers_total = LabeledCounter("replica")
        self.spliced_tokens_total = Counter()
        self.router_shed_total = Counter()
        self.readmissions_total = LabeledCounter("replica")  # prober
        # robustness layer (round 17): retry/backoff + circuit breaker
        self.retries_total = LabeledCounter("op")     # migrate/http hops
        self.breaker_opens_total = LabeledCounter("replica")
        self.chaos_injected_total = LabeledCounter("point")  # router-side
        # disaggregated tier (round 14)
        self.migrations_total = Counter()        # prefill->decode splices
        self.migrated_pages_total = Counter()    # KV pages transferred
        self.migration_fallbacks_total = Counter()  # re-prefilled instead
        # fleet prefix cache (round 18): router-driven prefix ships
        self.prefix_ships_total = Counter()      # completed ships
        self.prefix_shipped_pages_total = Counter()
        self.prefix_ship_fallbacks_total = Counter()  # recompute instead
        self.prefix_ship_skipped_total = LabeledCounter("reason")
        self.prefix_dedup_drops_total = Counter()  # pages dropped by dedup
        # hierarchical KV tiers (round 20): local host-tier restores —
        # probe order is local device -> local host tier -> remote
        # donor -> recompute, so a restored page never ships
        self.tier_restores_total = Counter()
        self.tier_restored_pages_total = Counter()
        self.prewarm_restored_pages_total = Counter()  # autoscale grow
        # versioned live deployment (round 21): placements skipped by
        # the per-stream version pin (failover mid-rollout must not
        # splice two weight versions into one stream)
        self.version_pin_skips_total = Counter()
        self.autoscale_events = LabeledCounter("direction", "role")
        self.replica_healthy = LabeledCounter("replica")   # gauge-ish
        self.replica_draining = LabeledCounter("replica")
        self.replica_breaker_open = LabeledCounter("replica")  # gauge-ish

    def export(self):
        return {name: m.export() if hasattr(m, "export") else m
                for name, m in vars(self).items()}

    def to_prometheus(self, prefix="paddle_tpu_serving_router"):
        lines = []
        for name, m in vars(self).items():
            full = f"{prefix}_{name}"
            kind = ("gauge" if name.startswith("replica_") else "counter")
            if isinstance(m, LabeledCounter):
                lines.append(f"# TYPE {full} {kind}")
                lines += m.prom_lines(full)
            elif isinstance(m, (Counter, Gauge)):
                lines += [f"# TYPE {full} {kind}", f"{full} {m.value}"]
        return "\n".join(lines) + "\n"


class RouterStream:
    """One client-facing stream spanning (possibly) several replica
    streams. Consumed from ONE client thread; failover happens inline
    when that thread observes the failure, so no extra router threads
    exist. ``events()``/``result()`` mirror ``RequestStream``."""

    def __init__(self, router, req_id, prompt, kwargs, n):
        self.router = router
        self.req_id = req_id
        self.request_id = kwargs.get("request_id")
        self.prompt = prompt
        self.kwargs = kwargs
        self.n = int(n)
        self.replica_idx = None
        self._inner = None
        self._delivered = [0] * self.n
        self._finished = [False] * self.n
        self._skip = [0] * self.n
        self.failovers = 0
        # versioned live deployment (round 21): the target weight
        # version this stream started on. Set at first successful
        # placement; every re-placement (failover resubmission) must
        # land on a replica advertising the SAME version or the
        # spliced tail would come from different weights.
        self.pinned_version = None

    @property
    def done(self):
        return all(self._finished)

    def events(self, timeout=120.0, idle_s=None):
        """Yield token/finish (and idle) events until every sample
        finished, transparently failing over and splicing when the
        serving replica dies mid-stream."""
        while not self.done:
            try:
                for ev in self._inner.events(timeout=timeout,
                                             idle_s=idle_s):
                    if ev["type"] == "idle":
                        yield ev
                        continue
                    idx = ev.get("index", 0)
                    if self._finished[idx]:
                        continue  # replayed sample already delivered
                    if ev["type"] == "token":
                        if self._skip[idx] > 0:
                            self._skip[idx] -= 1   # splice: drop replay
                            continue
                        self._delivered[idx] += 1
                        self.router._token_delivered(self.replica_idx)
                        yield ev
                    elif ev["type"] == "finish":
                        self._finished[idx] = True
                        yield ev
                break
            except TimeoutError:
                raise
            except RuntimeError as exc:  # loop death / ReplicaFailed
                self.router._failover(self, exc)
        self.router._stream_done(self)

    def result(self, timeout=120.0):
        out = [{"tokens": [], "finish_reason": None}
               for _ in range(self.n)]
        for ev in self.events(timeout=timeout):
            if ev["type"] == "token":
                out[ev["index"]]["tokens"].append(ev["token"])
            elif ev["type"] == "finish":
                out[ev["index"]]["finish_reason"] = ev["reason"]
        return out


class ServingRouter:
    stream_cls = RouterStream  # DisaggRouter swaps in DisaggStream

    def __init__(self, replicas, *, policy=None, page_size=16,
                 cache_load_cap=None, max_tree_pages=8,
                 max_tree_nodes=4096, seed=None,
                 probe_interval_s=None, chaos=None,
                 breaker_clock=None, prefix_fleet=None,
                 prefix_ship_min_pages=None, prefix_max_owners=None,
                 journal=None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        policy = policy or os.environ.get(
            "PADDLE_TPU_SERVING_ROUTER_POLICY") or "cache_aware"
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}; one of "
                             f"{POLICIES}")
        self.replicas = list(replicas)
        # advertised routing roles (disagg tier reads these; the base
        # policies ignore them — every replica is routable)
        self.roles = [getattr(r, "role", "mixed") for r in self.replicas]
        self.policy = policy
        self.page_size = int(page_size)
        cap = os.environ.get("PADDLE_TPU_SERVING_ROUTER_LOAD_CAP")
        self.cache_load_cap = float(
            cap if cap is not None else
            (cache_load_cap if cache_load_cap is not None else 32))
        self.max_tree_pages = int(max_tree_pages)
        self.max_tree_nodes = int(max_tree_nodes)
        # fleet-wide prefix cache (round 18): on a prefix miss at the
        # routed replica but a hit elsewhere in the fleet, ship the
        # cached pages over the pagewire path instead of recomputing
        # the prefill; the affinity tree doubles as the transfer index
        if prefix_fleet is None:
            prefix_fleet = os.environ.get(
                "PADDLE_TPU_SERVING_PREFIX_FLEET") == "1"
        self.prefix_fleet = bool(prefix_fleet)
        if prefix_ship_min_pages is None:
            prefix_ship_min_pages = int(os.environ.get(
                "PADDLE_TPU_SERVING_PREFIX_SHIP_MIN_PAGES", "1") or 1)
        self.prefix_ship_min_pages = max(1, int(prefix_ship_min_pages))
        if prefix_max_owners is None:
            prefix_max_owners = int(os.environ.get(
                "PADDLE_TPU_SERVING_PREFIX_MAX_OWNERS", "0") or 0)
        self.prefix_max_owners = int(prefix_max_owners)
        # PrefixDrift re-export attempts per ship (shares the migration
        # retry knob: both are the same bounce-and-re-export contract)
        self.prefix_ship_retries = max(1, int(os.environ.get(
            "PADDLE_TPU_SERVING_MIGRATE_RETRIES", "2") or 2))
        self.metrics = RouterMetrics()
        # router-side spans (routed/failover_splice/migration) keyed by
        # the router stream id; X-Request-Id is the cross-replica
        # stitch key /debug/trace merges on (round 16)
        self.trace = ServingTrace()
        self._lock = threading.Lock()
        self._rr = 0
        self._ids = itertools.count()
        self._root = _Node(None, None)
        self._nodes = 0
        self._clock = 0
        self._down: set[int] = set()
        self._draining: set[int] = set()
        self._retired: set[int] = set()   # autoscaler scale-downs
        # in-flight prefix ships keyed by (target, prefix bytes): a
        # shared-prefix burst must not dogpile N identical transfers
        # onto one cold replica (the engine-side thundering-herd
        # refresh already makes the losers hit after the winner lands)
        self._ships_inflight: set[tuple] = set()
        self._streams: dict[int, RouterStream] = {}
        self._seed_rng = np.random.default_rng(seed)
        self._started = False
        # crash-rebuildable state (round 19): every routing decision
        # input is either journaled (affinity/ownership, breaker opens,
        # stream begin/end) or re-derivable from one /healthz sweep
        # (liveness, loads, reservations) — a cold router replays the
        # journal, sweeps once, and converges (see fleet.RouterJournal)
        self.journal = journal
        self._crashed = False     # halt(): this router object is dead
        self._orphans: dict = {}  # replay: begun-but-unfinished streams
        # unified chaos layer (round 17): router-side fault points
        # (replica crash during drain/readmit/shrink, migration faults
        # in the disagg subclass) + the retry/backoff knobs; the legacy
        # ROUTER_KILL drill aliases into the same config
        if isinstance(chaos, ChaosInjector):
            self.chaos = chaos
        else:
            assert chaos is None or isinstance(chaos, ChaosConfig)
            self.chaos = ChaosInjector(chaos, name="router")
        self.chaos.bind(self.trace)
        # per-replica circuit breakers: repeated failures open the
        # breaker (replica excluded from routing), the cooldown admits
        # a half-open trial, a success closes it again.  breaker_clock
        # injects the time source for deterministic tests.
        self._breaker_clock = breaker_clock
        self._breakers = [self._new_breaker()
                          for _ in range(len(self.replicas))]
        kill = self.chaos.cfg.router_kill
        self._kill = [kill[0], kill[1], False] if kill else None
        self._replica_tokens = [0] * len(self.replicas)
        # background health prober (round 12): bounded re-probe of DOWN
        # replicas, auto-readmit on recovery
        if probe_interval_s is None:
            probe_interval_s = float(
                os.environ.get("PADDLE_TPU_SERVING_PROBE_S", "0") or 0)
        self.probe_interval_s = max(0.0, float(probe_interval_s))
        self._probe_stop = threading.Event()
        self._probe_thread = None

    # -- crash-rebuildable state (round 19, fleet control plane) -----------
    def _journal(self, **rec):
        """Best-effort append to the routing journal (fleet.py): the
        journal is a recovery accelerant, never a serving dependency —
        a full disk or torn writer must not fail a request."""
        j = self.journal
        if j is None:
            return
        try:
            j.append(rec)
        except Exception:  # pragma: no cover - journal is best-effort
            pass

    def _journal_prefix(self, prompt):
        """The journaled form of a prompt's affinity chain: exactly the
        tokens the tree stores (page-aligned, depth-capped)."""
        ps = self.page_size
        pages = min(len(prompt) // ps, self.max_tree_pages)
        return [int(t) for t in prompt[:pages * ps]]

    def sweep_health(self):
        """ONE full /healthz pass over every non-retired replica — the
        live half of recovery (the journal is the other half): liveness
        and breaker-worthiness come from here, not from stale journal
        hints.  A replica answering ``ok`` becomes routable; one that
        is unreachable/failed goes down.  Returns ``{idx: health}``."""
        out = {}
        for i in range(len(self.replicas)):
            if i in self._retired:
                continue
            try:
                h = dict(self.replicas[i].health())
            except Exception as e:
                h = {"status": "unreachable", "error": repr(e)}
            out[i] = h
            status = h.get("status")
            with self._lock:
                if status == "ok":
                    self._down.discard(i)
                elif status in ("failed", "unreachable"):
                    self._down.add(i)
        return out

    def adopt_journal(self, journal):
        """Rebuild the journaled half of the routing state from
        ``journal`` and continue appending to it.  Replays placements
        (affinity/ownership tree, original order = original clocks),
        ownership drops, breaker opens (restored open with a fresh
        cooldown), down/up hints, and stream begin/end pairs — begun-
        but-unfinished streams become ``_orphans`` for
        :meth:`release_orphans`.  Call :meth:`sweep_health` after: the
        sweep is the truth for liveness, the journal for affinity."""
        self.journal = None  # replay must not re-journal itself
        n = 0
        for rec in journal.replay():
            self._apply_journal_record(rec)
            n += 1
        self.journal = journal
        return n

    def _apply_journal_record(self, rec):
        ev = rec.get("ev")
        r = rec.get("r")
        if r is not None and (not isinstance(r, int)
                              or r >= len(self.replicas)):
            return  # journal from a larger fleet: ignore unknown slots
        if ev == "place":
            self._record(np.asarray(rec.get("p", ()), np.int32), r)
        elif ev == "drop":
            with self._lock:
                self._forget_prefix_owner(
                    np.asarray(rec.get("p", ()), np.int32), r)
        elif ev == "begin":
            self._orphans[rec.get("rid")] = (r, rec.get("inner"),
                                             rec.get("req"))
        elif ev == "end":
            self._orphans.pop(rec.get("rid"), None)
        elif ev == "down":
            with self._lock:
                self._down.add(r)
        elif ev == "up":
            with self._lock:
                self._down.discard(r)
        elif ev == "breaker_open":
            self._breakers[r].force_open()

    def release_orphans(self):
        """Best-effort release of the dead router's in-flight work: a
        begun-but-unfinished journal entry means SOME replica may still
        hold that stream's request (running lanes, held prefill pages)
        with nobody left to consume it.  In-process replicas cancel it
        outright (pages freed now); remote ones saw the dead router's
        sockets close (disconnect-cancel) and anything held falls to
        the deadline-expiry sweep — the existing backstop.  Returns the
        number of orphans cancelled."""
        released = 0
        orphans, self._orphans = self._orphans, {}
        for rid, (idx, inner, _req) in orphans.items():
            if inner is None or idx is None or idx in self._down:
                continue
            try:
                if self.replicas[idx].cancel_request(inner):
                    released += 1
            except Exception:
                continue
        if released:
            _log.info(json.dumps({"event": "router_orphans_released",
                                  "count": released}))
        return released

    @classmethod
    def recover(cls, replicas, journal, **kw):
        """Build a router whose state converges to a never-crashed
        router's view: construct cold, replay the journal (affinity,
        ownership, breaker opens, orphaned streams), then ONE health
        sweep (liveness + loads are live state, owned by the fleet).
        The recovered router keeps journaling to the same file."""
        router = cls(replicas, **kw)
        router.adopt_journal(journal)
        router.sweep_health()
        router.release_orphans()
        return router

    def halt(self):
        """Mark THIS router object dead (supervisor takeover): stop the
        prober, refuse new submissions.  The replicas are untouched —
        they belong to the fleet, not to this incarnation."""
        self._crashed = True
        self._probe_stop.set()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if not self._started:
            for r in self.replicas:
                r.start()
            self._started = True
            if self.probe_interval_s > 0 \
                    and self._probe_thread is None:
                self._probe_thread = threading.Thread(
                    target=self._probe_loop,
                    name="serving-router-prober", daemon=True)
                self._probe_thread.start()
        return self

    @property
    def state(self):
        """Front-end-compatible aggregate state: "ok" while ANY replica
        is routable, else "draining" if any is draining, else
        "failed"."""
        if self._routable():
            return "ok"
        return "draining" if self._draining else "failed"

    def drain(self, timeout=120.0):
        """Fleet drain (ServingServer.close path): drain every replica
        in parallel-ish sequence; True when all drained."""
        ok = True
        for i in range(len(self.replicas)):
            if i in self._down or i in self._retired:
                continue
            self._draining.add(i)
            ok = self.replicas[i].drain(timeout) and ok
        return ok

    def close(self, timeout=120.0):
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
        ok = self.drain(timeout)
        for r in self.replicas:
            r.close()
        return ok

    # -- circuit breaker (round 17) ----------------------------------------
    def _new_breaker(self):
        cfg = self.chaos.cfg
        return CircuitBreaker(cfg.breaker_n, cfg.breaker_cooldown_s,
                              clock=self._breaker_clock)

    def breaker_state(self, i):
        return self._breakers[i].state

    def _record_replica_failure(self, idx, cause):
        """Feed the replica's circuit breaker; on the closed→open (or
        half-open→open) transition, count it, and dump the router's
        flight ring to the structured log — the breaker opening means
        the fleet lost capacity to a FLAKY (not hard-dead) replica,
        which is exactly the post-mortem the ring exists for."""
        if idx is None or idx >= len(self._breakers):
            return
        if not self._breakers[idx].record_failure():
            return
        self._journal(ev="breaker_open", r=idx)
        self.metrics.breaker_opens_total.inc(replica=idx)
        _log.warning(json.dumps({"event": "router_breaker_open",
                                 "replica": idx, "cause": str(cause)}))
        if self.trace.enabled:
            self.trace.flight.record("breaker_open", replica=idx,
                                     cause=str(cause))
            _log.error(json.dumps({
                "event": "flight_recorder_dump",
                "cause": "breaker_open", "replica": idx,
                "recorded": self.trace.flight.recorded,
                "events": self.trace.flight.dump()}))

    # -- background health prober (round 12) -------------------------------
    def _probe_loop(self):
        while not self._probe_stop.wait(self.probe_interval_s):
            try:
                self.probe_now()
            except Exception:  # pragma: no cover - probe must not die
                pass

    def probe_now(self):
        """One synchronous probe pass over the DOWN replicas: any that
        reports ``"ok"`` again is auto-readmitted (its prefix affinity
        forgotten — a restarted server's cache is cold). Replicas whose
        in-process loop FAILED report "failed" and stay down (they need
        ``readmit_replica`` with a reload). Returns the list of replica
        indexes readmitted."""
        with self._lock:
            down = [i for i in self._down if i not in self._draining
                    and i not in self._retired]
        readmitted = []
        for i in down:
            # the breaker feeds the prober: an open breaker's cooldown
            # gates the re-probe (no point hammering a flaky replica),
            # and a failed probe re-opens a half-open breaker
            if not self._breakers[i].allow():
                continue
            try:
                status = self.replicas[i].health().get("status")
            except Exception as e:
                self._record_replica_failure(i, e)
                continue
            if status != "ok":
                continue
            self._journal(ev="up", r=i)
            with self._lock:
                self._down.discard(i)
                self._forget_owner(self._root, i)
            self._breakers[i].record_success()
            self.metrics.readmissions_total.inc(replica=i)
            readmitted.append(i)
            _log.info(json.dumps({"event": "router_replica_readmitted",
                                  "replica": i, "by": "health_prober"}))
        return readmitted

    # -- client API (ServingFrontend-shaped) -------------------------------
    def submit(self, prompt, max_new_tokens=16, **kw):
        """Route a request; returns a RouterStream. Raises Rejected
        only when EVERY routable replica sheds (aggregated 429,
        ``retry_after`` = max over replica hints), Unavailable when no
        replica is routable at all."""
        if self._crashed:
            raise Unavailable("router crashed (superseded by takeover)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if kw.get("do_sample") and kw.get("seed") is None:
            # failover determinism needs an explicit seed: token t is
            # pure in (weights, history, seed, t), so the retried
            # stream is exact only if the seed rides along
            kw["seed"] = int(self._seed_rng.integers(1, 2 ** 31 - 1))
        kw["max_new_tokens"] = int(max_new_tokens)
        stream = self.stream_cls(self, next(self._ids), prompt, kw,
                                 n=int(kw.get("n", 1)))
        if self.trace.enabled:
            with self._lock:
                self.trace.begin(stream.req_id, kw.get("request_id"))
        self._place(stream, exclude=())
        with self._lock:
            self._streams[stream.req_id] = stream
        if self._crashed:
            # raced a supervisor takeover: the teardown snapshot may
            # have missed this stream, so nothing would ever kick its
            # consumer off the dead router — refuse it here (the
            # supervisor resubmits on the standby; the placed request
            # falls to the new router's orphan release)
            raise Unavailable("router crashed (superseded by takeover)")
        return stream

    def cancel(self, req_id):
        """Cancel a routed request on whichever replica currently
        serves it."""
        with self._lock:
            stream = self._streams.pop(req_id, None)
        if stream is None or stream._inner is None:
            return False
        return bool(self.replicas[stream.replica_idx]
                    .cancel_stream(stream._inner))

    def health(self):
        per = []
        for i, r in enumerate(self.replicas):
            if i in self._retired:
                per.append({"status": "retired",
                            "role": self.roles[i]})
            elif i in self._down:
                per.append({"status": "down", "role": self.roles[i]})
            else:
                try:
                    h = dict(r.health())
                except Exception as e:  # remote probe blew up
                    h = {"status": "unreachable", "error": repr(e)}
                if i in self._draining:
                    h["status"] = "draining"
                h.setdefault("role", self.roles[i])
                per.append(h)
            # breaker state is advertised for EVERY slot: routers and
            # operators see flaky-but-alive replicas before they 5xx
            per[-1]["breaker"] = self._breakers[i].state
        agg = self.state
        return {"status": agg,
                "policy": self.policy,
                "replicas": per,
                "waiting": sum(h.get("waiting", 0) for h in per),
                "live": sum(h.get("live", 0) for h in per),
                "free_pages": sum(h.get("free_pages", 0) for h in per),
                "requests_finished": sum(h.get("requests_finished", 0)
                                         for h in per)}

    def prometheus(self):
        """Merged fleet exposition: every replica's families tagged
        ``replica="<i>"``, plus the router's own counters."""
        for i in range(len(self.replicas)):
            healthy = int(i not in self._down and i not in self._draining
                          and self._replica_state(i) == "ok")
            self.metrics.replica_healthy._values[(str(i),)] = healthy
            self.metrics.replica_draining._values[(str(i),)] = int(
                i in self._draining)
            self.metrics.replica_breaker_open._values[(str(i),)] = int(
                self._breakers[i].state == "open")
            # HTTP replicas count their own transport retries; surface
            # them in the fleet exposition next to the migrate retries
            hops = getattr(self.replicas[i], "retry_count", 0)
            if hops:
                self.metrics.retries_total._values[
                    (f"http:{i}",)] = hops
        for point, n in self.chaos.counts.items():
            self.metrics.chaos_injected_total._values[(point,)] = n
        parts = [(None, self.metrics.to_prometheus())]
        for i, r in enumerate(self.replicas):
            if i in self._down or i in self._retired:
                continue
            try:
                parts.append((str(i), r.prometheus()))
            except Exception:  # pragma: no cover - remote flake
                pass
        return merge_prometheus(parts)

    # -- observability (round 16): fleet-merged trace + flight -------------
    def debug_trace(self, request_id=None, req_id=None):
        """Cross-replica trace merge, /metrics-style: every replica's
        timelines for ``request_id`` (the X-Request-Id stitch key —
        engine ``req_id`` values are replica-local, so ``req_id`` only
        filters the router's own spans) tagged with their replica
        index, plus the router's own routed/failover/migration spans,
        and ONE ``stitched`` span list ordered on the shared wall
        clock."""
        timelines = []
        for i in range(len(self.replicas)):
            if i in self._retired:
                continue
            try:
                # DOWN in-process replicas still answer (their trace
                # store is the post-mortem); unreachable HTTP ones skip
                d = self.replicas[i].debug_trace(request_id=request_id)
            except Exception:
                continue
            for tl in d.get("timelines", []):
                timelines.append(dict(tl, replica=i))
        own = self.trace.timelines(request_id=request_id,
                                   req_id=req_id)
        timelines.extend(dict(tl, replica="router") for tl in own)
        stitched = []
        for tl in timelines:
            for s in tl["spans"]:
                stitched.append(dict(s, req_id=tl["req_id"],
                                     replica=tl["replica"]))
        stitched.sort(key=lambda s: s.get("t0_unix", 0.0))
        return {"request_id": request_id, "timelines": timelines,
                "stitched": stitched}

    def debug_flight(self):
        """Every replica's flight ring plus the router's own, keyed by
        replica index (the /metrics merge shape)."""
        out = {"router": {"events": self.trace.flight.dump(),
                          "recorded": self.trace.flight.recorded,
                          "cap": self.trace.flight.cap},
               "replicas": {}}
        for i in range(len(self.replicas)):
            if i in self._retired:
                continue
            try:
                out["replicas"][str(i)] = self.replicas[i].debug_flight()
            except Exception:
                continue
        return out

    # -- rolling drain -----------------------------------------------------
    def drain_replica(self, i, timeout=120.0):
        """Route new work away from replica ``i`` and finish its
        in-flight requests (zero lost work). Returns True when fully
        drained in time.  A replica that CRASHES mid-drain (the chaos
        ``crash_drain`` point, or any drain exception) reports False —
        its live pages were released on the failure path and its open
        streams failed over; the drain never deadlocks on it."""
        with self._lock:
            self._draining.add(i)
        if self.chaos.fire("crash_drain", replica=i):
            self.kill_replica(i, ReplicaFailed(
                "chaos: replica crashed during drain"))
        try:
            ok = self.replicas[i].drain(timeout)
        except Exception as e:  # a crashed replica must not stall drain
            self._record_replica_failure(i, e)
            ok = False
        _log.info(json.dumps({"event": "router_drain_replica",
                              "replica": i, "drained": ok}))
        return ok

    def readmit_replica(self, i, reload=None):
        """Re-admit a drained replica, optionally applying a weight
        reload first (``reload(model)`` for in-process replicas). The
        router forgets the replica's prefix affinity — its engine cache
        was flushed with the old weights."""
        rep = self.replicas[i]
        if hasattr(rep, "reload"):
            rep.reload(reload)
        else:
            rep.resume()
        if self.chaos.fire("crash_readmit", replica=i):
            # crash between resume and routability: the slot stays
            # down, its (empty — just resumed) state is released
            self.kill_replica(i, ReplicaFailed(
                "chaos: replica crashed during readmit"))
            return
        self._journal(ev="up", r=i)
        with self._lock:
            self._draining.discard(i)
            self._down.discard(i)
            self._forget_owner(self._root, i)
        self._breakers[i].record_success()  # operator readmit: clean slate
        _log.info(json.dumps({"event": "router_readmit_replica",
                              "replica": i}))

    # -- fleet mutation (autoscaler, round 14) -----------------------------
    def add_replica(self, replica, role=None):
        """Grow the fleet: append a replica (started if the router is
        live) and make it routable immediately. Returns its index."""
        with self._lock:
            self.replicas.append(replica)
            self.roles.append(role or getattr(replica, "role", "mixed"))
            self._replica_tokens.append(0)
            self._breakers.append(self._new_breaker())
            i = len(self.replicas) - 1
        if self._started:
            replica.start()
        _log.info(json.dumps({"event": "router_add_replica",
                              "replica": i, "role": self.roles[i]}))
        return i

    def retire_replica(self, i, timeout=120.0):
        """Shrink the fleet: route new work away, finish replica
        ``i``'s in-flight requests through the rolling-drain path
        (zero lost requests), then close it and mark it retired —
        indexes stay stable, the slot just stops being routable.
        Returns True when the drain completed in time."""
        with self._lock:
            if i in self._retired:
                return True
            self._draining.add(i)
        if self.chaos.fire("crash_shrink", replica=i):
            self.kill_replica(i, ReplicaFailed(
                "chaos: replica crashed during autoscaler shrink"))
        try:
            ok = self.replicas[i].drain(timeout)
        except Exception as e:  # crashed mid-shrink: retire anyway —
            self._record_replica_failure(i, e)  # pages were released
            ok = False                          # on the failure path
        try:
            self.replicas[i].close(timeout)
        except Exception:  # pragma: no cover - best-effort teardown
            pass
        with self._lock:
            self._retired.add(i)
            self._draining.discard(i)
            self._forget_owner(self._root, i)
        _log.info(json.dumps({"event": "router_retire_replica",
                              "replica": i, "drained": ok}))
        return ok

    def kill_replica(self, i, exc=None):
        """Fault hook (tests/bench): hard-kill an in-process replica;
        its open streams fail over."""
        self._journal(ev="down", r=i)
        with self._lock:
            self._down.add(i)
        if self.trace.enabled:
            self.trace.flight.record("kill_replica", replica=i,
                                     cause=repr(exc) if exc else None)
        self.replicas[i].fail(exc)

    # -- routing internals -------------------------------------------------
    def _replica_state(self, i):
        try:
            return self.replicas[i].state
        except Exception:
            return "unreachable"

    def _routable(self, exclude=()):
        out = []
        for i in range(len(self.replicas)):
            if i in self._down or i in self._draining \
                    or i in self._retired or i in exclude:
                continue
            # open breaker: the replica is alive but flaky — keep
            # traffic away until the cooldown admits a half-open trial
            if not self._breakers[i].allow():
                continue
            out.append(i)
        return out

    def _loads(self, idxs):
        loads = {}
        for i in idxs:
            try:
                loads[i] = self.replicas[i].load()
            except Exception:
                loads[i] = float("inf")
        return loads

    def _order(self, prompt, exclude=()):
        """Replica indexes to try, best first, per the active policy."""
        idxs = self._routable(exclude)
        if not idxs:
            return []
        if self.policy == "round_robin":
            with self._lock:
                start = self._rr
                self._rr += 1
            return [idxs[(start + j) % len(idxs)]
                    for j in range(len(idxs))]
        loads = self._loads(idxs)
        by_load = sorted(idxs, key=lambda i: (loads[i], i))
        if self.policy == "least_loaded":
            return by_load
        # cache_aware: deepest recent owner of the prompt's page chain
        with self._lock:
            preferred = self._match(prompt, set(idxs))
        if preferred is None:
            return by_load
        if loads.get(preferred, 0) > self.cache_load_cap \
                and by_load[0] != preferred \
                and loads[by_load[0]] < loads[preferred]:
            # hot-prefix load cap: spill to the lightest replica, which
            # then learns the prefix too (affinity widens under load)
            return by_load
        return [preferred] + [i for i in by_load if i != preferred]

    def _match(self, prompt, alive):
        """Walk the affinity tree; return the deepest-match replica
        (ties: most recently routed). Call under the lock."""
        ps = self.page_size
        node = self._root
        best = None
        pages = min(len(prompt) // ps, self.max_tree_pages)
        for i in range(pages):
            key = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            node = node.children.get(key)
            if node is None:
                break
            owners = [(clk, r) for r, clk in node.owners.items()
                      if r in alive]
            if owners:
                best = max(owners)[1]
        return best

    def _record(self, prompt, replica_idx):
        """Teach the affinity tree that this prompt's prefix now lives
        on ``replica_idx``. Bounded: at most ``max_tree_pages`` nodes
        per prompt, LRU leaf eviction beyond ``max_tree_nodes``."""
        ps = self.page_size
        pages = min(len(prompt) // ps, self.max_tree_pages)
        if pages == 0:
            return
        self._journal(ev="place", r=replica_idx,
                      p=self._journal_prefix(prompt))
        with self._lock:
            self._clock += 1
            node = self._root
            for i in range(pages):
                key = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
                child = node.children.get(key)
                if child is None:
                    child = _Node(key, node)
                    node.children[key] = child
                    self._nodes += 1
                child.owners[replica_idx] = self._clock
                child.clock = self._clock
                node = child
            while self._nodes > self.max_tree_nodes:
                if not self._evict_lru_leaf():
                    break

    def _evict_lru_leaf(self):
        victim = None

        def walk(node):
            nonlocal victim
            for child in node.children.values():
                if child.children:
                    walk(child)
                elif victim is None or child.clock < victim.clock:
                    victim = child

        walk(self._root)
        if victim is None:
            return False
        del victim.parent.children[victim.key]
        self._nodes -= 1
        return True

    def _forget_owner(self, node, idx):
        node.owners.pop(idx, None)
        for child in node.children.values():
            self._forget_owner(child, idx)

    # -- fleet prefix transfer (round 18) ----------------------------------
    def _owner_depths(self, prompt, alive):
        """Walk the affinity tree: replica index -> deepest page of
        ``prompt``'s chain it was recorded owning. Call under the
        lock."""
        ps = self.page_size
        node = self._root
        depths = {}
        pages = min(len(prompt) // ps, self.max_tree_pages)
        for i in range(pages):
            key = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            node = node.children.get(key)
            if node is None:
                break
            for r in node.owners:
                if r in alive:
                    depths[r] = i + 1
        return depths

    def _forget_prefix_owner(self, prompt, idx):
        """Drop ``idx``'s recorded ownership along ``prompt``'s chain
        (a dedup drop made the record stale). Call under the lock."""
        ps = self.page_size
        node = self._root
        pages = min(len(prompt) // ps, self.max_tree_pages)
        for i in range(pages):
            key = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            node = node.children.get(key)
            if node is None:
                break
            node.owners.pop(idx, None)

    def _replica_cache_dtype(self, i):
        """The replica's advertised KV dtype, or None when unknown —
        the up-front dtype-skew guard (an int8 payload shipped into a
        bf16 tree would only bounce on GeometryMismatch later)."""
        fn = getattr(self.replicas[i], "cache_dtype", None)
        if fn is None:
            return None
        try:
            return fn() if callable(fn) else fn
        except Exception:
            return None

    def _replica_tp_degree(self, i):
        """The replica's advertised tensor-parallel degree, or None
        when unknown — the up-front tp-skew guard (a per-shard payload
        shipped across degrees would only bounce on GeometryMismatch
        later)."""
        fn = getattr(self.replicas[i], "tp_degree", None)
        if fn is None:
            return None
        try:
            return fn() if callable(fn) else fn
        except Exception:
            return None

    def _replica_weight_version(self, i, which="target"):
        """The replica's CURRENT target weight version, or None when
        unknown.  Unlike ``cache_dtype`` (immutable for an engine's
        lifetime, cached forever by HTTPReplica) the version changes
        mid-life under a rolling deploy, so this must be a FRESH read
        every call — replica.weight_version() guarantees that."""
        fn = getattr(self.replicas[i], "weight_version", None)
        if fn is None:
            return None
        try:
            v = fn(which) if callable(fn) else fn
            return None if v is None else int(v)
        except Exception:
            return None

    def _maybe_ship_prefix(self, stream, target_idx):
        """The fleet prefix ship: if the replica we are about to place
        ``stream`` on misses its prompt prefix but another replica
        holds it cached, move the pages over the pagewire path so the
        target chunk-prefills only the uncovered suffix.  STRICTLY
        best-effort — every failure mode (donor gone, eviction race,
        dtype skew, torn payload, capacity shed) degrades to the plain
        recompute the engine would have done anyway, never to a failed
        request."""
        if not self.prefix_fleet:
            return
        try:
            self._ship_prefix(stream, target_idx)
        except Exception as e:  # the ship must never sink the request
            self.metrics.prefix_ship_fallbacks_total.inc()
            _log.warning(json.dumps({
                "event": "router_prefix_ship_failed",
                "to": target_idx, "request_id": stream.request_id,
                "cause": repr(e)}))

    def _ship_prefix(self, stream, target_idx):
        prompt = stream.prompt
        total_pages = len(prompt) // self.page_size
        if total_pages < self.prefix_ship_min_pages:
            return
        key = (target_idx,
               prompt[:self.page_size * self.max_tree_pages].tobytes())
        with self._lock:
            alive = set(self._routable()) - {target_idx}
            owners = self._owner_depths(prompt, alive)
            if owners and key in self._ships_inflight:
                # a concurrent submit is already moving this prefix to
                # this replica; the loser recomputes (or re-matches at
                # the prefill head once the winner's pages commit)
                self.metrics.prefix_ship_skipped_total.inc(
                    reason="inflight")
                return
            self._ships_inflight.add(key)
        try:
            self._ship_prefix_inner(stream, target_idx, prompt,
                                    total_pages, owners)
        finally:
            with self._lock:
                self._ships_inflight.discard(key)

    def _ship_prefix_inner(self, stream, target_idx, prompt,
                           total_pages, owners):
        target = self.replicas[target_idx]
        try:
            have = target.probe_pages(prompt)
        except Exception:
            return
        if have >= total_pages:
            return  # already fully resident: a local hit, not a miss
        # hierarchical KV tier (round 20): the target's OWN host tier
        # sits between the device miss and a remote donor — restoring
        # locally moves no bytes over the wire.  Best-effort: 0 on a
        # tierless replica or any failure, and the donor loop (or the
        # plain recompute) still covers whatever is missing.
        restored = self._tier_restore(target, prompt)
        if restored:
            self.metrics.tier_restores_total.inc()
            self.metrics.tier_restored_pages_total.inc(restored)
            self._record(prompt, target_idx)  # target owns pages now
            if self.trace.enabled:
                self.trace.flight.record(
                    "tier_restore", replica=target_idx,
                    pages=int(restored), request_id=stream.request_id)
            _log.info(json.dumps({
                "event": "router_tier_restore", "replica": target_idx,
                "pages": int(restored),
                "request_id": stream.request_id}))
            have += restored
            if have >= total_pages:
                return
        if not owners:
            return
        tgt_dtype = self._replica_cache_dtype(target_idx)
        tgt_ver = self._replica_weight_version(target_idx)
        tgt_tp = self._replica_tp_degree(target_idx)
        # deepest recorded owner first; recorded depth is approximate,
        # the donor's probe_pages is the truth
        for donor_idx in sorted(owners, key=owners.get, reverse=True):
            if self.chaos.fire("prefix_export_gone",
                               donor=donor_idx, to_replica=target_idx):
                # chaos: the donor vanished mid-ship — try the next one
                continue
            donor_dtype = self._replica_cache_dtype(donor_idx)
            if tgt_dtype is not None and donor_dtype is not None \
                    and donor_dtype != tgt_dtype:
                # up-front dtype-skew guard: the payload could only
                # bounce on GeometryMismatch at import — skip the
                # doomed transfer entirely
                self.metrics.prefix_ship_skipped_total.inc(
                    reason="dtype_skew")
                continue
            donor_tp = self._replica_tp_degree(donor_idx)
            if tgt_tp is not None and donor_tp is not None \
                    and donor_tp != tgt_tp:
                # up-front tp-skew guard (round 23): per-shard payload
                # lists only splice between equal shard degrees — a
                # skewed ship could only bounce on GeometryMismatch
                self.metrics.prefix_ship_skipped_total.inc(
                    reason="tp_skew")
                continue
            donor_ver = self._replica_weight_version(donor_idx)
            if tgt_ver is not None and donor_ver is not None \
                    and donor_ver != tgt_ver:
                # version-skew guard (round 21): K/V computed under
                # different target weights is stale numerics — shipping
                # it would splice two versions into one prefill
                self.metrics.prefix_ship_skipped_total.inc(
                    reason="version_skew")
                continue
            donor = self.replicas[donor_idx]
            try:
                donor_have = donor.probe_pages(prompt)
            except Exception:
                continue
            if donor_have - have < self.prefix_ship_min_pages:
                continue
            if self._ship_from(stream, donor_idx, target_idx, prompt,
                               have):
                return

    def _tier_restore(self, replica, prompt):
        """Best-effort host-tier restore on the placement target: 0 on
        a replica without the surface (older remote), without a tier,
        or on any failure — the tier contract says a miss costs only
        the recompute the engine was already going to do."""
        fn = getattr(replica, "restore_prefix", None)
        if fn is None:
            return 0
        try:
            return int(fn(prompt))
        except Exception:
            return 0

    def _ship_from(self, stream, donor_idx, target_idx, prompt, skip):
        """One donor→target transfer with bounded PrefixDrift
        re-export retries.  True when pages landed (or the ship became
        redundant); False to try the next donor."""
        from .kv_cache import GeometryMismatch, PrefixDrift
        from .pagewire import WireFormatError
        donor = self.replicas[donor_idx]
        target = self.replicas[target_idx]
        t0 = time.perf_counter()
        drift_left = self.prefix_ship_retries
        while True:
            try:
                meta, k, v = donor.export_prefix(prompt, skip)
            except PrefixDrift:
                return False  # donor's chain shrank below the probe
            except WireFormatError:
                # torn wire payload: recompute covers it — re-pulling
                # from the same donor would re-read the same stream
                self.metrics.prefix_ship_fallbacks_total.inc()
                return True
            except Exception:
                return False  # donor sick: next donor
            if self.chaos.fire("prefix_import_drift",
                               to_replica=target_idx):
                # chaos models the eviction race for REAL: the
                # target's matched chain is evicted between probe and
                # import, so a nonzero skip bounces with PrefixDrift
                try:
                    target.drop_prefix(prompt)
                except Exception:
                    pass
            try:
                imported = target.import_prefix(meta, k, v)
            except PrefixDrift as e:
                drift_left -= 1
                if drift_left <= 0:
                    self.metrics.prefix_ship_fallbacks_total.inc()
                    return True  # give up: recompute fallback
                skip = e.cached_pages  # re-export the right suffix
                continue
            except GeometryMismatch:
                # dtype/geometry skew the advertisement did not catch
                # (stale or unreadable /healthz): bounced up front at
                # deserialization — the recompute fallback covers it
                self.metrics.prefix_ship_skipped_total.inc(
                    reason="geometry_bounce")
                return True
            except Exception:
                self.metrics.prefix_ship_fallbacks_total.inc()
                return True  # target can't host it: recompute
            if not imported:
                # drift retries converged on "target already holds the
                # whole chain" (a concurrent ship or local prefill
                # landed first) — an owner, but not a ship
                self.metrics.prefix_ship_skipped_total.inc(
                    reason="redundant")
                self._record(prompt, target_idx)
                return True
            self.metrics.prefix_ships_total.inc()
            self.metrics.prefix_shipped_pages_total.inc(imported)
            self._record(prompt, target_idx)  # target is an owner now
            if self.trace.enabled:
                self.trace.span(stream.req_id, "prefix_ship", t0,
                                time.perf_counter() - t0,
                                pages=int(imported),
                                skip_pages=int(skip),
                                from_replica=donor_idx,
                                to_replica=target_idx)
                self.trace.flight.record(
                    "prefix_ship", from_replica=donor_idx,
                    to_replica=target_idx, pages=int(imported),
                    request_id=stream.request_id)
            _log.info(json.dumps({
                "event": "router_prefix_ship", "from": donor_idx,
                "to": target_idx, "pages": int(imported),
                "skip_pages": int(skip),
                "request_id": stream.request_id}))
            self._dedup_prefix_owners(prompt, target_idx)
            return True

    def _dedup_prefix_owners(self, prompt, target_idx):
        """Router-driven eviction pressure: when a hot prefix is now
        resident on more replicas than ``prefix_max_owners`` allows,
        ask the most-loaded surplus owners to drop their unpinned copy
        — the fleet keeps ship-reachable coverage without every
        replica pinning its own pages."""
        cap = self.prefix_max_owners
        if cap <= 0:
            return
        with self._lock:
            owners = self._owner_depths(
                prompt, set(self._routable()) | {target_idx})
        all_owners = set(owners) | {target_idx}
        excess = len(all_owners) - cap
        if excess <= 0:
            return
        cands = [i for i in all_owners if i != target_idx]
        loads = self._loads(cands)
        cands.sort(key=lambda i: (-loads[i], i))
        for idx in cands[:excess]:
            try:
                dropped = self.replicas[idx].drop_prefix(prompt)
            except Exception:
                continue
            if dropped:
                self.metrics.prefix_dedup_drops_total.inc(dropped)
                _log.info(json.dumps({
                    "event": "router_prefix_dedup_drop",
                    "replica": idx, "pages": int(dropped)}))
            self._journal(ev="drop", r=idx,
                          p=self._journal_prefix(prompt))
            with self._lock:
                self._forget_prefix_owner(prompt, idx)

    def _place(self, stream, exclude):
        """Try replicas in policy order until one admits the request.
        Shared by first placement and failover resubmission."""
        sheds = []
        tried = set(exclude)
        ship_tried = False
        for idx in self._order(stream.prompt, exclude=exclude):
            if idx in tried:
                continue
            tried.add(idx)
            if not ship_tried:
                # fleet prefix cache: before the prompt lands on the
                # policy's first choice, pull its cached prefix over
                # from wherever the fleet holds it (best-effort; the
                # admission check then counts only uncached pages).
                # Only the first candidate — shipping to every replica
                # a shed walks past would spray copies across the fleet
                ship_tried = True
                self._maybe_ship_prefix(stream, idx)
            if stream.pinned_version is not None:
                # version pin (round 21): a re-placement mid-rollout
                # must land on the weight version the stream started
                # on — the armed splice drops replayed tokens by
                # COUNT, so a different version's tail would be
                # silently grafted onto the old version's head.
                # Candidates advertising a different version are
                # skipped; unknown (None) is allowed — best-effort,
                # like the dtype-skew guard.
                v = self._replica_weight_version(idx)
                if v is not None and v != stream.pinned_version:
                    self.metrics.version_pin_skips_total.inc()
                    continue
            try:
                inner = self.replicas[idx].submit(stream.prompt,
                                                  **stream.kwargs)
            except Rejected as e:
                sheds.append(e)
                continue
            except Unavailable:
                continue
            except ReplicaFailed as e:
                self._journal(ev="down", r=idx)
                with self._lock:
                    self._down.add(idx)
                self._record_replica_failure(idx, e)
                _log.warning(json.dumps(
                    {"event": "router_replica_down", "replica": idx,
                     "cause": str(e)}))
                continue
            stream._inner = inner
            stream.replica_idx = idx
            if stream.pinned_version is None:
                # pin at FIRST placement. Reading after submit is safe
                # under the deploy protocol: the deployer drains the
                # replica (placement stops) before swapping, so an
                # admitted stream cannot interleave with a swap.
                stream.pinned_version = self._replica_weight_version(idx)
            self._breakers[idx].record_success()
            inner_rid = getattr(inner, "req_id", None)
            self._journal(
                ev="begin", rid=stream.req_id, r=idx,
                inner=inner_rid if isinstance(inner_rid, int) else None,
                req=stream.request_id)
            self.metrics.routed_total.inc(policy=self.policy,
                                          replica=idx)
            if self.trace.enabled:
                self.trace.span(stream.req_id, "routed",
                                time.perf_counter(), replica=idx,
                                policy=self.policy)
            if self.policy == "cache_aware" or self.prefix_fleet:
                # with the fleet prefix cache on, the tree is a
                # TRANSFER INDEX under every policy — placements must
                # teach it ownership or nothing is ever shippable
                self._record(stream.prompt, idx)
            return stream
        if sheds:
            self.metrics.router_shed_total.inc()
            exc = Rejected(
                "all replicas shed: " + "; ".join(map(str, sheds)))
            exc.retry_after = max(
                float(getattr(e, "retry_after", 1)) for e in sheds)
            raise exc
        raise Unavailable("no routable replica")

    def _failover(self, stream, exc):
        """The serving replica died mid-stream: mark it down, resubmit
        on a survivor, arm the splice (skip already-delivered tokens).
        Raises RuntimeError when no survivor admits the request."""
        if self._crashed:
            # the ROUTER died, not the replica: this incarnation must
            # not mark fleet members down or resubmit — the supervisor
            # retries the stream on the promoted standby
            raise RuntimeError(
                "router crashed (superseded by takeover)") from exc
        failed = stream.replica_idx
        self._journal(ev="down", r=failed)
        with self._lock:
            self._down.add(failed)
        self._record_replica_failure(failed, exc)
        stream.failovers += 1
        spliced = sum(d for d, f in zip(stream._delivered,
                                        stream._finished) if not f)
        self.metrics.failovers_total.inc(replica=failed)
        self.metrics.spliced_tokens_total.inc(spliced)
        _log.warning(json.dumps({
            "event": "router_failover", "replica": failed,
            "request_id": stream.request_id,
            "router_req_id": stream.req_id,
            "delivered_tokens": spliced, "cause": str(exc)}))
        # splice arming: the resubmission replays the stream from
        # token 0, so skip everything this stream already emitted PLUS
        # any skip remainder still unconsumed from a previous splice —
        # a failover landing mid-splice (or mid-supervisor-reattach)
        # otherwise re-delivers the dropped remainder (duplicated
        # tokens, caught by the fleet harness's exactness gate)
        stream._skip = [s + d if not f else 0
                        for s, d, f in zip(stream._skip,
                                           stream._delivered,
                                           stream._finished)]
        t0 = time.perf_counter()
        try:
            self._place(stream, exclude={failed})
        except (Rejected, Unavailable) as e:
            raise RuntimeError(
                f"failover failed for request "
                f"{stream.request_id or stream.req_id}: {e}") from e
        if self.trace.enabled:
            self.trace.span(stream.req_id, "failover_splice", t0,
                            time.perf_counter() - t0,
                            from_replica=failed,
                            to_replica=stream.replica_idx,
                            spliced_tokens=spliced, cause=str(exc))
            self.trace.flight.record(
                "failover", replica=failed,
                to_replica=stream.replica_idx,
                request_id=stream.request_id,
                spliced_tokens=spliced)

    # -- fault injection / bookkeeping -------------------------------------
    def _token_delivered(self, replica_idx):
        if self._kill is None:
            return
        self._replica_tokens[replica_idx] += 1
        idx, after, fired = self._kill
        if not fired and replica_idx == idx \
                and self._replica_tokens[idx] >= after:
            self._kill[2] = True
            _log.warning(json.dumps({"event": "router_env_kill",
                                     "replica": idx,
                                     "after_tokens": after}))
            self.kill_replica(idx, ReplicaFailed(
                f"env-injected kill after {after} tokens"))

    def _stream_done(self, stream):
        self._journal(ev="end", rid=stream.req_id)
        with self._lock:
            self._streams.pop(stream.req_id, None)
            if self.trace.enabled:
                self.trace.finish(stream.req_id)
