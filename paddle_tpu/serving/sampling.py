"""On-device fused sampling for the serving decode hot path.

Reference capability: the fused sampler every vLLM-class TPU serving
stack runs inside the decode program (PAPERS.md Gemma-on-TPU serving
comparison: the per-step host round-trip of [B, V] logits is the decode
latency killer on TPU). Moving sampling on-device shrinks the per-step
host fetch from ``B * V * 4`` bytes of logits to ``B`` int32 token ids
plus ``B`` float32 logprobs (<= B*8 bytes) while keeping the axon
one-dispatch + one-fetch rule intact.

Design constraints (CLAUDE.md transport + reproducibility rules):

- Everything here is pure jnp — it traces inside the engine's bucketed
  step program; per-request ``(seed, step)`` ride as int32 ARGUMENTS,
  so no RNG state is baked into the compiled program and the jit cache
  stays bounded (no per-seed recompiles).
- The RNG is counter-based: lane i draws from
  ``fold_in(PRNGKey(seed_i), step_i)`` where ``step`` is the REQUEST's
  token index (len(out_tokens) at sampling time), not the engine step.
  Token t of a request is therefore a pure function of
  ``(weights, history, seed, t)`` — preemption + recompute replays the
  identical stream, and forked children (distinct seeds) diverge
  deterministically.
- Categorical sampling is Gumbel-max over the filtered/temperature-
  scaled logits: one argmax, no normalization, no [B, V] division —
  and a greedy lane is literally the same argmax without noise, which
  is what makes greedy device-vs-host parity token-exact.
- ``sample_capable=False`` (a STATIC python flag at the engine's jit
  boundary) compiles the greedy-only variant with no sort in it, so
  an all-greedy decode batch — the common serving case — never pays
  the top-k/top-p sort. The trace cache at most doubles (still
  bounded by 2 * (log2(max_batch) + 2)).

Filter semantics match the host oracle (`engine._sample`, numpy):
``top_k <= 0`` or ``>= V`` disables top-k; ``top_p <= 0`` or ``>= 1``
disables top-p; both thresholds KEEP ties; top-p is applied after
top-k on the already-filtered distribution and always keeps at least
the most probable token.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["fused_sample", "fused_sample_multi"]


def _lane_keys(seeds, steps):
    """Counter-based per-lane keys: fold the request's token index into
    a key derived from its seed. Both are traced int32 arguments."""
    def one(seed, step):
        return jax.random.fold_in(jax.random.PRNGKey(seed), step)
    return jax.vmap(one)(seeds, steps)


def _filter_top_k(scaled, top_k):
    """Per-lane top-k mask (k<=0 disables; ties kept)."""
    b, v = scaled.shape
    srt = jnp.sort(scaled, axis=-1)[:, ::-1]                 # descending
    k = jnp.clip(top_k, 1, v)
    kth = jnp.take_along_axis(srt, (k - 1)[:, None], axis=-1)  # [B,1]
    disabled = (top_k[:, None] <= 0) | (top_k[:, None] >= v)
    return disabled | (scaled >= kth)


def _filter_top_p(filtered, top_p):
    """Per-lane nucleus mask on the (already top-k-filtered) logits:
    keep the smallest set of tokens whose cumulative probability
    reaches top_p (the crossing token included; ties kept)."""
    srt = jnp.sort(filtered, axis=-1)[:, ::-1]               # descending
    probs = jax.nn.softmax(srt, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p[:, None]   # exclusive cumsum < p
    thr = jnp.min(jnp.where(keep_sorted, srt, jnp.inf), axis=-1)
    disabled = (top_p[:, None] <= 0.0) | (top_p[:, None] >= 1.0)
    return disabled | (filtered >= thr[:, None])


def fused_sample(logits, do_sample, temperature, top_k, top_p, seeds,
                 steps, *, sample_capable=True):
    """Sample one token per lane inside the compiled step program.

    logits [B, V] float; do_sample bool [B]; temperature float32 [B];
    top_k int32 [B]; top_p float32 [B]; seeds/steps int32 [B].
    ``sample_capable`` is a PYTHON bool resolved at trace time.

    Returns ``(tokens int32 [B], logprobs float32 [B])`` — the logprob
    is the chosen token's log-probability under the distribution it was
    actually drawn from (post-filter, post-temperature for sampled
    lanes; the raw softmax for greedy lanes).
    """
    lg = logits.astype(jnp.float32)
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    if not sample_capable:
        lp = jax.nn.log_softmax(lg, axis=-1)
        return greedy, jnp.take_along_axis(
            lp, greedy[:, None], axis=-1)[:, 0]
    scaled = lg / jnp.maximum(temperature, 1e-6)[:, None]
    keep = _filter_top_k(scaled, top_k)
    filtered = jnp.where(keep, scaled, -jnp.inf)
    keep = keep & _filter_top_p(filtered, top_p)
    final = jnp.where(keep, scaled, -jnp.inf)
    gumbel = jax.vmap(
        lambda key: jax.random.gumbel(key, (lg.shape[1],), jnp.float32)
    )(_lane_keys(seeds, steps))
    sampled = jnp.argmax(final + gumbel, axis=-1).astype(jnp.int32)
    tok = jnp.where(do_sample, sampled, greedy)
    dist = jnp.where(do_sample[:, None], final, lg)
    lp = jax.nn.log_softmax(dist, axis=-1)
    return tok, jnp.take_along_axis(lp, tok[:, None], axis=-1)[:, 0]


def fused_sample_multi(logits, do_sample, temperature, top_k, top_p,
                       seeds, steps0, *, sample_capable=True):
    """Per-POSITION fused sampling for the speculative verify step.

    ``logits`` is [B, S, V]; the per-lane sampling params are [B] and
    broadcast over the S positions; position j of lane i draws with the
    counter key ``fold_in(PRNGKey(seeds[i]), steps0[i] + j)`` — exactly
    the key the non-speculative engine would use when sampling that
    request's token ``steps0[i] + j``. That identity is what makes
    deterministic-sample verification token-exact vs the plain decode
    loop: the verify step recomputes the SAME samples the one-token-at-
    a-time engine would have emitted, and acceptance is a pure prefix
    match against the draft's proposals.

    Returns ``(tokens int32 [B, S], logprobs float32 [B, S])``.
    """
    b, s, _ = logits.shape
    flat = logits.reshape(b * s, logits.shape[-1])

    def rep(a):
        return jnp.repeat(a, s, axis=0)

    steps = (steps0[:, None]
             + jnp.arange(s, dtype=jnp.int32)[None, :]).reshape(-1)
    tok, lp = fused_sample(flat, rep(do_sample), rep(temperature),
                           rep(top_k), rep(top_p), rep(seeds), steps,
                           sample_capable=sample_capable)
    return tok.reshape(b, s), lp.reshape(b, s)
