"""Versioned live weight deployment (round 21): the push half of the
training↔serving loop.

Weights are ARGUMENTS of every compiled step program (no recompile on
change), and the front-end lock is held across each engine step — so a
weight swap that takes that lock is, by construction, a one-step
quiesce: no program can be mid-flight while the argument pytree
changes.  This module adds the missing coordination layer on top:

- :class:`WeightRegistry` — named weight sets ("target", "draft")
  under MONOTONIC version ids; handles are in-process array lists or
  bytes-on-disk (``.npz`` under ``PADDLE_TPU_SERVING_DEPLOY_DIR``).
- :class:`RollingDeployer` — rolls a fleet one replica at a time:
  stop placement on the replica (router drain), finish its in-flight
  streams on the version they started on, quiesce-swap the argument
  pytree under the engine lock (``ServingFrontend.swap_weights`` —
  the blessed path, graftlint ``weight-swap-lock``), flush
  stale-weight K/V (``clear_prefix()`` detaches + invalidates any
  spilled kvtier chains), and re-admit.  The new version is advertised
  in ``/healthz`` and ``/metrics``.

The router side pins every in-flight stream to the weight version it
started on (the ``cache_dtype`` skew-guard pattern, router.py), so a
failover resubmission mid-rollout can never splice tokens computed
under two versions into one stream.

Failure contract (the chaos points police it): every swap failure —
``deploy_swap_fail``, a torn payload, a dead replica — must degrade to
the replica SERVING THE OLD VERSION, never to a failed request.  The
swap itself is all-or-nothing: the payload is validated against the
model's full tensor list (count, shape, dtype-compatibility) before
the first ``_data`` write, so a torn push (``distill_push_torn``)
leaves the old weights untouched.
"""
from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

from .chaos import ChaosConfig, ChaosInjector

_log = logging.getLogger("paddle_tpu.serving")

__all__ = ["DeployError", "RollingDeployer", "WeightRegistry",
           "snapshot_weights"]

# registry spill directory (bytes-on-disk handles); unset = in-process
_ENV_DIR = "PADDLE_TPU_SERVING_DEPLOY_DIR"
# seconds the deployer waits for a replica's in-flight work to finish
_ENV_DRAIN_S = "PADDLE_TPU_SERVING_DEPLOY_DRAIN_S"

WEIGHT_SET_NAMES = ("target", "draft")


class DeployError(RuntimeError):
    """A deployment step could not be completed (the replica keeps
    serving the version it already has — this error never propagates
    into a request stream)."""


def snapshot_weights(model):
    """Host snapshot of a model's generate-state pytree (parameters +
    buffers, ``_gen_state_tensors`` order) — the registry's in-process
    weight handle.  Safe to call on a serving engine's model only
    under the front-end lock (the deployer does; direct callers own
    the race)."""
    return [np.asarray(t._data) for t in model._gen_state_tensors()]


class WeightRegistry:
    """Monotonic-versioned store of named weight sets.

    One version counter spans ALL names, so a version id is globally
    unique and orders target and draft pushes on one timeline (the
    rollout journal a post-mortem wants).  ``publish`` accepts a model
    (snapshotted here) or a ready array list; ``spill`` moves a
    version's bytes to disk (``.npz``), ``get`` loads it back
    transparently."""

    def __init__(self, dirpath=None):
        self.dir = dirpath or os.environ.get(_ENV_DIR) or None
        self._lock = threading.Lock()
        self._mem = {}        # (name, version) -> [np.ndarray, ...]
        self._spilled = {}       # (name, version) -> spilled filepath
        self._latest = {}     # name -> version
        self._next = 1

    def publish(self, name, weights, *, spill=False):
        """Register a new version of ``name``; returns its version id.
        ``weights`` is a model (snapshotted) or a list of arrays
        (copied — the registry owns its bytes, a later optimizer step
        on the source must not mutate a published version)."""
        name = str(name)
        if hasattr(weights, "_gen_state_tensors"):
            arrays = snapshot_weights(weights)
        else:
            arrays = [np.array(a, copy=True) for a in weights]
        if not arrays:
            raise ValueError("empty weight set")
        with self._lock:
            version = self._next
            self._next += 1
            self._mem[(name, version)] = arrays
            self._latest[name] = version
        if spill:
            self.spill(name, version)
        return version

    def latest(self, name):
        """Newest published version id for ``name`` (None if never
        published)."""
        with self._lock:
            return self._latest.get(str(name))

    def versions(self, name):
        name = str(name)
        with self._lock:
            keys = [v for (n, v) in self._mem if n == name]
            keys += [v for (n, v) in self._spilled if n == name]
        return sorted(set(keys))

    def get(self, name, version=None):
        """The array list for (name, version) — latest when version is
        None; loads spilled versions back from disk."""
        name = str(name)
        if version is None:
            version = self.latest(name)
        if version is None:
            raise KeyError(f"no published version of {name!r}")
        key = (name, int(version))
        with self._lock:
            arrays = self._mem.get(key)
            path = self._spilled.get(key)
        if arrays is not None:
            return arrays
        if path is None:
            raise KeyError(f"unknown weight version {name}@{version}")
        with np.load(path, allow_pickle=False) as z:
            return [z[f"w{i}"] for i in range(len(z.files))]

    def spill(self, name, version):
        """Move a version's bytes to disk (requires a registry dir);
        returns the path.  Idempotent."""
        if not self.dir:
            raise DeployError(
                f"no registry dir: set {_ENV_DIR} or pass dirpath=")
        key = (str(name), int(version))
        with self._lock:
            path = self._spilled.get(key)
            arrays = self._mem.get(key)
        if path is not None and arrays is None:
            return path
        if arrays is None:
            raise KeyError(f"unknown weight version {name}@{version}")
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, f"{name}-v{int(version)}.npz")
        tmp = path + ".tmp.npz"  # np.savez appends .npz to bare names
        np.savez(tmp, **{f"w{i}": a for i, a in enumerate(arrays)})
        os.replace(tmp, path)  # atomic: readers see whole files only
        with self._lock:
            self._spilled[key] = path
            self._mem.pop(key, None)
        return path

    def drop(self, name, version):
        """Forget one version (rollback targets usually stay; this is
        the retention hook).  Never drops the latest."""
        key = (str(name), int(version))
        with self._lock:
            if self._latest.get(key[0]) == key[1]:
                raise DeployError(
                    f"refusing to drop the latest version {key[1]} of "
                    f"{key[0]!r}")
            self._mem.pop(key, None)
            path = self._spilled.pop(key, None)
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass

    def stats(self):
        with self._lock:
            return {"names": dict(self._latest),
                    "in_memory": len(self._mem),
                    "on_disk": len(self._spilled),
                    "next_version": self._next}


def _replica_weight_version(rep, which="target"):
    """Best-effort FRESH read of a replica's advertised weight version
    (None when unknown/unreachable).  Never cache the result — unlike
    ``cache_dtype`` (fixed for an engine's lifetime) the weight version
    is mutable mid-life; HTTPReplica.weight_version re-reads /healthz
    per call for exactly this reason."""
    fn = getattr(rep, "weight_version", None)
    if fn is None:
        return None
    try:
        return fn(which) if callable(fn) else fn
    except Exception:
        return None


class RollingDeployer:
    """Roll a weight version across a fleet, one replica at a time.

    ``fleet`` is a ServingRouter, a RouterSupervisor, or a bare list of
    replicas.  With a router, each replica is drained at the ROUTER
    level first (placement stops, in-flight streams finish on the
    version they started on — this is what makes the per-stream version
    pin structurally true on the happy path) and re-admitted after the
    swap.  Every failure degrades to the old version serving; the
    rollout report records per-replica quiesce time for the bench."""

    def __init__(self, fleet, registry, *, chaos=None,
                 drain_timeout_s=None):
        self.fleet = fleet
        self.registry = registry
        if isinstance(chaos, ChaosInjector):
            self.chaos = chaos
        else:
            assert chaos is None or isinstance(chaos, ChaosConfig)
            self.chaos = ChaosInjector(chaos, name="deploy")
        if drain_timeout_s is None:
            drain_timeout_s = float(os.environ.get(_ENV_DRAIN_S)
                                    or 120.0)
        self.drain_timeout_s = float(drain_timeout_s)
        self.history = []       # rollout report dicts, oldest first

    # -- fleet resolution --------------------------------------------------
    def _router(self):
        f = self.fleet
        if isinstance(f, (list, tuple)):
            return None
        active = getattr(f, "active", None)     # RouterSupervisor
        if active is not None and hasattr(active, "replicas"):
            return active
        return f if hasattr(f, "replicas") else None

    def replicas(self):
        router = self._router()
        if router is not None:
            return list(router.replicas)
        return list(self.fleet)

    # -- the rollout -------------------------------------------------------
    def rollout(self, name="target", version=None):
        """Deploy ``name``@``version`` (latest when None) to every
        replica, one at a time.  Returns the report dict (also appended
        to ``self.history``): per-replica ok/quiesce_s/advertised, plus
        totals.  Replicas that already advertise the version are
        skipped (idempotent — re-running a half-applied rollout
        finishes it)."""
        if name not in WEIGHT_SET_NAMES:
            raise ValueError(
                f"unknown weight set {name!r}; one of "
                f"{WEIGHT_SET_NAMES}")
        if version is None:
            version = self.registry.latest(name)
        if version is None:
            raise DeployError(f"no published version of {name!r}")
        arrays = self.registry.get(name, version)
        report = {"name": name, "version": int(version), "replicas": [],
                  "ok": 0, "skipped": 0, "failed": 0}
        for idx, rep in enumerate(self.replicas()):
            entry = self._deploy_one(idx, rep, name, int(version),
                                     arrays)
            report["replicas"].append(entry)
            key = ("skipped" if entry.get("skipped")
                   else "ok" if entry["ok"] else "failed")
            report[key] += 1
        report["complete"] = report["failed"] == 0
        self.history.append(report)
        _log.info("deploy rollout %s@%d: ok=%d skipped=%d failed=%d",
                  name, version, report["ok"], report["skipped"],
                  report["failed"])
        return report

    def rollback(self, name="target", version=None):
        """Roll the fleet BACK to ``version`` (default: the newest
        version older than the current latest).  Same path as rollout —
        a rollback is just a rollout of an older id (versions stay
        monotonic; the registry never reuses ids)."""
        if version is None:
            vs = self.registry.versions(name)
            if len(vs) < 2:
                raise DeployError(
                    f"nothing to roll back to for {name!r}")
            version = vs[-2]
        return self.rollout(name, version)

    def sync_replica(self, rep, names=WEIGHT_SET_NAMES):
        """Bring ONE replica to the registry's latest versions — the
        autoscaler's grown-replica hook and the supervisor's
        restart-resync: a rebuilt process serves the build-time (base)
        weights until this runs.  Best-effort: any failure leaves the
        replica serving what it has."""
        out = {}
        for name in names:
            version = self.registry.latest(name)
            if version is None:
                continue
            if _replica_weight_version(rep, name) == version:
                continue
            try:
                arrays = self.registry.get(name, version)
            except KeyError:
                continue
            entry = self._deploy_one(None, rep, name, int(version),
                                     arrays)
            out[name] = entry
        return out

    def _deploy_one(self, idx, rep, name, version, arrays):
        """One replica's deployment: router drain (when driving a
        router) → quiesce-swap → readmit → verify the advertisement.
        All failure paths land on ok=False with the OLD version still
        serving."""
        entry = {"replica": idx, "name": name, "version": version,
                 "ok": False, "skipped": False, "quiesce_s": None,
                 "advertised": None, "error": None}
        if _replica_weight_version(rep, name) == version:
            entry["ok"] = entry["skipped"] = True
            entry["advertised"] = version
            return entry
        router = self._router() if idx is not None else None
        drained = False
        try:
            if router is not None:
                drained = router.drain_replica(
                    idx, timeout=self.drain_timeout_s)
                if not drained:
                    raise DeployError(
                        f"replica {idx} did not drain within "
                        f"{self.drain_timeout_s}s")
            if self.chaos.fire("deploy_swap_fail"):
                raise DeployError("chaos: deploy_swap_fail")
            t0 = time.perf_counter()
            rep.swap_weights(name, arrays, version)
            entry["quiesce_s"] = time.perf_counter() - t0
            entry["ok"] = True
        except Exception as exc:
            entry["error"] = f"{type(exc).__name__}: {exc}"
            _log.warning("deploy: replica %s swap %s@%d failed (%s); "
                         "old version keeps serving", idx, name,
                         version, entry["error"])
        finally:
            if router is not None and drained:
                try:
                    router.readmit_replica(idx)
                except Exception as exc:  # readmit must not kill a rollout
                    entry["ok"] = False
                    entry["error"] = (entry["error"] or
                                      f"readmit: {exc}")
        if entry["ok"]:
            stale = self.chaos.fire("deploy_stale_version")
            advertised = (None if stale
                          else _replica_weight_version(rep, name))
            if advertised != version:
                # a stale advertisement (the cached-/healthz hazard
                # HTTPReplica.weight_version exists to avoid, or the
                # chaos point simulating it): the swap is atomic under
                # the engine lock, so ONE fresh re-read converges —
                # never re-roll the replica for a stale scrape
                advertised = _replica_weight_version(rep, name)
            entry["advertised"] = advertised
            if advertised is not None and advertised != version:
                entry["ok"] = False
                entry["error"] = (f"advertised {advertised} after "
                                  f"swap to {version}")
        return entry
