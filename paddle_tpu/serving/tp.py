"""Tensor-parallel SPMD serving (round 23 / ISSUE 19).

One :class:`TPContext` per :class:`~paddle_tpu.serving.engine.
ServingEngine` turns the whole decode/prefill/ragged step into ONE
GSPMD program over a device mesh: weights and KV page pools are
committed to mesh shardings at engine build, and the step bodies pin
activation layouts with ``with_sharding_constraint`` so the compiled
program's collectives are known by construction.

The exactness contract (TP=k token-exact vs TP=1, greedy AND seeded,
across preemption/recompute) is what picks the layout:

- Only the LAST (output / non-contracting) dim of an ndim>=2 weight is
  ever sharded — every matmul keeps its FULL contraction local to each
  shard, so the per-element f32 summation order is identical to the
  single-device program and the only collectives the step needs are
  all-gathers (pure data movement, bit-exact).  A Megatron-style
  row-parallel split would partial-sum + all-reduce — a DIFFERENT
  summation order, which is exactly the silent non-exactness this
  module exists to rule out.
- 1-D params (norm scales, biases) and non-divisible dims replicate.
- KV page pools shard on the HEAD axis ([NP, PS, KV, D] ->
  P(None, None, 'tp', None); int8 scale pools [NP, PS, KV] ->
  P(None, None, 'tp')): the append scatter and the paged-attention
  einsums both batch over the kv-head axis, so the whole attention
  inner loop is shard-local.  One host allocator, replicated page
  tables — per-shard tables stay in lockstep for free.
- lm_head shards the VOCAB column dim, so each shard holds partial
  (column-sliced, never partially-summed) logits; the step replicates
  them right before fused sampling — the all-gather happens only at
  the sampled lane (decode fetches [B, D] hidden first, so the
  gathered tensor is [B, V] per step, not [B, S, V]).

``pallas_call`` has no GSPMD partitioning rule (CLAUDE.md invariant),
so a TP step must never trace the Pallas paged-attention kernel: the
engine passes ``spmd=True`` down to ``attention.paged_attention`` /
``ragged_paged_attention``, which forces the jnp gather path loudly
(log + ``tp_kernel_fallbacks`` metric) even when
``PADDLE_TPU_PAGED_KERNEL=1`` asks for the kernel.  The graftlint
``pallas-hazards`` rule polices the module split structurally (no
file may both build mesh shardings and call ``pallas_call``).
"""
from __future__ import annotations

import logging
import os

_log = logging.getLogger("paddle_tpu.serving")

# the serving TP mesh axis name; distinct from the fleet trainer's
# 'mp'/'sharding'/'pp' axes so a spec composed on top of a fleet
# dist_spec can never alias an existing axis
TP_AXIS = "tp"

_ENV_TP = "PADDLE_TPU_SERVING_TP"


def resolve_tp(mesh=None, tp_degree=None):
    """Resolve the engine's tensor-parallel context.

    ``mesh`` (a ``jax.sharding.Mesh`` with a ``'tp'`` axis) wins;
    else ``tp_degree=k`` builds a 1-D mesh over the first k local
    devices; else the ``PADDLE_TPU_SERVING_TP`` knob.  Degree <= 1
    (or nothing configured) returns None — the engine runs the plain
    single-device step with zero TP code on the hot path.
    """
    if mesh is not None:
        if TP_AXIS not in mesh.axis_names:
            raise ValueError(
                f"serving TP mesh must carry a {TP_AXIS!r} axis, got "
                f"{mesh.axis_names}")
        degree = mesh.shape[TP_AXIS]
        if degree <= 1:
            return None
        return TPContext(mesh, degree)
    if tp_degree is None:
        raw = os.environ.get(_ENV_TP)
        if not raw:
            return None
        tp_degree = int(raw)
    degree = int(tp_degree)
    if degree <= 1:
        return None
    import jax
    from jax.sharding import Mesh
    devices = jax.devices()
    if degree > len(devices):
        raise ValueError(
            f"tp_degree={degree} exceeds the {len(devices)} visible "
            f"device(s); on CPU set --xla_force_host_platform_"
            f"device_count (the test conftest pins 8)")
    return TPContext(Mesh(devices[:degree], (TP_AXIS,)), degree)


class TPContext:
    """Resolved TP geometry + the sharding helpers the step bodies use.

    Rides the compiled step the same way ``model``/``core`` do —
    closed over via ``functools.partial``, never traced — so the jit
    signature and its static argnums stay exactly the TP=1 ones.
    """

    def __init__(self, mesh, degree):
        self.mesh = mesh
        self.degree = int(degree)
        self.axis = TP_AXIS

    @property
    def mesh_shape(self):
        """JSON-able geometry for /healthz (axis name -> size)."""
        return {name: int(self.mesh.shape[name])
                for name in self.mesh.axis_names}

    # -- sharding builders -------------------------------------------------
    def named(self, *spec):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        return NamedSharding(self.mesh, P(*spec))

    def param_spec(self, shape, dist_spec=None):
        """Placement spec for one weight (see module docstring).

        A param that already carries a fleet ``dist_spec`` is NEVER
        returned verbatim (the spmd.py composition invariant): the tp
        axis is composed ON TOP via ``_add_sharding`` — and kept only
        when the composition lands on the last dim, because any other
        dim is (or feeds) a contraction and a sharded contraction
        partial-sums, breaking token-exactness.  Plain params take the
        last-dim rule directly.
        """
        from jax.sharding import PartitionSpec as P

        from ..distributed.fleet.spmd import _add_sharding
        shape = tuple(int(s) for s in shape)
        if len(shape) >= 2 and dist_spec:
            # fleet axes ('mp'/'sharding'/'pp') don't exist in the
            # serving mesh — drop them before placing, keep them as
            # occupied slots for the composition so tp never doubles
            # onto a dim the trainer already split
            base = self._known_axes_only(dist_spec)
            composed = _add_sharding(dist_spec, shape, self.degree,
                                     axis=self.axis)
            if composed is not None and len(composed) == len(shape) \
                    and composed[-1] == self.axis:
                tail = list(base) + [None] * (len(shape) - len(base))
                tail[-1] = self.axis
                return P(*tail)
            return base  # replicate over tp
        if len(shape) >= 2 and shape[-1] % self.degree == 0 \
                and shape[-1] >= self.degree:
            return P(*([None] * (len(shape) - 1) + [self.axis]))
        return P()

    def _known_axes_only(self, spec):
        """A spec with every axis this mesh doesn't know replaced by
        None (axis elements may be strings or tuples of strings)."""
        from jax.sharding import PartitionSpec as P
        known = set(self.mesh.axis_names)

        def keep(el):
            if el is None:
                return None
            if isinstance(el, (tuple, list)):
                kept = tuple(a for a in el if a in known)
                return kept if kept else None
            return el if el in known else None

        return P(*[keep(el) for el in spec])

    # -- in-program layout constraints -------------------------------------
    def replicate(self, arr):
        """Pin ``arr`` replicated — the exactness-critical all-gather
        points (post-embed, post-o_proj, pre-down_proj, logits)."""
        import jax
        return jax.lax.with_sharding_constraint(arr, self.named())

    def shard_heads(self, arr):
        """Pin a [B, S, H, D] q/k/v tensor head-sharded."""
        import jax
        return jax.lax.with_sharding_constraint(
            arr, self.named(None, None, self.axis, None))

    def shard_pool(self, pool):
        """Pin a KV page pool head-sharded; int8 pools are
        (codes [NP, PS, KV, D], scales [NP, PS, KV]) tuples and the
        scales ride the SAME head split (round-15 rule)."""
        import jax
        if isinstance(pool, tuple):
            codes, scales = pool
            return (jax.lax.with_sharding_constraint(
                        codes, self.named(None, None, self.axis, None)),
                    jax.lax.with_sharding_constraint(
                        scales, self.named(None, None, self.axis)))
        return jax.lax.with_sharding_constraint(
            pool, self.named(None, None, self.axis, None))

    # -- build-time placement ----------------------------------------------
    def shard_model_weights(self, model, replicate=False):
        """Commit every generation-state tensor of ``model`` to its
        mesh placement (``replicate=True`` pins everything replicated
        — the draft-model mode: a distinct draft runs as its own
        non-TP dispatch, and replicated weights keep that program's
        numerics byte-identical to the TP=1 engine's draft)."""
        import jax
        for t in model._gen_state_tensors():
            shape = tuple(int(s) for s in t._data.shape)
            spec = () if replicate else self.param_spec(
                shape, getattr(t, "dist_spec", None))
            t._data = jax.device_put(t._data, self.named(*tuple(spec)))  # noqa: E501 # graftlint: disable=weight-swap-lock (same-value placement commit, not a weight swap: runs at engine build and inside set_weights AFTER its validation/flush, both under the blessed paths)

    def shard_cache_pools(self, cache):
        """Commit a :class:`PagedKVCache`'s pools to the head-axis
        sharding (codes AND scales; the allocator, page tables and
        every other host-side structure stay replicated/host-only)."""
        import jax
        head_nd = self.named(None, None, self.axis, None)
        head_sc = self.named(None, None, self.axis)
        cache.k_pages = [jax.device_put(p, head_nd)
                         for p in cache.k_pages]
        cache.v_pages = [jax.device_put(p, head_nd)
                         for p in cache.v_pages]
        if cache.quantized:
            cache.k_scales = [jax.device_put(p, head_sc)
                              for p in cache.k_scales]
            cache.v_scales = [jax.device_put(p, head_sc)
                              for p in cache.v_scales]
