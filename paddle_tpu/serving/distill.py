"""Online draft distillation (round 21): the training half of the
training↔serving loop.

The speculative verify step already computes the TARGET model's sample
for every draft position (engine ``_spec_round``) — i.e. live traffic
continuously produces free (history, target-token) supervision for the
draft.  This module captures it and turns it into refreshed draft
weights:

- :class:`DistillBuffer` — a bounded ring of (history, target-token)
  pairs, fed by the engine's verify loop (one cheap append per emitted
  token, under the front-end lock; knob-gated via
  ``PADDLE_TPU_SERVING_DISTILL``).  Histories are clipped to the last
  ``PADDLE_TPU_SERVING_DISTILL_HIST`` tokens — the draft's effective
  conditioning window; training on a bounded window is what keeps one
  update cheap.
- :class:`DraftDistiller` — trains a TRAINING COPY of the draft
  (never the serving engine's tensors: the serving pytree only changes
  through the deployer's quiesce path, graftlint ``weight-swap-lock``)
  with the existing stack — ``F.cross_entropy`` on the buffered hard
  targets + ``P.optimizer.AdamW`` — and pushes the refreshed weights
  through a :class:`~paddle_tpu.serving.deploy.RollingDeployer` as a
  new "draft" registry version.  Draft K/V is DISPOSABLE engine state
  (freed anywhere, catchup-prefilled next round), so a draft swap
  needs no prefix flush and in-flight streams stay token-exact: the
  draft only PROPOSES, the target's verify step decides every emitted
  token.  Acceptance rate (``spec_acceptance_rate``) becomes the
  per-workload self-improving metric the fleet harness tracks.

The ``distill_push_torn`` chaos point tears the pushed payload (drops
the tail of the array list) before it reaches the deployer: the swap's
all-or-nothing validation must bounce it and keep the old draft
serving — a bad push degrades acceptance back to where it was, never
correctness.
"""
from __future__ import annotations

import logging
import os
import threading
from collections import deque

import numpy as np

from .chaos import ChaosConfig, ChaosInjector

_log = logging.getLogger("paddle_tpu.serving")

__all__ = ["DistillBuffer", "DraftDistiller", "distill_buffer_from_env"]

# "1" = engines create a DistillBuffer and log verify pairs
_ENV_DISTILL = "PADDLE_TPU_SERVING_DISTILL"
# ring capacity (pairs) and history clip (tokens)
_ENV_BUFFER = "PADDLE_TPU_SERVING_DISTILL_BUFFER"
_ENV_HIST = "PADDLE_TPU_SERVING_DISTILL_HIST"


def distill_buffer_from_env():
    """The engine's constructor hook: a DistillBuffer when the
    ``PADDLE_TPU_SERVING_DISTILL`` knob is on, else None (logging off —
    the verify loop then pays nothing)."""
    if os.environ.get(_ENV_DISTILL) != "1":
        return None
    cap = int(os.environ.get(_ENV_BUFFER) or 4096)
    hist = int(os.environ.get(_ENV_HIST) or 64)
    return DistillBuffer(capacity=cap, max_history=hist)


class DistillBuffer:
    """Bounded ring of (history, target-token) pairs.

    ``log`` runs on the engine loop thread under the front-end lock —
    it must stay O(max_history) per token (tuple slice + append).  The
    trainer reads via ``snapshot()`` from its own thread; the internal
    mutex makes the handoff safe without touching the engine lock."""

    def __init__(self, capacity=4096, max_history=64):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}")
        if max_history < 1:
            raise ValueError(f"max_history={max_history}")
        self.capacity = int(capacity)
        self.max_history = int(max_history)
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.capacity)
        self.logged = 0         # lifetime pairs (ring may have evicted)

    def log(self, prompt, out_tokens, target_token):
        """One verify-step pair: the token history BEFORE the emitted
        token (prompt + accepted output so far, clipped to the last
        ``max_history`` tokens) and the target's chosen token."""
        k = self.max_history
        out = tuple(out_tokens[-k:]) if out_tokens else ()
        if len(out) < k:
            take = k - len(out)
            hist = tuple(int(t) for t in prompt[-take:]) + out
        else:
            hist = out
        with self._lock:
            self._ring.append((hist, int(target_token)))
            self.logged += 1

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def snapshot(self, clear=False):
        """The buffered pairs, oldest first."""
        with self._lock:
            pairs = list(self._ring)
            if clear:
                self._ring.clear()
        return pairs

    def stats(self):
        with self._lock:
            return {"pairs": len(self._ring), "logged": self.logged,
                    "capacity": self.capacity,
                    "max_history": self.max_history}


class DraftDistiller:
    """Train a draft copy on buffered verify pairs; push via the
    deployer.

    ``train_model`` is the caller-built TRAINING instance of the draft
    architecture (never the serving engine's model object — build it
    up front, and build it SERIALLY with any engine builds:
    ``P.seed()`` is process-global, the round-19 RNG-interleave
    hazard).  ``run_background()`` drives train→push cycles on a
    daemon thread — the "background process" of the loop; it shares
    the interpreter but touches serving state only through the
    deployer's quiesced swap."""

    def __init__(self, train_model, buffer, *, lr=1e-3, batch_size=32,
                 min_pairs=64, chaos=None):
        self.model = train_model
        self.buffer = buffer
        self.lr = float(lr)
        self.batch_size = int(batch_size)
        self.min_pairs = int(min_pairs)
        if isinstance(chaos, ChaosInjector):
            self.chaos = chaos
        else:
            assert chaos is None or isinstance(chaos, ChaosConfig)
            self.chaos = ChaosInjector(chaos, name="distill")
        self._opt = None
        self._stop = threading.Event()
        self._thread = None
        self.steps_trained = 0
        self.pushes = 0

    # -- training ----------------------------------------------------------
    def _optimizer(self):
        if self._opt is None:
            import paddle_tpu as P
            self._opt = P.optimizer.AdamW(
                self.lr, parameters=self.model.parameters())
        return self._opt

    def train_once(self, max_steps=50, clear=False):
        """One training pass over the current buffer contents (hard
        targets, cross-entropy on the LAST position of each history —
        ``ignore_index`` masks the rest, no slicing on the logits).
        Same-length histories batch together.  Returns a report with
        first/last loss so the harness can assert learning happened."""
        import paddle_tpu as P
        import paddle_tpu.nn.functional as F
        pairs = self.buffer.snapshot(clear=clear)
        if len(pairs) < self.min_pairs:
            return {"steps": 0, "pairs": len(pairs),
                    "skipped": "not enough pairs"}
        by_len = {}
        for hist, tok in pairs:
            by_len.setdefault(len(hist), []).append((hist, tok))
        self.model.train()
        opt = self._optimizer()
        losses = []
        steps = 0
        for length in sorted(by_len, reverse=True):
            group = by_len[length]
            for i in range(0, len(group), self.batch_size):
                if steps >= max_steps:
                    break
                chunk = group[i:i + self.batch_size]
                ids = np.asarray([h for h, _ in chunk], np.int32)
                labels = np.full(ids.shape, -100, np.int64)
                labels[:, -1] = [t for _, t in chunk]
                logits = self.model(P.to_tensor(ids))
                loss = F.cross_entropy(logits, P.to_tensor(labels),
                                       ignore_index=-100)
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(np.asarray(loss._data)))
                steps += 1
            if steps >= max_steps:
                break
        self.steps_trained += steps
        return {"steps": steps, "pairs": len(pairs),
                "loss_first": losses[0] if losses else None,
                "loss_last": losses[-1] if losses else None}

    # -- the push ----------------------------------------------------------
    def push(self, registry, deployer=None):
        """Publish the trained weights as a new "draft" version and
        (with a deployer) roll the fleet to it.  The
        ``distill_push_torn`` point tears the payload here — the
        deployer-side all-or-nothing validation must bounce the swap
        and keep the OLD draft serving (the push is retried whole next
        cycle; a torn push never becomes a half-swapped draft)."""
        from .deploy import snapshot_weights
        arrays = snapshot_weights(self.model)
        if self.chaos.fire("distill_push_torn"):
            arrays = arrays[:max(1, len(arrays) // 2)]
        version = registry.publish("draft", arrays)
        report = {"version": version, "rolled": None}
        if deployer is not None:
            report["rolled"] = deployer.rollout("draft", version)
        self.pushes += 1
        return report

    # -- background loop ---------------------------------------------------
    def run_background(self, registry, deployer, *, interval_s=1.0,
                       max_steps=50):
        """Start the train→push cycle on a daemon thread.  Returns the
        thread; ``stop()`` ends it.  Push failures (torn payload, swap
        chaos) are logged and the cycle continues — the loop is
        strictly best-effort, serving never depends on it."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("distiller already running")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    rep = self.train_once(max_steps=max_steps)
                    if rep["steps"]:
                        self.push(registry, deployer)
                except Exception:
                    _log.warning("distill cycle failed; retrying next "
                                 "interval", exc_info=True)
                self._stop.wait(interval_s)

        self._thread = threading.Thread(
            target=loop, name="serving-distill", daemon=True)
        self._thread.start()
        return self._thread

    def stop(self, timeout=10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
