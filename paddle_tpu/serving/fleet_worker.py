"""Replica server process entry — what :class:`ProcessReplicaBackend`
spawns (``python -m paddle_tpu.serving.fleet_worker``).

One worker = one :class:`~paddle_tpu.serving.engine.ServingEngine`
behind one :class:`~paddle_tpu.serving.server.ServingServer` on an
ephemeral port.  The bound port is announced through an atomically
written ready file (tmp + rename, so the supervising backend never
reads a half-written announcement), then the worker serves until:

- SIGTERM/SIGINT — graceful: drain in-flight requests (bounded by the
  spec's ``drain_s``), then exit 0;
- its PARENT dies — the self-reap watchdog: a worker whose supervising
  process vanished (harness SIGKILLed, pytest timeout, operator ^C -9)
  notices ``os.getppid()`` changed and drains itself out, so fleet
  workers can never become stale-pytest-style orphans (CLAUDE.md
  round-4 addenda) no matter how the parent went away.

The device platform is forced to ``cpu`` by default BEFORE any jax
work: the axon sitecustomize bakes ``JAX_PLATFORMS`` at interpreter
start and a dead tunnel makes the first device touch hang forever
(CLAUDE.md chip hygiene) — a control-plane worker must never gamble on
that.  A deployment that owns its accelerator passes
``platform: null`` in the spec.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def build_engine_from_spec(spec):
    """``spec`` (the :class:`~paddle_tpu.serving.fleet.ReplicaSpec`
    dict) → a ready ``ServingEngine``.  ``builder:
    "module:function"`` overrides the default tiny-Llama builder —
    the function receives the spec dict and returns the engine (real
    deployments load real weights there)."""
    builder = spec.get("builder")
    if builder:
        import importlib
        mod, _, fn = str(builder).partition(":")
        make = getattr(importlib.import_module(mod), fn)
        return make(spec)
    import paddle_tpu as P
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from .engine import ServingEngine
    model_kw = dict(spec.get("model") or {})
    seed = int(model_kw.pop("seed", 0))
    model_kw.setdefault("vocab_size", 97)
    model_kw.setdefault("hidden_size", 32)
    model_kw.setdefault("intermediate_size", 64)
    model_kw.setdefault("num_hidden_layers", 2)
    model_kw.setdefault("num_attention_heads", 4)
    model_kw.setdefault("max_position_embeddings", 64)
    P.seed(seed)
    model = LlamaForCausalLM(LlamaConfig(**model_kw))
    model.eval()
    engine_kw = dict(spec.get("engine") or {})
    engine_kw.setdefault("page_size", 4)
    engine_kw.setdefault("num_pages", 160)
    engine_kw.setdefault("max_batch", 8)
    engine_kw.setdefault("prefill_chunk", 8)
    return ServingEngine(model, **engine_kw)


def _write_ready(path, info):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, path)  # atomic: the backend never reads a torn file


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--spec", required=True,
                    help="path to the ReplicaSpec JSON")
    ap.add_argument("--ready-file", required=True,
                    help="where to announce {port, pid} once serving")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--parent-pid", type=int, default=0,
                    help="self-reap when this process disappears")
    args = ap.parse_args(argv)

    with open(args.spec) as f:
        spec = json.load(f)

    engine_spec = dict(spec.get("engine") or {})
    tp = int(engine_spec.get("tp_degree") or 0)
    if tp > 1 and "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # a tp_degree spec needs a multi-device mesh; on the CPU
        # platform that means the host-device-count flag, which XLA
        # reads at backend init — set it BEFORE the first jax touch
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={max(tp, 8)}")

    platform = spec.get("platform", "cpu")
    if platform:
        # must land BEFORE the first jax device touch; the env var is
        # ignored (sitecustomize bakes it), the config update is not
        import jax
        jax.config.update("jax_platforms", platform)

    engine = build_engine_from_spec(spec)
    from .server import ServingServer
    srv = ServingServer(engine, host=args.host, port=0,
                        role=spec.get("role"),
                        max_queued=int(spec.get("max_queued", 64)))
    _, port = srv.start()
    _write_ready(args.ready_file, {"port": port, "pid": os.getpid()})

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    if args.parent_pid:
        def watchdog():
            while not stop.wait(2.0):
                if os.getppid() != args.parent_pid:
                    stop.set()  # parent died: self-reap, never orphan
                    return
        threading.Thread(target=watchdog, name="fleet-parent-watchdog",
                         daemon=True).start()

    stop.wait()
    srv.close(timeout=float(spec.get("drain_s", 10.0)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
