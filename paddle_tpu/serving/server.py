"""OpenAI-compatible HTTP front-end for the serving engine — stdlib
only (``http.server`` + ``socketserver`` threading mixin), no new
dependencies (reference capability: the FastDeploy / Paddle Serving
HTTP layer; protocol shape: the OpenAI completions API that vLLM-class
servers expose).

Endpoints
---------
- ``POST /v1/completions`` — ``{"prompt": [token ids], "max_tokens",
  "stream", "temperature", "top_k", "seed", "n", "deadline_s"}``.
  The repo has no tokenizer, so prompts are TOKEN ID LISTS by default;
  pass ``tokenizer=`` (str → ids) to accept strings.
- ``POST /v1/chat/completions`` — ``{"messages": [{"role", "content"}]}``
  with the same generation fields; message contents are id lists (or
  strings via ``tokenizer``), concatenated in order.
- ``GET /healthz`` — ``{"status": "ok"|"draining"|"failed", ...}``
  (200 while serving or draining, 503 once failed).
- ``GET /metrics`` — Prometheus text exposition (format 0.0.4).

Streaming: ``"stream": true`` responds as Server-Sent Events, one
OpenAI-shaped chunk per token (plus a ``token_id`` extension field so
clients that brought their own tokenizer stay bit-exact), a final
finish-reason chunk per sample, then ``data: [DONE]``. The connection
is close-delimited (HTTP/1.0 semantics) — no chunked framing needed.

Overload semantics: an admission the front-end sheds (queue full or
page reservation would dip into the scheduler watermark — see
``frontend.py``) returns **429** with ``Retry-After: 1``; a draining or
failed server returns **503**; malformed requests 400. A client that
disconnects mid-stream gets its request **cancelled** — the engine
frees its KV pages and purges the scheduler queues on the spot.

Shutdown: ``drain()`` flips /healthz to "draining", 503s new work,
finishes every in-flight request; ``close()`` then stops the listener.
"""
from __future__ import annotations

import json
import logging
import os
import re
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .engine import EngineDraining
from .frontend import Rejected, ServingFrontend, Unavailable

# `: ping` SSE comment frames flow at this cadence whenever no token is
# ready — bounded disconnect detection even while decode/prefill stalls
_KEEPALIVE_ENV = "PADDLE_TPU_SERVING_KEEPALIVE_S"
_REQ_ID_SAFE = re.compile(r"[^A-Za-z0-9._:-]")

__all__ = ["ServingServer"]

_log = logging.getLogger("paddle_tpu.serving")


class _BadRequest(ValueError):
    pass


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    owner = None  # set by ServingServer.start


class ServingServer:
    """Owns a :class:`ServingFrontend` (engine loop thread) and a
    threaded HTTP listener. ``start()`` binds and returns
    ``(host, port)`` (port 0 → ephemeral)."""

    def __init__(self, engine, *, host="127.0.0.1", port=0,
                 model_name="paddle-tpu", tokenizer=None,
                 detokenizer=None, max_queued=64, stream_timeout_s=120.0,
                 poll_interval_s=0.001, role=None):
        if hasattr(engine, "submit"):
            # a ready front-end-shaped object (ServingFrontend or a
            # ServingRouter): serve it as-is — the router speaks the
            # same submit/cancel/health/prometheus/drain surface, so
            # one ServingServer can front N replicas
            self.frontend = engine
        else:
            self.frontend = ServingFrontend(
                engine, max_queued=max_queued,
                poll_interval_s=poll_interval_s, role=role)
        self.host = host
        self.port = int(port)
        self.model_name = model_name
        self.tokenizer = tokenizer        # str -> list[int]
        self.detokenizer = detokenizer    # int -> str
        self.stream_timeout_s = float(stream_timeout_s)
        self._httpd = None
        self._serve_thread = None
        # close()/abort() can race (a chaos kill drill aborting while the
        # fleet supervisor tears the replica down): the listener handoff
        # must be atomic or the loser dereferences a None _httpd.
        self._teardown_lock = threading.Lock()

    def _take_httpd(self):
        with self._teardown_lock:
            httpd, self._httpd = self._httpd, None
        return httpd

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._httpd is not None:
            raise RuntimeError("server already started")
        self.frontend.start()
        self._httpd = _HTTPServer((self.host, self.port), _Handler)
        self._httpd.owner = self
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="serving-http", daemon=True)
        self._serve_thread.start()
        _log.info(json.dumps({"event": "server_started",
                              "host": self.host, "port": self.port}))
        return self.host, self.port

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def drain(self, timeout=120.0):
        """Graceful drain: reject new admissions (503), finish all
        in-flight requests. The listener stays up for /healthz and
        /metrics until close(). True when fully drained in time."""
        return self.frontend.drain(timeout)

    def cancel(self, req_id):
        return self.frontend.cancel(req_id)

    def close(self, timeout=120.0):
        """drain() then stop the HTTP listener."""
        drained = self.frontend.drain(timeout)
        httpd = self._take_httpd()
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        return drained

    def abort(self, exc=None):
        """kill -9 semantics without a process (fleet ThreadLauncher /
        chaos drills): fail the front-end hard — live pages released,
        open streams erred, NO drain — and stop the listener
        immediately, so clients see exactly what a SIGKILLed server
        process looks like: connections reset, /healthz unreachable."""
        try:
            if hasattr(self.frontend, "fail"):
                self.frontend.fail(exc or RuntimeError("server aborted"))
        except Exception:  # pragma: no cover - teardown is best-effort
            pass
        httpd = self._take_httpd()
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()

    # -- request translation ----------------------------------------------
    def _encode(self, body, chat):
        def ids_of(content, what):
            if isinstance(content, list) and all(
                    isinstance(t, int) for t in content):
                return content
            if isinstance(content, str):
                if self.tokenizer is None:
                    raise _BadRequest(
                        f"{what} is a string but the server has no "
                        "tokenizer; send a token id list")
                return list(self.tokenizer(content))
            raise _BadRequest(
                f"{what} must be a list of token ids"
                + (" or a string" if self.tokenizer else ""))

        if chat:
            msgs = body.get("messages")
            if not isinstance(msgs, list) or not msgs:
                raise _BadRequest("messages must be a non-empty list")
            ids = []
            for i, m in enumerate(msgs):
                if not isinstance(m, dict) or "content" not in m:
                    raise _BadRequest(
                        f"messages[{i}] needs a content field")
                ids += ids_of(m["content"], f"messages[{i}].content")
            return ids
        if "prompt" not in body:
            raise _BadRequest("prompt is required")
        return ids_of(body["prompt"], "prompt")

    def _gen_kwargs(self, body):
        kw = {"max_new_tokens": body.get("max_tokens", 16)}
        if not isinstance(kw["max_new_tokens"], int):
            raise _BadRequest("max_tokens must be an integer")
        temp = body.get("temperature")
        if temp is not None and float(temp) > 0:
            kw.update(do_sample=True, temperature=float(temp))
        if body.get("n") is not None:
            kw["n"] = int(body["n"])
        if body.get("top_k") is not None:
            kw["top_k"] = int(body["top_k"])
        if body.get("top_p") is not None:
            kw["top_p"] = float(body["top_p"])
        if body.get("seed") is not None:
            kw["seed"] = int(body["seed"])
        if body.get("logprobs"):
            kw["logprobs"] = True
        if body.get("deadline_s") is not None:
            kw["deadline_s"] = float(body["deadline_s"])
        if body.get("speculative") is not None:
            # per-request speculative-decoding opt-out (False forces
            # plain decode; True/absent = engine default)
            kw["speculative"] = bool(body["speculative"])
        if body.get("prefill_only"):
            # disagg tier: run chunked prefill + the first token, then
            # hold the pages for /v1/_pages export (finish "prefilled")
            kw["prefill_only"] = True
        return kw

    def _piece(self, tok):
        if self.detokenizer is not None:
            return self.detokenizer(tok)
        return f"{tok} "  # no tokenizer: token ids as text


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.0: responses are close-delimited, which is exactly what the
    # SSE stream needs (no chunked framing, no keep-alive bookkeeping)
    protocol_version = "HTTP/1.0"
    server_version = "paddle-tpu-serving/1.0"

    def log_message(self, fmt, *args):  # route to logging, not stderr
        _log.debug("%s %s", self.address_string(), fmt % args)

    @property
    def owner(self) -> ServingServer:
        return self.server.owner

    # -- plumbing ----------------------------------------------------------
    def _json(self, code, obj, extra_headers=()):
        payload = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _error(self, code, message, err_type, retry=None):
        """``retry`` (seconds) adds a Retry-After header — the router
        propagates the max over its replicas' sheds here."""
        extra = (("Retry-After", str(max(1, int(retry)))),) \
            if retry else ()
        self._json(code, {"error": {"message": message,
                                    "type": err_type, "code": code}},
                   extra_headers=extra)

    def _read_json(self):
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            return body
        except (ValueError, json.JSONDecodeError) as e:
            self._error(400, f"invalid JSON body: {e}",
                        "invalid_request_error")
            return None

    def _sse(self, obj):
        self.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
        self.wfile.flush()

    # -- routes ------------------------------------------------------------
    def do_GET(self):
        if self.path == "/healthz":
            h = self.owner.frontend.health()
            self._json(503 if h["status"] == "failed" else 200, h)
        elif self.path.split("?", 1)[0] == "/debug/trace":
            self._debug_trace()
        elif self.path == "/debug/flight":
            self._debug_flight()
        elif self.path == "/metrics":
            text = self.owner.frontend.prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            self.wfile.write(text)
        else:
            self._error(404, f"no route {self.path}",
                        "invalid_request_error")

    # -- observability (round 16): /debug/trace + /debug/flight ------------
    def _debug_trace(self):
        """Span timelines as JSON — ``?request_id=`` (the X-Request-Id
        string, the cross-replica stitch key) or ``?req_id=`` (engine-
        local integer); a router front-end merges its replicas."""
        from urllib.parse import parse_qs, urlparse
        fe = self.owner.frontend
        if not hasattr(fe, "debug_trace"):
            self._error(404, "no trace store here",
                        "invalid_request_error")
            return
        q = parse_qs(urlparse(self.path).query)
        kw = {}
        rid = (q.get("request_id") or [None])[0]
        if rid is not None:
            kw["request_id"] = rid
        req_id = (q.get("req_id") or [None])[0]
        if req_id is not None:
            try:
                kw["req_id"] = int(req_id)
            except ValueError:
                self._error(400, f"req_id must be an integer, got "
                            f"{req_id!r}", "invalid_request_error")
                return
        self._json(200, fe.debug_trace(**kw))

    def _debug_flight(self):
        fe = self.owner.frontend
        if not hasattr(fe, "debug_flight"):
            self._error(404, "no flight recorder here",
                        "invalid_request_error")
            return
        self._json(200, fe.debug_flight())

    def do_POST(self):
        if self.path == "/v1/completions":
            self._completions(chat=False)
        elif self.path == "/v1/chat/completions":
            self._completions(chat=True)
        elif self.path == "/v1/_pages":
            self._pages_import()
        elif self.path == "/v1/_pages/probe":
            self._pages_probe()
        elif self.path == "/v1/_pages/export":
            self._pages_export()
        elif self.path == "/v1/_pages/release":
            self._pages_release()
        elif self.path == "/v1/_pages/prefix":
            self._prefix_import()
        elif self.path == "/v1/_pages/prefix/export":
            self._prefix_export()
        elif self.path == "/v1/_pages/prefix/drop":
            self._prefix_drop()
        elif self.path == "/v1/_pages/prefix/restore":
            self._prefix_restore()
        elif self.path == "/v1/_pages/prefix/prewarm":
            self._prefix_prewarm()
        elif self.path == "/v1/_deploy/swap":
            self._deploy_swap()
        else:
            self._error(404, f"no route {self.path}",
                        "invalid_request_error")

    # -- KV page migration (/v1/_pages, disagg tier) -----------------------
    def _migration_frontend(self):
        """The single-engine front-end behind this server, or None —
        routers/aggregators do not hold pages themselves."""
        fe = self.owner.frontend
        return fe if hasattr(fe, "export_request") else None

    def _pages_probe(self):
        fe = self._migration_frontend()
        body = self._read_json()
        if body is None:
            return
        if fe is None:
            self._error(404, "this endpoint serves an aggregator, not "
                        "an engine — probe its replicas directly",
                        "invalid_request_error")
            return
        try:
            prompt = body["prompt"]
            self._json(200, {"cached_pages": fe.probe_prefix(prompt)})
        except (KeyError, TypeError, ValueError) as e:
            self._error(400, f"bad probe request: {e}",
                        "invalid_request_error")

    def _pages_export(self):
        fe = self._migration_frontend()
        body = self._read_json()
        if body is None:
            return
        if fe is None:
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        from .pagewire import serialize_pages
        try:
            meta, k, v = fe.export_request(
                int(body["req_id"]), int(body.get("skip_pages", 0)))
        except KeyError as e:
            self._error(404, f"no held pages: {e}",
                        "invalid_request_error")
            return
        except (TypeError, ValueError) as e:
            self._error(400, f"bad export request: {e}",
                        "invalid_request_error")
            return
        payload = serialize_pages(meta, k, v)
        self.send_response(200)
        self.send_header("Content-Type",
                         "application/x-paddle-tpu-kv-pages")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _pages_release(self):
        fe = self._migration_frontend()
        body = self._read_json()
        if body is None:
            return
        if fe is None:
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        try:
            released = fe.release_request(int(body["req_id"]))
        except (KeyError, TypeError, ValueError) as e:
            self._error(400, f"bad release request: {e}",
                        "invalid_request_error")
            return
        self._json(200, {"released": bool(released)})

    def _pages_import(self):
        """Adopt a migrated sequence: the request body is the pagewire
        payload (geometry-checked twice — wire shape here, allocator
        shape at import) and the response is the SSE continuation
        stream.  409 carries ``cached_pages`` on prefix drift so the
        migration driver can re-export the right suffix."""
        from .kv_cache import GeometryMismatch, OutOfPages, PrefixDrift
        from .pagewire import (MAX_PAYLOAD_BYTES, WireFormatError,
                               deserialize_pages)
        fe = self._migration_frontend()
        if fe is None:
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if not 0 < length <= MAX_PAYLOAD_BYTES:
            self._error(400, f"bad Content-Length {length}",
                        "invalid_request_error")
            return
        request_id = self._request_id()
        try:
            meta, k, v, req = deserialize_pages(self.rfile.read(length))
            if not isinstance(req, dict):
                raise WireFormatError(
                    "payload carries no continuation request")
            kw = {}
            temp = req.get("temperature")
            if temp is not None and float(temp) > 0:
                kw.update(do_sample=True, temperature=float(temp))
            if req.get("top_k") is not None:
                kw["top_k"] = int(req["top_k"])
            if req.get("top_p") is not None:
                kw["top_p"] = float(req["top_p"])
            if req.get("seed") is not None:
                kw["seed"] = int(req["seed"])
            if req.get("deadline_s") is not None:
                kw["deadline_s"] = float(req["deadline_s"])
            if req.get("speculative") is not None:
                kw["speculative"] = bool(req["speculative"])
            if req.get("logprobs"):
                kw["logprobs"] = True
            stream = fe.adopt(
                meta, k, v, max_new_tokens=int(req["max_tokens"]),
                request_id=req.get("request_id") or request_id, **kw)
        except PrefixDrift as e:
            self._json(409, {"error": {
                "message": str(e), "type": "prefix_drift", "code": 409,
                "cached_pages": e.cached_pages}})
            return
        except GeometryMismatch as e:
            self._json(409, {"error": {"message": str(e),
                                       "type": "geometry_mismatch",
                                       "code": 409}})
            return
        except (Rejected, OutOfPages) as e:
            self._error(429, str(e), "overloaded",
                        retry=getattr(e, "retry_after", 1))
            return
        except (Unavailable, EngineDraining) as e:
            self._error(503, str(e), "unavailable")
            return
        except (WireFormatError, KeyError, TypeError, ValueError) as e:
            self._error(400, f"bad page payload: {e}",
                        "invalid_request_error")
            return
        self._stream_sse(stream, False, f"cmpl-{stream.req_id}",
                         request_id)

    # -- fleet prefix transfer (/v1/_pages/prefix, round 18) ---------------
    def _prefix_export(self):
        """Serve this replica's cached prefix of the posted prompt as
        a pagewire payload (the donor side of a fleet prefix ship).
        409 carries ``cached_pages`` when the local chain drifted below
        the requested skip."""
        from .kv_cache import PrefixDrift
        from .pagewire import serialize_pages
        fe = self._migration_frontend()
        body = self._read_json()
        if body is None:
            return
        if fe is None:
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        try:
            meta, k, v = fe.export_prefix(
                body["prompt"], int(body.get("skip_pages", 0)))
        except PrefixDrift as e:
            self._json(409, {"error": {
                "message": str(e), "type": "prefix_drift", "code": 409,
                "cached_pages": e.cached_pages}})
            return
        except (KeyError, TypeError, ValueError) as e:
            self._error(400, f"bad prefix export request: {e}",
                        "invalid_request_error")
            return
        payload = serialize_pages(meta, k, v)
        self.send_response(200)
        self.send_header("Content-Type",
                         "application/x-paddle-tpu-kv-pages")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _prefix_import(self):
        """Land a shipped prefix payload in this replica's radix tree
        (no continuation stream — the pages enter CACHED and the
        follow-up completion request hits them).  The same bounce
        semantics as adoption: 409 drift (with cached_pages) /
        geometry, 429 capacity shed."""
        from .kv_cache import GeometryMismatch, OutOfPages, PrefixDrift
        from .pagewire import (MAX_PAYLOAD_BYTES, WireFormatError,
                               deserialize_pages)
        fe = self._migration_frontend()
        if fe is None:
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if not 0 < length <= MAX_PAYLOAD_BYTES:
            self._error(400, f"bad Content-Length {length}",
                        "invalid_request_error")
            return
        try:
            meta, k, v, _ = deserialize_pages(self.rfile.read(length))
            imported = fe.import_prefix(meta, k, v)
        except PrefixDrift as e:
            self._json(409, {"error": {
                "message": str(e), "type": "prefix_drift", "code": 409,
                "cached_pages": e.cached_pages}})
            return
        except GeometryMismatch as e:
            self._json(409, {"error": {"message": str(e),
                                       "type": "geometry_mismatch",
                                       "code": 409}})
            return
        except (Rejected, OutOfPages) as e:
            self._error(429, str(e), "overloaded",
                        retry=getattr(e, "retry_after", 1))
            return
        except (Unavailable, EngineDraining) as e:
            self._error(503, str(e), "unavailable")
            return
        except (WireFormatError, KeyError, TypeError, ValueError) as e:
            self._error(400, f"bad prefix payload: {e}",
                        "invalid_request_error")
            return
        self._json(200, {"imported_pages": int(imported)})

    def _prefix_drop(self):
        fe = self._migration_frontend()
        body = self._read_json()
        if body is None:
            return
        if fe is None:
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        try:
            dropped = fe.drop_prefix(body["prompt"])
        except (KeyError, TypeError, ValueError) as e:
            self._error(400, f"bad prefix drop request: {e}",
                        "invalid_request_error")
            return
        self._json(200, {"dropped_pages": int(dropped)})

    # -- hierarchical KV tier (/v1/_pages/prefix/restore, round 20) --------
    def _prefix_restore(self):
        """Restore the posted prompt's prefix from this replica's OWN
        host tier (the router's local-tier probe before scheduling a
        remote ship).  The tier is best-effort by contract, so a miss
        or no-tier engine is 200 with 0 pages, never an error."""
        fe = self._migration_frontend()
        body = self._read_json()
        if body is None:
            return
        if fe is None or not hasattr(fe, "restore_prefix"):
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        try:
            restored = fe.restore_prefix(body["prompt"])
        except (KeyError, TypeError, ValueError) as e:
            self._error(400, f"bad prefix restore request: {e}",
                        "invalid_request_error")
            return
        self._json(200, {"restored_pages": int(restored)})

    def _prefix_prewarm(self):
        """Restore this replica's hottest spilled chains (the
        autoscaler's grow hook).  Best-effort: 0 pages on a cold or
        tierless engine."""
        fe = self._migration_frontend()
        body = self._read_json()
        if body is None:
            return
        if fe is None or not hasattr(fe, "prewarm_prefix"):
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        try:
            mc = body.get("max_chains")
            restored = fe.prewarm_prefix(
                None if mc is None else int(mc))
        except (TypeError, ValueError) as e:
            self._error(400, f"bad prefix prewarm request: {e}",
                        "invalid_request_error")
            return
        self._json(200, {"restored_pages": int(restored)})

    # -- versioned live weight deployment (round 21) -----------------------
    def _deploy_swap(self):
        """Quiesce-swap this engine's weights to a pushed version
        (npz-over-JSON payload from HTTPReplica.swap_weights).
        All-or-nothing: a torn/mismatched payload is a 400 and the old
        version keeps serving — the deployer's degrade contract."""
        import base64
        import io

        import numpy as np
        fe = self.owner.frontend
        body = self._read_json()
        if body is None:
            return
        if not hasattr(fe, "swap_weights"):
            self._error(404, "no engine front-end here",
                        "invalid_request_error")
            return
        try:
            which = str(body["which"])
            version = int(body["version"])
            raw = base64.b64decode(body["npz_b64"])
            with np.load(io.BytesIO(raw), allow_pickle=False) as z:
                arrays = [z[f"w{i}"] for i in range(len(z.files))]
        except Exception as e:  # torn b64/zip payloads raise broadly
            self._error(400, f"bad swap payload: {e}",
                        "invalid_request_error")
            return
        try:
            flushed = fe.swap_weights(which, arrays, version)
        except (TypeError, ValueError) as e:
            self._error(400, f"swap rejected: {e}",
                        "invalid_request_error")
            return
        except Unavailable as e:
            self._error(503, str(e), "unavailable_error")
            return
        self._json(200, {"prefix_flushed": int(flushed),
                         "weight_version": dict(
                             fe.engine.weight_version)})

    # -- completion flow ---------------------------------------------------
    def _request_id(self):
        """Accept the client's ``X-Request-Id`` (sanitized, bounded) or
        mint one — threaded through add_request, the structured finish
        log, the SSE chunks, and the router's failover log, so one id
        traces a request across replicas."""
        rid = self.headers.get("X-Request-Id") or ""
        rid = _REQ_ID_SAFE.sub("", rid)[:64]
        return rid or f"req-{uuid.uuid4().hex[:16]}"

    def _completions(self, chat):
        srv = self.owner
        body = self._read_json()
        if body is None:
            return
        request_id = self._request_id()
        try:
            prompt = srv._encode(body, chat)
            kw = srv._gen_kwargs(body)
            stream = srv.frontend.submit(prompt, request_id=request_id,
                                         **kw)
        except Rejected as e:
            self._error(429, str(e), "overloaded",
                        retry=getattr(e, "retry_after", 1))
            return
        except (Unavailable, EngineDraining) as e:
            self._error(503, str(e), "unavailable")
            return
        except (_BadRequest, ValueError) as e:
            self._error(400, str(e), "invalid_request_error")
            return
        rid = f"{'chatcmpl' if chat else 'cmpl'}-{stream.req_id}"
        if body.get("stream"):
            self._stream_sse(stream, chat, rid, request_id)
        else:
            self._respond_full(stream, chat, rid, len(prompt),
                               request_id)

    def _chunk(self, chat, rid, index, *, piece=None, token=None,
               finish=None, logprob=None, request_id=None):
        if chat:
            choice = {"index": index,
                      "delta": ({"content": piece}
                                if piece is not None else {})}
            obj = "chat.completion.chunk"
        else:
            choice = {"index": index, "text": piece or ""}
            obj = "text_completion"
        if token is not None:
            choice["token_id"] = token
        if logprob is not None:
            choice["logprob"] = logprob
        choice["finish_reason"] = finish
        out = {"id": rid, "object": obj,
               "model": self.owner.model_name, "choices": [choice]}
        if request_id is not None:
            out["request_id"] = request_id
        return out

    def _stream_sse(self, stream, chat, rid, request_id=None):
        srv = self.owner
        keepalive = float(os.environ.get(_KEEPALIVE_ENV, "15") or 15)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        if request_id is not None:
            self.send_header("X-Request-Id", request_id)
        self.end_headers()
        try:
            for ev in stream.events(timeout=srv.stream_timeout_s,
                                    idle_s=keepalive):
                if ev["type"] == "idle":
                    # SSE comment frame: ignored by clients, but the
                    # write surfaces a hung-up socket within ~2
                    # keepalive periods even when no token flows
                    self.wfile.write(b": ping\n\n")
                    self.wfile.flush()
                elif ev["type"] == "token":
                    self._sse(self._chunk(
                        chat, rid, ev["index"],
                        piece=srv._piece(ev["token"]),
                        token=ev["token"],
                        logprob=ev.get("logprob"),
                        request_id=request_id))
                else:
                    self._sse(self._chunk(chat, rid, ev["index"],
                                          finish=ev["reason"],
                                          request_id=request_id))
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, TimeoutError,
                OSError) as e:
            # client went away (or stalled out): give the pages back
            srv.frontend.cancel(stream.req_id)
            _log.info(json.dumps({"event": "stream_aborted",
                                  "req_id": stream.req_id,
                                  "request_id": request_id,
                                  "cause": type(e).__name__}))
        except RuntimeError as e:  # engine loop died mid-stream
            _log.warning(json.dumps({"event": "stream_failed",
                                     "req_id": stream.req_id,
                                     "request_id": request_id,
                                     "cause": str(e)}))

    def _respond_full(self, stream, chat, rid, prompt_tokens,
                      request_id=None):
        srv = self.owner
        try:
            results = stream.result(timeout=srv.stream_timeout_s)
        except TimeoutError as e:
            srv.frontend.cancel(stream.req_id)
            self._error(504, str(e), "timeout")
            return
        except RuntimeError as e:
            self._error(503, f"engine failed: {e}", "unavailable")
            return
        choices = []
        for i, r in enumerate(results):
            text = "".join(srv._piece(t) for t in r["tokens"])
            if chat:
                choices.append({"index": i,
                                "message": {"role": "assistant",
                                            "content": text},
                                "token_ids": r["tokens"],
                                "finish_reason": r["finish_reason"]})
            else:
                choices.append({"index": i, "text": text,
                                "token_ids": r["tokens"],
                                "finish_reason": r["finish_reason"]})
        completion = sum(len(r["tokens"]) for r in results)
        out = {
            "id": rid,
            "object": "chat.completion" if chat else "text_completion",
            "model": srv.model_name,
            "choices": choices,
            "usage": {"prompt_tokens": prompt_tokens,
                      "completion_tokens": completion,
                      "total_tokens": prompt_tokens + completion}}
        extra = ()
        if request_id is not None:
            out["request_id"] = request_id
            extra = (("X-Request-Id", request_id),)
        self._json(200, out, extra_headers=extra)
