"""Paged attention: attend a query block over K/V read through a page
table (PAPERS.md "Ragged Paged Attention" — the TPU serving kernel
shape; reference capability: vLLM PagedAttention).

Two paths, selected by ``PADDLE_TPU_PAGED_KERNEL``:

- default — a pure jax/lax GATHER reference: pages are gathered into a
  contiguous [B, P·page_size, KV, D] view and attention runs exactly
  like models/generation.py::cached_attention (same einsums, same f32
  accumulation, same absolute-position mask), so it is CPU-testable and
  oracle-comparable against the contiguous static-cache path to 1e-5.
- ``PADDLE_TPU_PAGED_KERNEL=1`` — ONE unified ragged Pallas kernel
  (round 18, replacing the decode-only S=1 stub): the grid streams over
  packed query TOKENS, each grid cell resolving its own lane's
  (page_table row, context_len, absolute position), so decode lanes
  (q=1), prefill chunks, and speculative-verify bursts (q=k+1) all run
  through the same program. Validated in INTERPRET MODE ONLY this round
  (CLAUDE.md: no first-time Mosaic compiles while the chip grant is
  wedged). It streams pages with an online-softmax accumulator — the
  structure the real kernel needs — but reads the whole page pool per
  grid cell, which a Mosaic build must replace with per-page DMA to
  respect the O(block) VMEM invariant before it can be compile-gated.

:func:`ragged_paged_attention` is the token-packed entry point
(PAPERS.md "Ragged Paged Attention"): ``q [T, H, D]`` carries the
concatenated query tokens of L lanes, each lane with its own
``(query_len, context_len, q_offset)``; padding tokens (beyond
``sum(query_lens)``) attend position 0 of the last lane — garbage but
NaN-free, masked out by the caller. :func:`paged_attention` keeps the
rectangular [B, S] surface and, under the kernel gate, routes through
the SAME ragged kernel (row b = one lane of query_len S) — one gated
kernel, not two.

Both paths accept GQA natively (query heads grouped over KV heads, no
materialized head repeat) and a Mistral-style sliding ``window``.

int8 quantized cache (round 15): ``k_pages``/``v_pages`` may each be a
``(codes int8 [NP, PS, KV, D], scales f32 [NP, PS, KV])`` tuple — the
:class:`~.kv_cache.PagedKVCache` ``dtype="int8"`` layout. Dequant is
inline, the generation-path recipe (``cached_attention``): the score
einsum reads the CODES and the per-slot scales fold in post-dot
(``s_t·(codes_t·q) == (s_t·codes_t)·q``), V scales fold into the
softmax probabilities — no dequantized f32 copy of the pool is ever
materialized, so the per-step HBM stream is the code bytes.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

__all__ = ["paged_attention", "paged_attention_ref",
           "ragged_paged_attention", "quantize_q8"]


def quantize_q8(x):
    """Per-(slot, kv-head) absmax int8 quantization for the paged
    cache's append path: ``[..., KV, D]`` → ``(codes int8 [..., KV, D],
    scales f32 [..., KV])``. Deterministic (pure rounding), so
    preemption recompute and failover re-prefill regenerate
    bit-identical pages — the same recipe generation.py proved at
    delta-NLL ~1e-3 (BENCH_kv8_quality.json)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    s = jnp.maximum(amax / 127.0, 1e-8)
    codes = jnp.clip(jnp.round(xf / s[..., None]), -127,
                     127).astype(jnp.int8)
    return codes, s


def paged_attention(q, k_pages, v_pages, page_table, context_lens,
                    q_offsets, *, scale, window=None, spmd=False):
    """q [B,S,H,D]; k_pages/v_pages [NP, page_size, KV, D];
    page_table [B,P] int32 (pad = scratch page 0); context_lens [B]
    int32 — valid K tokens per row INCLUDING any just scattered;
    q_offsets [B] int32 — absolute position of each row's first query.
    Returns [B,S,H,D] in q.dtype.  ``spmd=True`` (the tensor-parallel
    step) forces the jnp gather path regardless of
    ``PADDLE_TPU_PAGED_KERNEL`` — ``pallas_call`` has no GSPMD
    partitioning rule, so tracing the kernel into a mesh program
    would be silent wrongness; the engine logs + counts the fallback.
    """
    if not spmd and os.environ.get("PADDLE_TPU_PAGED_KERNEL") == "1":
        # rectangular [B, S] is the degenerate ragged batch: row b is a
        # lane of query_len S — expand per token and run the ONE kernel
        b, s, nh, d = q.shape
        pt_tok = jnp.repeat(page_table, s, axis=0)
        cl_tok = jnp.repeat(context_lens, s)
        pos_tok = (q_offsets[:, None].astype(jnp.int32)
                   + jnp.arange(s, dtype=jnp.int32)[None, :]).reshape(-1)
        out = _ragged_attention_kernel(
            q.reshape(b * s, nh, d), k_pages, v_pages, pt_tok, cl_tok,
            pos_tok, scale=scale, window=window)
        return out.reshape(b, s, nh, d)
    return paged_attention_ref(q, k_pages, v_pages, page_table,
                               context_lens, q_offsets, scale=scale,
                               window=window)


def _token_lanes(query_lens, q_offsets, t):
    """Token-packed lane resolution: map packed query index -> (lane,
    absolute position). Padding tokens (index >= sum(query_lens)) clamp
    to the last lane at position 0 — their row only needs to be NaN-free
    (every lane keeps context_len >= 1 by the engine's padding
    contract), the caller discards the output."""
    ql = query_lens.astype(jnp.int32)
    ends = jnp.cumsum(ql)
    tok = jnp.arange(t, dtype=jnp.int32)
    lane = jnp.searchsorted(ends, tok, side="right").astype(jnp.int32)
    lane = jnp.minimum(lane, ql.shape[0] - 1)
    pos = q_offsets[lane].astype(jnp.int32) + tok - (ends - ql)[lane]
    pos = jnp.where(tok < ends[-1], pos, 0)
    return lane, pos


def ragged_paged_attention(q, k_pages, v_pages, page_table,
                           context_lens, query_lens, q_offsets, *,
                           scale, window=None, spmd=False):
    """Token-packed mixed-batch paged attention (one program for
    decode + prefill + verify lanes).

    q [T, H, D] — lane-major packed query tokens (lane 0's query_lens[0]
    tokens, then lane 1's, ...; trailing padding up to T);
    page_table [L, P] int32 per LANE (pad = scratch page 0);
    context_lens [L] int32 — valid K tokens per lane INCLUDING any just
    scattered (>= 1 even for padded lanes); query_lens [L] int32 (0 for
    padded lanes); q_offsets [L] int32 — absolute position of each
    lane's first query token. Returns [T, H, D] in q.dtype; padding
    rows are garbage but finite.

    Default path delegates to :func:`paged_attention_ref` with one row
    per token (the oracle — identical einsums/mask, so GQA, sliding
    window, and the int8 (codes, scales) tuple layout are inherited);
    ``PADDLE_TPU_PAGED_KERNEL=1`` runs the unified interpret-mode
    Pallas kernel on the same per-token expansion; ``spmd=True``
    (tensor-parallel step) overrides the knob and stays on the ref
    path — no Pallas under GSPMD.
    """
    t = q.shape[0]
    lane, pos = _token_lanes(query_lens, q_offsets, t)
    pt_tok = page_table[lane]
    cl_tok = context_lens[lane].astype(jnp.int32)
    if not spmd and os.environ.get("PADDLE_TPU_PAGED_KERNEL") == "1":
        return _ragged_attention_kernel(q, k_pages, v_pages, pt_tok,
                                        cl_tok, pos, scale=scale,
                                        window=window)
    return paged_attention_ref(q[:, None], k_pages, v_pages, pt_tok,
                               cl_tok, pos, scale=scale,
                               window=window)[:, 0]


def paged_attention_ref(q, k_pages, v_pages, page_table, context_lens,
                        q_offsets, *, scale, window=None):
    """Gather-based reference path (see module docstring)."""
    b, s, nh, d = q.shape
    k_quant = isinstance(k_pages, tuple)
    kp = k_pages[0] if k_quant else k_pages
    _, ps, nkv, _ = kp.shape
    p = page_table.shape[1]
    t = p * ps
    g = nh // nkv
    qg = q.reshape(b, s, nkv, g, d).astype(jnp.float32)
    if k_quant:
        # int8 pages: gather the codes, score in int8-as-f32, fold the
        # K scales in post-dot on the [T] axis and the V scales into
        # the probabilities — cached_attention's algebra over a page
        # table
        kq, ks = k_pages
        vq, vs = v_pages
        kg = kq[page_table].reshape(b, t, nkv, d)
        ksg = ks[page_table].reshape(b, t, nkv)            # [B,T,KV]
        sc = jnp.einsum("bskgd,btkd->bkgst", qg,
                        kg.astype(jnp.float32)) * scale
        sc = sc * jnp.transpose(ksg, (0, 2, 1))[:, :, None, None, :]
    else:
        # [B,P] pages -> contiguous [B,T,KV,D] logical view
        kg = k_pages[page_table].reshape(b, t, nkv, d)
        vg = v_pages[page_table].reshape(b, t, nkv, d)
        sc = jnp.einsum("bskgd,btkd->bkgst", qg,
                        kg.astype(jnp.float32)) * scale
    qpos = q_offsets[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    kpos = jnp.arange(t, dtype=jnp.int32)
    mask = kpos[None, None, :] <= qpos[:, :, None]            # [B,S,T]
    mask = mask & (kpos[None, None, :] < context_lens[:, None, None])
    if window:  # 0/None both disable (all-False band would NaN softmax)
        mask = mask & (kpos[None, None, :] > qpos[:, :, None]
                       - int(window))
    sc = jnp.where(mask[:, None, None], sc, -jnp.inf)
    pr = jax.nn.softmax(sc, axis=-1)
    if k_quant:
        vsg = vs[page_table].reshape(b, t, nkv)
        pr = pr * jnp.transpose(vsg, (0, 2, 1))[:, :, None, None, :]
        out = jnp.einsum("bkgst,btkd->bskgd", pr,
                         vq[page_table].reshape(b, t, nkv, d)
                         .astype(jnp.float32))
    else:
        out = jnp.einsum("bkgst,btkd->bskgd", pr,
                         vg.astype(jnp.float32))
    return out.reshape(b, s, nh, d).astype(q.dtype)


def _ragged_attention_kernel(q, k_pages, v_pages, pt_tok, cl_tok,
                             pos_tok, *, scale, window=None):
    """Unified ragged Pallas kernel, interpret mode only (see module
    docstring). q [T, H, D] packed tokens; pt_tok [T, P] / cl_tok [T] /
    pos_tok [T] are the PER-TOKEN lane rows (gathered by the caller, so
    the grid cell's BlockSpecs stay O(1)-indexed). Grid over tokens —
    decode, prefill-chunk, and verify tokens are indistinguishable
    cells; one online-softmax pass over the page list per cell. int8
    caches add the scale pools as two extra operands; dequant happens
    per page inside the streaming loop (the codes and the scale row of
    ONE page at a time — O(page) VMEM, the shape a Mosaic build
    keeps)."""
    from jax.experimental import pallas as pl

    t, nh, d = q.shape
    quant = isinstance(k_pages, tuple)
    if quant:
        (k_pages, k_scales), (v_pages, v_scales) = k_pages, v_pages
    np_, ps, nkv, _ = k_pages.shape
    p = pt_tok.shape[1]
    g = nh // nkv
    win = int(window) if window else 0

    def kernel(pt_ref, cl_ref, qo_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref = rest
        else:
            (o_ref,) = rest
        pt = pt_ref[...][0]                       # [P]
        cl = cl_ref[...][0]
        qpos = qo_ref[...][0]
        qh = q_ref[...][0].astype(jnp.float32).reshape(nkv, g, d)
        # interpret-mode full read; a Mosaic build must DMA per page
        k_all = k_ref[...]
        v_all = v_ref[...]

        def body(i, carry):
            m, l, acc = carry
            page = pt[i]
            kb = jax.lax.dynamic_index_in_dim(
                k_all, page, 0, keepdims=False).astype(jnp.float32)
            vb = jax.lax.dynamic_index_in_dim(
                v_all, page, 0, keepdims=False).astype(jnp.float32)
            if quant:
                ksb = jax.lax.dynamic_index_in_dim(
                    ks_ref[...], page, 0, keepdims=False)    # [PS,KV]
                vsb = jax.lax.dynamic_index_in_dim(
                    vs_ref[...], page, 0, keepdims=False)
                kb = kb * ksb[..., None]
                vb = vb * vsb[..., None]
            sc = jnp.einsum("kgd,tkd->kgt", qh, kb) * scale  # [KV,g,PS]
            tpos = i * ps + jnp.arange(ps, dtype=jnp.int32)
            ok = (tpos <= qpos) & (tpos < cl)
            if win:
                ok = ok & (tpos > qpos - win)
            sc = jnp.where(ok[None, None, :], sc, -jnp.inf)
            m2 = jnp.maximum(m, sc.max(-1))
            # dead blocks (all masked) keep the accumulator untouched:
            # exp guards avoid -inf minus -inf NaNs
            alive = jnp.isfinite(m2)
            alpha = jnp.where(alive, jnp.exp(m - m2), 1.0)
            pexp = jnp.where(alive[..., None],
                             jnp.exp(sc - m2[..., None]), 0.0)
            l2 = l * alpha + pexp.sum(-1)
            acc2 = acc * alpha[..., None] + \
                jnp.einsum("kgt,tkd->kgd", pexp, vb)
            return m2, l2, acc2

        m0 = jnp.full((nkv, g), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((nkv, g), jnp.float32)
        a0 = jnp.zeros((nkv, g, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, p, body, (m0, l0, a0))
        out = acc / jnp.maximum(l, 1e-20)[..., None]
        o_ref[...] = out.reshape(1, nh, d).astype(o_ref.dtype)

    full_k = pl.BlockSpec(k_pages.shape, lambda i: (0, 0, 0, 0))
    in_specs = [pl.BlockSpec((1, p), lambda i: (i, 0)),
                pl.BlockSpec((1,), lambda i: (i,)),
                pl.BlockSpec((1,), lambda i: (i,)),
                pl.BlockSpec((1, nh, d), lambda i: (i, 0, 0)),
                full_k, full_k]
    operands = [pt_tok, cl_tok, pos_tok, q, k_pages, v_pages]
    if quant:
        full_s = pl.BlockSpec(k_scales.shape, lambda i: (0, 0, 0))
        in_specs += [full_s, full_s]
        operands += [k_scales, v_scales]
    out = pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nh, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((t, nh, d), q.dtype),
        interpret=True,
    )(*operands)
    return out
