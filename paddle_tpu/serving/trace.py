"""Serving-wide request tracing + the per-engine flight recorder.

The serving stack spans admission, chunked prefill, fused-sampling
decode, speculative rounds, preemption recompute, prefix-cache hits,
page migration, failover splicing and autoscaling — this module is the
layer that can SEE the other eight.  Reference capability:
paddle.profiler's RecordEvent spans + chrome export (SURVEY.md §5.1 —
`paddle_tpu.profiler` mirrors the API; serving now emits into the same
chrome://tracing event shape), and the per-phase TTFT/TPOT latency
decompositions the TPU serving literature reasons in (PAPERS.md
Gemma-on-TPU, Ragged Paged Attention step accounting).

Three pieces:

- **Request spans** (:class:`RequestTrace`): every request accumulates
  typed spans — ``queued``, ``prefill_chunk``, ``recompute``,
  ``decode_round``, ``spec_round`` (attrs carry proposed/accepted),
  ``preempted``, ``prefix_hit``, ``migration`` (attrs carry pages),
  ``failover_splice``, ``held`` — with MONOTONIC-clock start/dur and a
  small attr dict.  Emission is an append to a per-request list under
  the existing engine/frontend lock (no new locking — the graftlint
  engine-lock discipline is unchanged), capped per request
  (``PADDLE_TPU_SERVING_TRACE_SPANS``, default 512; overflow is
  COUNTED, never stored).  Contiguous decode/spec rounds COALESCE into
  one run-span (``rounds``/``accepted`` attrs accumulate; any other
  span type breaks the run) — per-token span dicts measurably drag the
  CPU decode marginal, coalesced runs are free, and the timeline keeps
  its phase structure exactly.  Each trace records a
  ``(wall, monotonic)`` anchor pair at creation so serialized spans
  carry ``t0_unix`` — what lets a router stitch spans from SEPARATE
  processes (HTTP replicas have unrelated perf_counter origins) into
  one timeline.  Trace context rides the existing ``X-Request-Id``
  plumbing (``Request.request_id``) across HTTPReplica hops and the
  pagewire export meta, so a disaggregated request's prefill-replica
  spans and decode-replica spans stitch into ONE timeline at the
  router.

- **Flight recorder** (:class:`FlightRecorder`): a fixed-size ring of
  recent engine events (``PADDLE_TPU_SERVING_TRACE_FLIGHT``, default
  256) — step begin (batch composition) / step end (wall time),
  admission, shed, preemption, fault injection, drain, loop error;
  round 17 adds ``chaos`` (injected fault firings), ``held_expired``
  (deadline-released held pages) and, on the router ring,
  ``breaker_open``.  The ring is dumped to the structured log on loop
  failure, on fault ESCALATION, and on a circuit-breaker open, so the
  round-9/11 failure classes are post-mortem-able without a rerun.

- **Chrome export**: completed timelines convert to chrome://tracing
  JSON via the same event dict shape ``paddle_tpu.profiler`` emits
  (``{"name", "ph": "X", "ts", "dur", "pid", "tid"}`` — microseconds),
  one pid per replica, one tid per request lane, so
  ``bench_serving.py --trace-out`` drops a trace
  ``paddle_tpu.profiler.load_profiler_result`` can re-open.

Overhead contract: tracing is ALWAYS ON by default and must stay in
the noise of the decode marginal (<3%, the BENCH_serving_trace gate);
``PADDLE_TPU_SERVING_TRACE=0`` disables span/flight emission entirely
(the overhead bench's control arm).  Nothing in this module touches a
device or takes a lock: callers emit under the lock they already hold.
"""
from __future__ import annotations

import json
import os
import time
from collections import deque

__all__ = ["FlightRecorder", "RequestTrace", "ServingTrace",
           "chrome_trace_events", "export_chrome_trace",
           "load_trace_export"]

TRACE_ENV = "PADDLE_TPU_SERVING_TRACE"
TRACE_SPANS_ENV = "PADDLE_TPU_SERVING_TRACE_SPANS"
TRACE_FLIGHT_ENV = "PADDLE_TPU_SERVING_TRACE_FLIGHT"
# round 19 (fleet control plane): completed timelines append to a
# size-capped JSONL file the moment they finish, so a fleet-harness run
# leaves a post-mortem artifact even after the process that owned the
# trace store dies (the OTLP follow-on's minimal file-based form)
TRACE_EXPORT_ENV = "PADDLE_TPU_SERVING_TRACE_EXPORT"
TRACE_EXPORT_MB_ENV = "PADDLE_TPU_SERVING_TRACE_EXPORT_MB"

# completed request traces retained per engine (oldest evicted): bounds
# the store under sustained traffic without a knob per dimension
_KEEP_FINISHED = 1024

# phase attribution for the finish-log breakdown (queue/prefill/decode/
# stall); span types not listed (prefix_hit, preempted, migration, …)
# are markers, not time owners
_QUEUE_SPANS = ("queued",)
_PREFILL_SPANS = ("prefill_chunk",)
# ragged_round is the unified ragged step's plain-decode span (round
# 22): same coalescing run_span shape as decode_round, emitted by
# engine._ragged_step so phase attribution survives the one-dispatch
# refactor (verify lanes keep spec_round, the prefill lane keeps
# prefill_chunk/recompute)
_DECODE_SPANS = ("decode_round", "spec_round", "ragged_round")
_STALL_SPANS = ("recompute",)


def trace_enabled():
    """The always-on default: only an explicit =0/off disables."""
    return os.environ.get(TRACE_ENV, "1") not in ("0", "off", "false")


def span_cap():
    try:
        return max(8, int(os.environ.get(TRACE_SPANS_ENV, "512")))
    except ValueError:
        return 512


def flight_cap():
    try:
        return max(16, int(os.environ.get(TRACE_FLIGHT_ENV, "256")))
    except ValueError:
        return 256


def export_cap_bytes():
    try:
        mb = float(os.environ.get(TRACE_EXPORT_MB_ENV, "64") or 64)
    except ValueError:
        mb = 64.0
    return int(mb * 1024 * 1024)


class RequestTrace:
    """One request's span timeline.  Append-only, capped; overflow is
    counted in ``dropped`` (the timeline keeps its HEAD — the phase
    structure — and sheds the repetitive decode tail)."""

    __slots__ = ("req_id", "request_id", "spans", "dropped", "cap",
                 "anchor_wall", "anchor_mono", "marks")

    def __init__(self, req_id, request_id=None, cap=None,
                 anchor=None):
        self.req_id = req_id
        self.request_id = request_id
        self.cap = span_cap() if cap is None else int(cap)
        self.spans: list[dict] = []
        self.dropped = 0
        # (wall, monotonic) pair: spans store monotonic t0; export maps
        # to wall so cross-process timelines share a clock
        self.anchor_wall, self.anchor_mono = anchor or (
            time.time(), time.perf_counter())
        self.marks: dict = {}  # open-span bookkeeping (queued/held t0)

    def add(self, name, t0, dur=0.0, **attrs):
        if len(self.spans) >= self.cap:
            self.dropped += 1
            return
        span = {"name": name, "t0": float(t0), "dur": float(dur)}
        if attrs:
            span["attrs"] = attrs
        self.spans.append(span)

    def add_run(self, name, t0, dur, batch=None, **counters):
        """Coalescing append for the per-round span types
        (``decode_round``/``spec_round``): a CONTIGUOUS run of rounds
        extends the previous span in place — ``rounds`` counts them,
        counter attrs (accepted/proposed/…) accumulate, ``batch``
        tracks the latest composition — instead of allocating one span
        per token.  This is the overhead contract's load-bearing move:
        per-token span dicts cost ~3% of the CPU decode marginal at
        toy scale (measured, BENCH_serving_trace), coalesced runs are
        noise.  Any differently-named span (preempted, migration,
        prefill_chunk, …) breaks the run, so the timeline keeps its
        phase structure exactly; per-step composition detail stays in
        the flight ring."""
        spans = self.spans
        if spans:
            last = spans[-1]
            if last["name"] == name:
                last["dur"] = float(t0) + float(dur) - last["t0"]
                a = last["attrs"]
                a["rounds"] += 1
                if batch is not None:
                    a["batch"] = batch
                for k, v in counters.items():
                    a[k] = a.get(k, 0) + v
                return
        attrs = {"rounds": 1}
        if batch is not None:
            attrs["batch"] = batch
        attrs.update(counters)
        self.add(name, t0, dur, **attrs)

    def to_wall(self, t0):
        return self.anchor_wall + (float(t0) - self.anchor_mono)

    def total(self, names):
        return sum(s["dur"] for s in self.spans if s["name"] in names)

    def phase_breakdown(self):
        """The finish-log latency decomposition: wall seconds per
        phase, derived purely from the accumulated spans."""
        return {
            "queue_s": round(self.total(_QUEUE_SPANS), 6),
            "prefill_s": round(self.total(_PREFILL_SPANS), 6),
            "decode_s": round(self.total(_DECODE_SPANS), 6),
            "stall_s": round(self.total(_STALL_SPANS), 6),
        }

    def to_json(self):
        spans = []
        for s in self.spans:
            out = dict(s, t0_unix=self.to_wall(s["t0"]))
            spans.append(out)
        return {"req_id": self.req_id, "request_id": self.request_id,
                "spans": spans, "dropped": self.dropped}


class FlightRecorder:
    """Fixed-size ring of recent engine events.  ``record`` stamps each
    event with wall time; ``dump`` returns the ring oldest-first."""

    def __init__(self, cap=None):
        self._ring: deque = deque(maxlen=(flight_cap() if cap is None
                                          else int(cap)))
        self.recorded = 0

    @property
    def cap(self):
        return self._ring.maxlen

    def record(self, kind, **fields):
        self.recorded += 1
        ev = {"t_unix": time.time(), "kind": kind}
        ev.update(fields)
        self._ring.append(ev)

    def dump(self):
        return list(self._ring)


class ServingTrace:
    """Per-engine trace store: request timelines + the flight ring.

    All mutation happens from whichever thread drives the engine —
    i.e. under the front-end lock (or a single-threaded direct driver),
    exactly like the metrics objects; this class adds NO locking of its
    own.  ``enabled`` is resolved once at construction (engines are
    built per config; the overhead bench builds its control engine
    under PADDLE_TPU_SERVING_TRACE=0)."""

    def __init__(self, span_cap_=None, flight_cap_=None, enabled=None,
                 export_path=None):
        self.enabled = trace_enabled() if enabled is None else enabled
        self._span_cap = span_cap_
        self.flight = FlightRecorder(flight_cap_)
        self._requests: dict = {}          # req_id -> RequestTrace
        self._by_request_id: dict = {}     # request_id -> [req_id, ...]
        self._done: deque = deque()        # finished req_ids, FIFO
        # one anchor per store: every request trace shares it, so spans
        # from the same engine are mutually ordered exactly
        self._anchor = (time.time(), time.perf_counter())
        # file-based trace export (round 19): each finished timeline
        # appends its chrome-trace records as JSONL lines, flushed per
        # line — the artifact survives the owner's death.  Size-capped;
        # strictly best-effort (an unwritable path never fails serving)
        if export_path is None:
            export_path = os.environ.get(TRACE_EXPORT_ENV) or None
        self.export_path = export_path
        self._export_file = None
        self._export_bytes = 0
        self.export_written = 0     # records written
        self.export_dropped = 0     # records dropped at the size cap
        if self.export_path:
            try:
                self._export_bytes = os.path.getsize(self.export_path)
            except OSError:
                self._export_bytes = 0

    # -- request lifecycle -------------------------------------------------
    def begin(self, req_id, request_id=None):
        if not self.enabled or req_id in self._requests:
            return self._requests.get(req_id)
        tr = RequestTrace(req_id, request_id, cap=self._span_cap,
                          anchor=self._anchor)
        self._requests[req_id] = tr
        if request_id is not None:
            self._by_request_id.setdefault(str(request_id),
                                           []).append(req_id)
        return tr

    def get(self, req_id):
        return self._requests.get(req_id)

    def span(self, req_id, name, t0, dur=0.0, **attrs):
        tr = self._requests.get(req_id)
        if tr is not None:
            tr.add(name, t0, dur, **attrs)

    def run_span(self, req_id, name, t0, dur, batch=None, **counters):
        tr = self._requests.get(req_id)
        if tr is not None:
            tr.add_run(name, t0, dur, batch=batch, **counters)

    def mark(self, req_id, key, value):
        tr = self._requests.get(req_id)
        if tr is not None:
            tr.marks[key] = value

    def pop_mark(self, req_id, key):
        tr = self._requests.get(req_id)
        if tr is None:
            return None
        return tr.marks.pop(key, None)

    def finish(self, req_id):
        """Mark a request's timeline complete; evict the oldest
        finished traces beyond the retention bound.  Returns the trace
        (for the finish-log phase breakdown)."""
        tr = self._requests.get(req_id)
        if tr is None:
            return None
        if self.export_path:
            self._export(tr)
        self._done.append(req_id)
        while len(self._done) > _KEEP_FINISHED:
            old = self._done.popleft()
            dead = self._requests.pop(old, None)
            if dead is not None and dead.request_id is not None:
                ids = self._by_request_id.get(str(dead.request_id))
                if ids is not None:
                    try:
                        ids.remove(old)
                    except ValueError:
                        pass
                    if not ids:
                        del self._by_request_id[str(dead.request_id)]
        return tr

    # -- file export (round 19) --------------------------------------------
    def _export(self, tr):
        """Append one finished timeline's chrome-trace records as JSONL
        lines (one ``ph:"X"`` event per line, the
        :func:`chrome_trace_events` shape, so ``{"traceEvents":
        load_trace_export(path)}`` opens in chrome://tracing).  Caller
        holds the engine/frontend lock (finish() runs under it).  Lines
        are flushed immediately — the file is the post-mortem artifact
        a dead router leaves behind.  Failures are swallowed: export is
        an observability tap, never a serving dependency."""
        try:
            events = chrome_trace_events([tr.to_json()])
            payload = "".join(
                json.dumps(ev, separators=(",", ":")) + "\n"
                for ev in events)
            data = payload.encode()
            if self._export_bytes + len(data) > export_cap_bytes():
                self.export_dropped += 1
                return
            if self._export_file is None:
                self._export_file = open(self.export_path, "ab")
            self._export_file.write(data)
            self._export_file.flush()
            self._export_bytes += len(data)
            self.export_written += 1
        except (OSError, ValueError, TypeError):
            self.export_dropped += 1

    # -- query -------------------------------------------------------------
    def timelines(self, request_id=None, req_id=None):
        """Serialized timelines.  ``request_id`` (the X-Request-Id
        string) may match several engine requests (forks, re-
        submissions); ``req_id`` addresses exactly one; neither returns
        every retained timeline."""
        if req_id is not None:
            tr = self._requests.get(req_id)
            return [tr.to_json()] if tr is not None else []
        if request_id is not None:
            ids = self._by_request_id.get(str(request_id), [])
            return [self._requests[r].to_json() for r in ids
                    if r in self._requests]
        return [tr.to_json() for tr in self._requests.values()]


# -- chrome://tracing export ------------------------------------------------

def chrome_trace_events(timelines, pid=0, pid_name=None):
    """Convert serialized timelines (``RequestTrace.to_json`` dicts,
    each span carrying ``t0_unix``) into chrome trace events — the SAME
    event shape ``paddle_tpu.profiler`` emits (``ph: "X"``, ts/dur in
    microseconds): one ``pid`` per replica, one ``tid`` per request
    lane, plus thread-name metadata so the lanes are labelled."""
    events = []
    for tl in timelines:
        tid = tl["req_id"] if isinstance(tl["req_id"], int) \
            else abs(hash(tl["req_id"])) % (1 << 31)
        label = (f"req {tl['req_id']}"
                 + (f" [{tl['request_id']}]" if tl.get("request_id")
                    else ""))
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": label}})
        for s in tl["spans"]:
            events.append({
                "name": s["name"], "ph": "X",
                "ts": s["t0_unix"] * 1e6,
                "dur": max(s["dur"], 0.0) * 1e6,
                "pid": pid, "tid": tid,
                "args": dict(s.get("attrs", {}))})
    if pid_name is not None:
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": pid_name}})
    return events


def load_trace_export(path):
    """Read a ``PADDLE_TPU_SERVING_TRACE_EXPORT`` JSONL artifact back
    into a chrome event list.  A torn final line (the writer died
    mid-append) is skipped, not an error — the file exists precisely
    for post-mortems of processes that did not exit cleanly.  Wrap the
    result as ``{"traceEvents": events}`` to open it in
    chrome://tracing."""
    events = []
    with open(path, "rb") as f:
        for raw in f:
            if not raw.endswith(b"\n"):
                break  # torn tail: the writer died mid-line
            try:
                events.append(json.loads(raw))
            except ValueError:
                continue  # interleaved/garbled line: skip, keep reading
    return events


def export_chrome_trace(path, timelines_by_pid):
    """Write ``{"traceEvents": [...]}`` chrome JSON.
    ``timelines_by_pid``: iterable of ``(pid, pid_name, timelines)``.
    The file round-trips through
    ``paddle_tpu.profiler.load_profiler_result``."""
    events = []
    for pid, pid_name, timelines in timelines_by_pid:
        events.extend(chrome_trace_events(timelines, pid=pid,
                                          pid_name=pid_name))
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path
