"""Serving observability: counters, gauges and reservoir histograms,
exported as JSON for the bench harness (PERF.md convention: one JSON
artifact per measurement, banked the moment it lands) and as Prometheus
text exposition for the HTTP front-end's ``/metrics`` endpoint.

Host-side and allocation-light by design — metrics must never add a
device sync; the engine records values it already fetched.
"""
from __future__ import annotations

import json

import numpy as np

__all__ = ["Counter", "Gauge", "Histogram", "ServingMetrics"]


class Counter:
    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def export(self):
        return self.value


class Gauge:
    """A point-in-time value (queue depth, occupancy, batch size) —
    ``set()`` overwrites; the exposition shows the LAST value, unlike a
    Histogram which keeps the distribution."""

    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = float(v)

    def export(self):
        return self.value


class Histogram:
    """Bounded reservoir of samples; percentiles computed at export.
    Keeps the LAST `cap` samples (serving metrics care about recent
    behavior; a trace replay fits entirely)."""

    def __init__(self, cap=65536):
        self.cap = int(cap)
        self._samples: list[float] = []
        self.count = 0
        self.total = 0.0  # running sum over ALL samples (summary _sum)

    def record(self, v):
        self.count += 1
        self.total += float(v)
        self._samples.append(float(v))
        if len(self._samples) > self.cap:
            del self._samples[: len(self._samples) - self.cap]

    def percentile(self, p):
        """Percentile over the reservoir; None (never a raise) while no
        sample has been recorded — scrapes happen before traffic."""
        if not self._samples:
            return None
        return float(np.percentile(np.asarray(self._samples), p))

    def export(self):
        if not self._samples:
            return {"count": self.count, "mean": None, "p50": None,
                    "p99": None, "max": None}
        a = np.asarray(self._samples)
        return {"count": self.count,
                "mean": float(a.mean()),
                "p50": float(np.percentile(a, 50)),
                "p99": float(np.percentile(a, 99)),
                "max": float(a.max())}


class ServingMetrics:
    """The engine's counter/gauge/histogram set (names are the export
    keys and, prefixed, the Prometheus metric family names)."""

    def __init__(self):
        self.ttft_s = Histogram()             # arrival -> first token
        self.inter_token_s = Histogram()      # gap between tokens
        self.queue_depth = Histogram()        # waiting queue, per step
        self.batch_size = Histogram()         # decode lanes, per step
        self.page_occupancy = Histogram()     # used/allocatable, per step
        self.prefill_chunks = Counter()
        self.decode_steps = Counter()
        self.tokens_generated = Counter()
        self.requests_finished = Counter()
        self.preemptions = Counter()
        self.deadline_evictions = Counter()
        self.cow_copies = Counter()
        # front-end lifecycle (round 9)
        self.cancellations = Counter()        # cancel() calls that landed
        self.rejections = Counter()           # load-shed admissions (429)
        self.faults_injected = Counter()      # injected step faults
        # decode hot path (round 10)
        self.fetch_bytes = Counter()          # host<-device bytes/steps
        self.prefix_hit_pages = Counter()     # prompt pages served from
        self.prefix_miss_pages = Counter()    # the radix tree vs prefilled
        self.prefix_evictions = Counter()     # cached pages LRU-reclaimed
        # point-in-time gauges, refreshed per step and at /metrics scrape
        self.queue_depth_gauge = Gauge()
        self.page_occupancy_gauge = Gauge()
        self.running_gauge = Gauge()          # running decode batch size
        self.prefix_hit_rate = Gauge()        # hit/(hit+miss), cumulative
        self.cached_pages_gauge = Gauge()     # pages resident in the tree

    def export(self):
        return {name: m.export() for name, m in vars(self).items()}

    def to_json(self, **extra):
        return json.dumps({**self.export(), **extra})

    def to_prometheus(self, prefix="paddle_tpu_serving"):
        """Prometheus text exposition (format 0.0.4): counters and
        gauges as single samples, histograms as summaries with p50/p99
        quantiles plus _count/_sum. Empty histograms expose only
        _count/_sum (a quantile of no data is omitted, not NaN, so the
        text stays trivially parseable)."""
        lines = []
        for name, m in vars(self).items():
            full = f"{prefix}_{name}"
            if isinstance(m, Counter):
                lines += [f"# TYPE {full} counter", f"{full} {m.value}"]
            elif isinstance(m, Gauge):
                lines += [f"# TYPE {full} gauge", f"{full} {m.value}"]
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {full} summary")
                for q, p in ((0.5, 50), (0.99, 99)):
                    v = m.percentile(p)
                    if v is not None:
                        lines.append(f'{full}{{quantile="{q}"}} {v}')
                lines += [f"{full}_count {m.count}",
                          f"{full}_sum {m.total}"]
        return "\n".join(lines) + "\n"
