"""Serving observability: counters, gauges and reservoir histograms,
exported as JSON for the bench harness (PERF.md convention: one JSON
artifact per measurement, banked the moment it lands) and as Prometheus
text exposition for the HTTP front-end's ``/metrics`` endpoint.

Host-side and allocation-light by design — metrics must never add a
device sync; the engine records values it already fetched.
"""
from __future__ import annotations

import bisect
import json
import re

import numpy as np

__all__ = ["Counter", "Gauge", "Histogram", "LabeledCounter",
           "ServingMetrics", "merge_prometheus"]

# Prometheus histogram bucket bounds for serving latencies (seconds).
# TTFT and TPOT land here; the cumulative _bucket{le=...} exposition is
# what lets a scraper compute real quantiles across replicas (summary
# quantiles are NOT aggregatable — the router's merged /metrics needs
# buckets).
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Count-shaped buckets for the queue-depth histogram (requests, not
# seconds): powers of two so a scraper can see where admission backs up
# across replicas (round 16 — the tracing/observability PR)
DEPTH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


class Counter:
    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def export(self):
        return self.value


class Gauge:
    """A point-in-time value (queue depth, occupancy, batch size) —
    ``set()`` overwrites; the exposition shows the LAST value, unlike a
    Histogram which keeps the distribution."""

    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = float(v)

    def export(self):
        return self.value


class LabeledCounter:
    """A counter family with fixed label names — the router's
    ``routed_total{policy,replica}`` class of metric. Values are kept
    per label-value tuple; ``inc`` creates series on demand."""

    def __init__(self, *label_names):
        self.label_names = tuple(label_names)
        self._values: dict[tuple, int | float] = {}

    def inc(self, n=1, **labels):
        key = tuple(str(labels[k]) for k in self.label_names)
        self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels):
        key = tuple(str(labels[k]) for k in self.label_names)
        return self._values.get(key, 0)

    @property
    def total(self):
        return sum(self._values.values())

    def export(self):
        return {",".join(k): v for k, v in sorted(self._values.items())}

    def prom_lines(self, full):
        out = []
        for key, v in sorted(self._values.items()):
            labels = ",".join(f'{n}="{x}"'
                              for n, x in zip(self.label_names, key))
            out.append(f"{full}{{{labels}}} {v}")
        return out


class Histogram:
    """Bounded reservoir of samples; percentiles computed at export.
    Keeps the LAST `cap` samples (serving metrics care about recent
    behavior; a trace replay fits entirely).

    With ``buckets=`` (ascending upper bounds, seconds for latencies)
    the Prometheus exposition switches from a summary to a REAL
    histogram: cumulative ``_bucket{le=...}`` lines per the 0.0.4 text
    format, aggregatable across replicas. Bucket counts run over ALL
    samples (like ``count``/``total``), not just the reservoir."""

    def __init__(self, cap=65536, buckets=None):
        self.cap = int(cap)
        self._samples: list[float] = []
        self.count = 0
        self.total = 0.0  # running sum over ALL samples (summary _sum)
        self.buckets = tuple(buckets) if buckets else None
        if self.buckets and list(self.buckets) != sorted(self.buckets):
            raise ValueError("histogram buckets must be ascending")
        # per-bucket (non-cumulative) counts; the +Inf bucket is `count`
        self.bucket_counts = ([0] * len(self.buckets)
                              if self.buckets else None)

    def record(self, v):
        v = float(v)
        self.count += 1
        self.total += v
        if self.buckets is not None:
            i = bisect.bisect_left(self.buckets, v)
            if i < len(self.bucket_counts):
                self.bucket_counts[i] += 1
        self._samples.append(v)
        if len(self._samples) > self.cap:
            del self._samples[: len(self._samples) - self.cap]

    def percentile(self, p):
        """Percentile over the reservoir; None (never a raise) while no
        sample has been recorded — scrapes happen before traffic."""
        if not self._samples:
            return None
        return float(np.percentile(np.asarray(self._samples), p))

    def export(self):
        if not self._samples:
            return {"count": self.count, "mean": None, "p50": None,
                    "p99": None, "max": None}
        a = np.asarray(self._samples)
        return {"count": self.count,
                "mean": float(a.mean()),
                "p50": float(np.percentile(a, 50)),
                "p99": float(np.percentile(a, 99)),
                "max": float(a.max())}


class ServingMetrics:
    """The engine's counter/gauge/histogram set (names are the export
    keys and, prefixed, the Prometheus metric family names)."""

    def __init__(self):
        # TTFT/TPOT carry REAL Prometheus buckets (the router-merged
        # /metrics must stay aggregatable; summary quantiles are not)
        self.ttft_s = Histogram(buckets=LATENCY_BUCKETS)
        self.inter_token_s = Histogram(buckets=LATENCY_BUCKETS)
        # engine step wall time (round 16): the flight recorder keeps
        # the recent per-step detail; this keeps the aggregatable
        # distribution on /metrics
        self.step_duration_s = Histogram(buckets=LATENCY_BUCKETS)
        # bucketed (round 16) so the router-merged /metrics can show
        # WHERE admission backs up, not just the last gauge value
        self.queue_depth = Histogram(buckets=DEPTH_BUCKETS)
        self.batch_size = Histogram()         # decode lanes, per step
        self.page_occupancy = Histogram()     # used/allocatable, per step
        self.prefill_chunks = Counter()
        self.decode_steps = Counter()
        self.tokens_generated = Counter()
        self.requests_finished = Counter()
        self.preemptions = Counter()
        self.deadline_evictions = Counter()
        self.cow_copies = Counter()
        # front-end lifecycle (round 9)
        self.cancellations = Counter()        # cancel() calls that landed
        self.rejections = Counter()           # load-shed admissions (429)
        self.faults_injected = Counter()      # injected step faults
        # chaos/robustness layer (round 17)
        self.held_expired = Counter()         # held pages released on
        #                                       deadline expiry
        # speculative decoding (round 12)
        self.spec_rounds = Counter()          # draft-propose/verify rounds
        self.spec_draft_tokens = Counter()    # tokens the draft proposed
        self.spec_accepted_tokens = Counter()  # proposals verified+emitted
        self.spec_fallbacks = Counter()       # lanes demoted to plain
        # tensor-parallel SPMD serving (round 23)
        self.tp_kernel_fallbacks = Counter()  # Pallas kernel requests
        #                                       demoted to the jnp path
        #                                       (no GSPMD rule for
        #                                       pallas_call)
        # disaggregated prefill/decode (round 14)
        self.prefills_held = Counter()        # requests held "prefilled"
        self.pages_exported = Counter()       # KV pages shipped out
        self.pages_imported = Counter()       # KV pages spliced in
        self.adoptions = Counter()            # migrated-in requests
        # fleet prefix cache (round 18): router-driven prefix ships
        self.prefix_pages_exported = Counter()  # cached pages donated
        self.prefix_pages_imported = Counter()  # cached pages received
        self.prefix_drops = Counter()         # dedup drop_prefix pages
        # decode hot path (round 10)
        self.fetch_bytes = Counter()          # host<-device bytes/steps
        # round 22 (PR 18, unified ragged step): dispatch accounting —
        # every device dispatch / host fetch the engine issues, and the
        # number of distinct compiled program classes behind them. The
        # ragged path's contract is <= 2 classes and ONE dispatch + ONE
        # fetch per mixed prefill+decode step.
        self.step_dispatches = Counter()      # device dispatches issued
        self.step_fetches = Counter()         # host<-device fetches
        self.step_program_classes = Gauge()   # distinct compiled classes
        self.prefix_hit_pages = Counter()     # prompt pages served from
        self.prefix_miss_pages = Counter()    # the radix tree vs prefilled
        self.prefix_evictions = Counter()     # cached pages LRU-reclaimed
        # point-in-time gauges, refreshed per step and at /metrics scrape
        self.queue_depth_gauge = Gauge()
        self.page_occupancy_gauge = Gauge()
        self.running_gauge = Gauge()          # running decode batch size
        self.prefix_hit_rate = Gauge()        # hit/(hit+miss), cumulative
        self.cached_pages_gauge = Gauge()     # pages resident in the tree
        self.spec_acceptance_rate = Gauge()   # accepted/proposed, cumul.
        # quantized serving (round 15): honest per-page byte cost incl.
        # int8 scale rows — what the hbm_budget sizing divides by
        self.kv_page_bytes = Gauge()
        # hierarchical KV tiers (round 20): host/disk spill + restore
        self.tier_spill_pages = Counter()     # pages landed in the tier
        self.tier_spill_dropped = Counter()   # spills shed/failed
        self.tier_restore_pages = Counter()   # pages restored to device
        self.tier_restore_hits = Counter()    # restores that moved pages
        self.tier_restore_misses = Counter()  # probes the tier missed
        self.tier_corrupt_dropped = Counter()  # CRC-failed entries purged
        self.tier_spill_s = Histogram(buckets=LATENCY_BUCKETS)
        self.tier_restore_s = Histogram(buckets=LATENCY_BUCKETS)
        self.tier_restore_hit_rate = Gauge()  # hits/(hits+misses), cumul.
        self.host_pool_pages = Gauge()        # RAM-tier resident pages
        self.host_pool_bytes = Gauge()
        self.disk_pool_pages = Gauge()        # disk-tier resident pages
        # versioned live weight deployment (round 21): swap counts +
        # per-swap quiesce latency (lock-held window), and the version
        # each weight set is serving (what /healthz advertises — the
        # router's version-pin skew guard reads the same numbers)
        self.weight_swaps = Counter()         # set_weights that landed
        self.weight_swap_rejects = Counter()  # torn/mismatched payloads
        self.weight_swap_s = Histogram(buckets=LATENCY_BUCKETS)
        self.weight_version_target = Gauge()
        self.weight_version_draft = Gauge()
        self.distill_pairs = Counter()        # verify pairs logged

    def export(self):
        return {name: m.export() for name, m in vars(self).items()}

    def to_json(self, **extra):
        return json.dumps({**self.export(), **extra})

    def to_prometheus(self, prefix="paddle_tpu_serving"):
        """Prometheus text exposition (format 0.0.4): counters and
        gauges as single samples; bucketed histograms (TTFT/TPOT) as
        REAL histograms with cumulative ``_bucket{le=...}`` lines plus
        ``le="+Inf"``; bucket-less histograms as summaries with p50/p99
        quantiles. Empty summaries expose only _count/_sum (a quantile
        of no data is omitted, not NaN, so the text stays trivially
        parseable)."""
        lines = []
        for name, m in vars(self).items():
            full = f"{prefix}_{name}"
            if isinstance(m, Counter):
                lines += [f"# TYPE {full} counter", f"{full} {m.value}"]
            elif isinstance(m, LabeledCounter):
                lines.append(f"# TYPE {full} counter")
                lines += m.prom_lines(full)
            elif isinstance(m, Gauge):
                lines += [f"# TYPE {full} gauge", f"{full} {m.value}"]
            elif isinstance(m, Histogram) and m.buckets:
                lines.append(f"# TYPE {full} histogram")
                acc = 0
                for bound, c in zip(m.buckets, m.bucket_counts):
                    acc += c
                    lines.append(
                        f'{full}_bucket{{le="{bound:g}"}} {acc}')
                lines += [f'{full}_bucket{{le="+Inf"}} {m.count}',
                          f"{full}_count {m.count}",
                          f"{full}_sum {m.total}"]
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {full} summary")
                for q, p in ((0.5, 50), (0.99, 99)):
                    v = m.percentile(p)
                    if v is not None:
                        lines.append(f'{full}{{quantile="{q}"}} {v}')
                lines += [f"{full}_count {m.count}",
                          f"{full}_sum {m.total}"]
        return "\n".join(lines) + "\n"


# -- multi-replica merge (router /metrics) ----------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (.*)$")


def _label_sample(line, key, value):
    """Inject ``key="value"`` into one exposition sample line."""
    m = _SAMPLE_RE.match(line)
    if m is None:  # pragma: no cover - we only feed our own output
        return line
    name, labels, val = m.groups()
    tag = f'{key}="{value}"'
    if labels:
        return f"{name}{{{tag},{labels[1:-1]}}} {val}"
    return f"{name}{{{tag}}} {val}"


def merge_prometheus(parts, label="replica"):
    """Merge several Prometheus expositions into one, tagging every
    sample with ``label="<value>"`` and grouping families (one # TYPE
    line per family, all its samples together — the 0.0.4 grouping
    rule). ``parts`` is an iterable of ``(label_value, text)``; a
    ``label_value`` of None passes the part through UNLABELLED (the
    router's own families carry their labels already). Texts must be
    TYPE-then-samples shaped, which is what
    :meth:`ServingMetrics.to_prometheus` emits."""
    families: dict[str, tuple[str, list]] = {}
    order = []
    for value, text in parts:
        fam = None
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                _, _, name, kind = line.split()
                if name not in families:
                    families[name] = (kind, [])
                    order.append(name)
                fam = families[name]
                continue
            if line.startswith("#"):
                continue
            if fam is not None:
                fam[1].append(line if value is None
                              else _label_sample(line, label, value))
    lines = []
    for name in order:
        kind, samples = families[name]
        lines.append(f"# TYPE {name} {kind}")
        lines += samples
    return "\n".join(lines) + "\n"
