"""Serving observability: counters + reservoir histograms exported as
JSON for the bench harness (PERF.md convention: one JSON artifact per
measurement, banked the moment it lands).

Host-side and allocation-light by design — metrics must never add a
device sync; the engine records values it already fetched.
"""
from __future__ import annotations

import json

import numpy as np

__all__ = ["Counter", "Histogram", "ServingMetrics"]


class Counter:
    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def export(self):
        return self.value


class Histogram:
    """Bounded reservoir of samples; percentiles computed at export.
    Keeps the LAST `cap` samples (serving metrics care about recent
    behavior; a trace replay fits entirely)."""

    def __init__(self, cap=65536):
        self.cap = int(cap)
        self._samples: list[float] = []
        self.count = 0

    def record(self, v):
        self.count += 1
        self._samples.append(float(v))
        if len(self._samples) > self.cap:
            del self._samples[: len(self._samples) - self.cap]

    def percentile(self, p):
        if not self._samples:
            return None
        return float(np.percentile(np.asarray(self._samples), p))

    def export(self):
        if not self._samples:
            return {"count": self.count, "mean": None, "p50": None,
                    "p99": None, "max": None}
        a = np.asarray(self._samples)
        return {"count": self.count,
                "mean": float(a.mean()),
                "p50": float(np.percentile(a, 50)),
                "p99": float(np.percentile(a, 99)),
                "max": float(a.max())}


class ServingMetrics:
    """The engine's counter/histogram set (names are the export keys)."""

    def __init__(self):
        self.ttft_s = Histogram()             # arrival -> first token
        self.inter_token_s = Histogram()      # gap between tokens
        self.queue_depth = Histogram()        # waiting queue, per step
        self.batch_size = Histogram()         # decode lanes, per step
        self.page_occupancy = Histogram()     # used/allocatable, per step
        self.prefill_chunks = Counter()
        self.decode_steps = Counter()
        self.tokens_generated = Counter()
        self.requests_finished = Counter()
        self.preemptions = Counter()
        self.deadline_evictions = Counter()
        self.cow_copies = Counter()

    def export(self):
        return {name: m.export() for name, m in vars(self).items()}

    def to_json(self, **extra):
        return json.dumps({**self.export(), **extra})
