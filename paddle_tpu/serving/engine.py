"""Continuous-batching inference engine over the paged KV cache.

Reference capability: the serving loop of vLLM / Paddle FastDeploy —
admission, chunked prefill, batched decode, preemption — realized
TPU-natively (SURVEY.md §7 static-shape stance):

- ONE step program class, compiled per BUCKETED shape: decode runs at
  batch buckets (powers of two up to ``max_batch``, S=1), prefill runs
  at (B=1, S=``prefill_chunk``). The jit trace cache is therefore
  bounded by ``log2(max_batch) + 2`` programs for the engine's lifetime.
- Weights enter every compiled step as ARGUMENTS, never baked constants
  (the round-3 HTTP-413 lesson in models/generation.py): weight updates
  flow through with NO recompile and NO stale-constant hazard, and the
  serialized program stays O(HLO). Parameter-object replacement rewires
  positionally (order comes from the module tree, which is stable) —
  the same contract the generate() program cache relies on.
- Padded lanes are real lanes pointed at the cache's SCRATCH page: every
  program sees fully-defined fixed-shape operands; garbage lanes are
  masked on the host.
- The decode loop targets RoPE causal-LM families (LLaMA zoo shape:
  ``model.llama`` or a module exposing embed_tokens/layers/norm +
  lm_head); positions are computed analytically, so chunk padding can
  run past the context limit without a table clamp-gather hazard.

The engine is host-driven: ``step()`` runs one scheduler iteration
(decode-priority batch + at most one prefill chunk), advances request
state, and ``run()`` loops until drained. All device work is CPU-mesh
testable; nothing here compiles a first-time Mosaic kernel (the paged
Pallas stub stays interpret-gated).

Decode hot path (round 10):

- **Sampling runs INSIDE the compiled step program**
  (:mod:`.sampling`): greedy/temperature/top-k/top-p with per-lane
  counter-based RNG driven by per-request ``(seed, token_index)`` int32
  ARGUMENTS, so the per-step host fetch is ``[B]`` int32 token ids plus
  ``[B]`` float32 logprobs (``fetch_bytes`` metric: <= B*8, down from
  B*V*4) and streams stay reproducible across preemption + recompute.
  The host numpy sampler remains the oracle path behind
  ``PADDLE_TPU_SERVING_HOST_SAMPLE=1`` (greedy is token-exact against
  it; sampled modes are distributionally checked).
- **Radix-tree prefix caching** (``prefix_cache=True`` or
  ``PADDLE_TPU_SERVING_PREFIX_CACHE=1``): ``add_request`` pins the
  longest cached prompt prefix, the scheduler admits on UNCACHED page
  need, and ``_prefill_chunk`` starts past the cached tokens and
  registers fresh full prompt pages back into the tree.
- Decode batches are staged through PERSISTENT per-bucket host buffers
  (``_build_decode_batch``) — no per-step np.zeros garbage on the hot
  path.

Batched speculative decoding (round 12):

- ``draft_model=``/``speculative_k=``: per decode round a small draft
  model proposes up to k tokens per running lane (ONE fused
  ``lax.scan`` program — k+1 draft steps, one dispatch), then ONE
  target step over the [B, k+1] extend shape — the chunked-prefill
  program class in ``multi_pos`` mode — verifies every position.
- Verification is DETERMINISTIC-SAMPLE MATCHING, not distributional
  rejection sampling: the verify step recomputes the target's own
  counter-RNG sample at every position (token ``t`` is pure in
  ``(weights, history, seed, t)`` — the PR-3 contract), and a draft
  proposal is accepted iff it EQUALS that sample. Every emitted token
  is therefore exactly what the non-speculative engine would have
  emitted — greedy AND seeded-sampled streams are token-exact, so
  router failover splicing and preemption recompute work unchanged.
  The draft shares the per-lane counter keys, so its Gumbel noise is
  correlated with the target's — a well-matched draft accepts at the
  argmax-agreement rate even for sampled lanes.
- Rejected positions roll back by ACCOUNTING only
  (``PagedKVCache.free_tail``): the garbage K/V stays masked by
  context_len and is overwritten when the lane grows again. The draft
  keeps its own (cheap, narrow) paged cache, rebuilt lazily after
  preemption/fork — draft-cache state can be dropped at ANY time
  without affecting output correctness, only the acceptance rate.
- Admission reserves each lane's worst-case round growth (k+1 tokens,
  ``Scheduler.spec_reserve_tokens``) so a verify burst never preempts
  a running decode; per-request opt-out rides ``speculative=False``.

Quantized serving (round 15):

- ``cache_dtype="int8"`` (or ``PADDLE_TPU_SERVING_KV_DTYPE``) selects
  the quantized paged cache: codes + per-(slot, head) f32 scales,
  quantized on append INSIDE the compiled step (deterministic — all
  recompute/failover/migration exactness contracts hold within the
  config), dequantized inline by ``paged_attention``; ~2x the bf16
  page capacity at an equal ``hbm_budget_mb``. The draft cache follows
  the SAME resolved dtype.
- ``weight_quant="int8"|"int4"`` (or
  ``PADDLE_TPU_SERVING_WEIGHT_QUANT``) swaps nn.Linear layers for
  weight-only-quantized storage (lm_head exempt); the quantized
  buffers ride every step as ARGUMENTS like all other weights.
"""
from __future__ import annotations

import functools
import json
import logging
import math
import os
import time

import numpy as np

from .chaos import ChaosConfig, ChaosInjector
from .distill import distill_buffer_from_env
from .kv_cache import (SCRATCH_PAGE, GeometryMismatch, OutOfPages,
                       PagedKVCache)
from .kvtier import KVTier, host_pool_from_env
from .metrics import ServingMetrics
from .scheduler import Request, RequestState, Scheduler
from .tp import resolve_tp
from .trace import ServingTrace

__all__ = ["EngineDraining", "FaultInjected", "ServingEngine"]

_log = logging.getLogger("paddle_tpu.serving")


class EngineDraining(RuntimeError):
    """Raised by add_request once drain() started — in-flight work
    finishes; new admissions are refused (the front-end maps it to
    HTTP 503)."""


class FaultInjected(RuntimeError):
    """The env-gated fault hook fired at a step boundary. Injected
    BEFORE any device work or state mutation, so the step is safely
    retryable — the front-end loop counts it and keeps stepping."""


class ServingEngine:
    @staticmethod
    def _validate_causal_lm(model, what="model"):
        cfg = getattr(model, "cfg", None)
        core = getattr(model, "llama", model)
        for attr in ("embed_tokens", "layers", "norm"):
            if not hasattr(core, attr):
                raise TypeError(
                    "ServingEngine needs a LLaMA-family causal LM "
                    "(model.llama or a core module with embed_tokens/"
                    f"layers/norm); {what} {type(model).__name__} "
                    f"lacks {attr!r}")
        if not hasattr(model, "lm_head"):
            raise TypeError(f"{what} must expose lm_head")
        if cfg is None:
            raise TypeError(f"{what} must carry a .cfg")
        return cfg, core

    @staticmethod
    def _resolve_cache_dtype(cache_dtype, cfg):
        """Resolve the KV cache dtype: explicit arg, else the
        PADDLE_TPU_SERVING_KV_DTYPE knob, else bfloat16-or-float32 from
        the model config. "int8" selects the quantized codes+scales
        layout (generation.py's proven recipe); other integer dtypes
        would astype-truncate K/V to garbage and are rejected."""
        import jax.numpy as jnp
        if cache_dtype is None:
            cache_dtype = os.environ.get(
                "PADDLE_TPU_SERVING_KV_DTYPE") or None
        if cache_dtype is None:
            return ("bfloat16" if getattr(cfg, "dtype", "float32")
                    == "bfloat16" else "float32")
        try:
            name = str(jnp.dtype(cache_dtype))
        except TypeError:
            name = str(cache_dtype)
        if name not in ("int8", "bfloat16", "float16", "float32"):
            raise ValueError(
                f"unsupported cache_dtype {cache_dtype!r}: use "
                "'int8' (quantized codes+scales) or a float dtype")
        return name

    def __init__(self, model, *, page_size=16, num_pages=None,
                 hbm_budget_mb=None, max_batch=8, prefill_chunk=32,
                 max_seq_len=None, eos_token_id=None, watermark_frac=0.05,
                 cache_dtype=None, on_event=None, prefix_cache=None,
                 draft_model=None, speculative_k=None,
                 weight_quant=None, chaos=None, host_pool=None,
                 distill=None, ragged=None, mesh=None, tp_degree=None):
        cfg, core = self._validate_causal_lm(model)
        if weight_quant is None:
            weight_quant = os.environ.get(
                "PADDLE_TPU_SERVING_WEIGHT_QUANT") or None
        if weight_quant not in (None, "int8", "int4"):
            raise ValueError(
                f"weight_quant must be 'int8', 'int4' or None, got "
                f"{weight_quant!r}")
        self.weight_quant = weight_quant
        if weight_quant:
            # decode is HBM-bound: int8/int4 weight storage halves/
            # quarters the bytes every step streams. lm_head stays full
            # precision (the usual LLM recipe, as in bench_generate).
            # The swapped-in qweight/scale are BUFFERS, so they ride
            # the compiled step as ARGUMENTS like every other weight
            # (never baked constants — the HTTP-413/stale-cache
            # contract holds). Converting an already-converted model is
            # a no-op (only exact nn.Linear instances are swapped).
            from ..nn.quant import convert_to_weight_only
            convert_to_weight_only(model,
                                   algo=f"weight_only_{weight_quant}",
                                   exclude=("lm_head",))
        self.model = model
        self._core = core
        nh = cfg.num_attention_heads
        nkv = getattr(cfg, "num_key_value_heads", None) or nh
        hd = cfg.hidden_size // nh
        self.max_seq_len = int(max_seq_len
                               or cfg.max_position_embeddings)
        maxpos = getattr(cfg, "max_position_embeddings", None)
        if maxpos is not None and self.max_seq_len > maxpos:
            raise ValueError(
                f"max_seq_len({self.max_seq_len}) exceeds "
                f"max_position_embeddings({maxpos})")
        cache_dtype = self._resolve_cache_dtype(cache_dtype, cfg)
        self.cache_dtype = cache_dtype
        # -- tensor-parallel SPMD step (round 23 / ISSUE 19) ----------------
        # resolve_tp returns None at degree <= 1, so the TP=1 hot path
        # carries zero TP code; heads must split evenly or the
        # per-shard q/kv slices would be ragged (loud at build time,
        # never silently at step time)
        self._tp = resolve_tp(mesh=mesh, tp_degree=tp_degree)
        if self._tp is not None and (nh % self._tp.degree
                                     or nkv % self._tp.degree):
            raise ValueError(
                f"tp_degree={self._tp.degree} must divide "
                f"num_attention_heads={nh} and num_key_value_heads="
                f"{nkv}")
        self.tp_degree = self._tp.degree if self._tp else 1
        self.tp_mesh_shape = self._tp.mesh_shape if self._tp else None
        self._tp_kernel_warned = False
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "PADDLE_TPU_SERVING_PREFIX_CACHE") == "1"
        self.cache = PagedKVCache(
            cfg.num_hidden_layers, nkv, hd, page_size=page_size,
            num_pages=num_pages,
            hbm_budget_bytes=(int(hbm_budget_mb * 2 ** 20)
                              if hbm_budget_mb is not None else None),
            dtype=cache_dtype, prefix_cache=bool(prefix_cache),
            tp_degree=self.tp_degree)
        self.max_pages_per_seq = math.ceil(
            self.max_seq_len / self.cache.page_size)
        # -- speculative decoding (round 12) -------------------------------
        self.draft = draft_model
        if draft_model is not None:
            dcfg, dcore = self._validate_causal_lm(draft_model,
                                                   what="draft_model")
            if getattr(dcfg, "vocab_size", None) != cfg.vocab_size:
                raise ValueError(
                    "draft and target models must share a vocab "
                    f"({dcfg.vocab_size} vs {cfg.vocab_size})")
            dmax = getattr(dcfg, "max_position_embeddings", None)
            if dmax is not None and self.max_seq_len > dmax:
                raise ValueError(
                    f"draft max_position_embeddings({dmax}) < "
                    f"max_seq_len({self.max_seq_len})")
            k = 4 if speculative_k is None else int(speculative_k)
            if not 1 <= k <= 16:
                raise ValueError(
                    f"speculative_k must be in [1, 16], got {k}")
            self.spec_k = k
            self._draft_core = dcore
            self._draft_window = getattr(dcfg, "sliding_window",
                                         None) or None
            dnh = dcfg.num_attention_heads
            dnkv = getattr(dcfg, "num_key_value_heads", None) or dnh
            # same page geometry/count as the target (token-capacity
            # parity), narrow per-page bytes (the draft is the cheap
            # model); no prefix cache — draft K/V is disposable state.
            # The dtype FOLLOWS the resolved cache_dtype (incl. int8):
            # a duplicated bf16-or-f32 decision here once let draft and
            # target caches silently diverge (regression-tested).
            self._draft_cache = PagedKVCache(
                dcfg.num_hidden_layers, dnkv,
                dcfg.hidden_size // dnh, page_size=page_size,
                num_pages=self.cache.num_pages,
                dtype=self.cache_dtype)
        else:
            if speculative_k:
                raise ValueError("speculative_k needs a draft_model")
            self.spec_k = 0
            self._draft_cache = None
            self._draft_core = None
            self._draft_window = None
        if self._tp is not None:
            # committed placements: weights last-dim sharded, pools
            # head-sharded — both ride every compiled step as ARGUMENTS,
            # so the shardings persist across steps with no per-step
            # host work.  A DISTINCT draft model replicates instead:
            # its propose/catchup programs then stay byte-identical to
            # the TP=1 engine's draft (a self-draft shares the target's
            # sharded tensors; the verify contract keeps the emitted
            # stream exact regardless of draft numerics).
            self._tp.shard_model_weights(self.model)
            self._tp.shard_cache_pools(self.cache)
            if self.draft is not None and self.draft is not self.model:
                self._tp.shard_model_weights(self.draft,
                                             replicate=True)
        self.scheduler = Scheduler(self.cache, max_batch=max_batch,
                                   prefill_chunk=prefill_chunk,
                                   watermark_frac=watermark_frac,
                                   spec_reserve_tokens=self.spec_k)
        # -- unified ragged step (round 22 / PR 18) ------------------------
        # ONE token-packed program for mixed prefill+decode+verify
        # steps (attention.py::ragged_paged_attention lane layout):
        # opt-in via ragged= or PADDLE_TPU_SERVING_RAGGED=1; the
        # bucketed path stays the default and the exactness oracle.
        if ragged is None:
            ragged = os.environ.get("PADDLE_TPU_SERVING_RAGGED") == "1"
        self.ragged = bool(ragged)
        self._ragged_fn = None        # one jit fn; <= 2 token shapes
        self._ragged_bufs = {}        # per-capacity persistent buffers
        # static geometry: L lanes always (max_batch decode/verify + 1
        # prefill); token capacity is one of TWO shapes — all-decode
        # steps pack into max_batch tokens, anything with a prefill
        # chunk or verify bursts pads to the mixed capacity. That pins
        # the compiled-program-class count at <= 2.
        self._ragged_lanes = max_batch + 1
        self._ragged_tok_small = max_batch
        self._ragged_tok_mixed = (max_batch * (self.spec_k + 1)
                                  + prefill_chunk)
        self._program_classes = set()  # static shape keys dispatched
        self.metrics = ServingMetrics()
        # always-on span timeline + flight recorder (round 16): every
        # mutation happens from the thread that drives the engine —
        # i.e. under the front-end lock — so no new locking appears
        self.trace = ServingTrace()
        # capacity observability: with dtype="int8" the same HBM budget
        # yields ~2*D/(D+4) x the bf16 page count — surface the honest
        # per-page cost so a scrape can verify the sizing
        self.metrics.kv_page_bytes.set(self.cache.bytes_total
                                       / self.cache.num_pages)
        self.eos = eos_token_id
        self.window = getattr(cfg, "sliding_window", None) or None
        self._step_fn = None          # one jit fn; traces per bucket
        self._draft_fn = None         # draft catchup/prefill step fn
        self._propose_fn = None       # fused k+1-step draft scan program
        self._logits_dev = None       # last step's on-device [B,V] logits
        self._decode_bufs = {}        # per-bucket persistent host buffers
        self._seed_rng = np.random.default_rng()  # seed=None fallback
        self._requests: dict[int, Request] = {}
        self._finished: dict[int, Request] = {}
        self._held: dict[int, Request] = {}   # "prefilled", pages kept
        self._rngs: dict[int, np.random.Generator] = {}
        # streaming callback: called synchronously with every event dict
        # the moment it is emitted (token/finish), from the thread that
        # runs step(). Must be cheap and non-blocking — the front-end
        # uses it to route tokens into per-request stream queues.
        self.on_event = on_event
        self._draining = False
        # unified chaos layer (round 17): ONE injector per engine —
        # accepts a ChaosInjector, a ChaosConfig, or None (env mode:
        # the legacy FAULT_* knobs keep working as aliases, re-read
        # per evaluation so monkeypatch-mid-test workflows still work)
        if isinstance(chaos, ChaosInjector):
            self.chaos = chaos
        else:
            assert chaos is None or isinstance(chaos, ChaosConfig)
            self.chaos = ChaosInjector(chaos, name="engine")
        self.chaos.bind(self.trace)
        self._chaos_spike = None  # (seq_id, steps_left) alloc pressure
        # hierarchical KV tier (round 20): host-RAM/disk page pools
        # behind the prefix cache.  ``host_pool=`` injects a (possibly
        # engine-shared) kvtier.HostPagePool; None resolves the
        # PADDLE_TPU_SERVING_HOST_POOL_* knobs.  Meaningless without
        # the prefix cache — nothing ever spills from a tree that
        # doesn't exist — so it is quietly absent there.
        if host_pool is None:
            host_pool = host_pool_from_env()
        if host_pool is not None and self.cache.prefix_cache_enabled:
            self.kvtier = KVTier(host_pool, chaos=self.chaos,
                                 metrics=self.metrics, trace=self.trace)
            self.cache.attach_tier(self.kvtier)
        else:
            self.kvtier = None
        # versioned live weight deployment (round 21): the per-set
        # version this engine is serving — 0 = the build-time weights.
        # Advertised in /healthz (frontend.health) and /metrics so the
        # router's version-pin skew guard reads it fresh.  Mutates only
        # through set_weights (graftlint weight-swap-lock).
        self.weight_version = {"target": 0, "draft": 0}
        # online draft distillation (round 21): when a DistillBuffer
        # rides here, the speculative verify loop logs one (history,
        # target-token) pair per emitted token — free hard-target
        # supervision for the draft.  None = logging off, the verify
        # loop pays nothing (distill= arg, else the knob).
        if distill is None:
            distill = distill_buffer_from_env()
        self.distill = distill

    # -- public API --------------------------------------------------------
    def add_request(self, prompt, max_new_tokens=32, *, deadline_s=None,
                    do_sample=False, temperature=1.0, top_k=0,
                    top_p=1.0, seed=None, n=1, logprobs=False,
                    request_id=None, speculative=None,
                    prefill_only=False):
        """Queue a request; returns its req_id (n>1 returns the PARENT id
        — forked children surface as their own req_ids in events). With
        the prefix cache on, the longest cached prompt prefix is PINNED
        here (so the front-end's reservation math, run under the same
        lock, can count only uncached pages without an eviction race)."""
        if self._draining:
            raise EngineDraining(
                "engine is draining: in-flight requests finish, new "
                "admissions are refused")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens}")
        total = prompt.size + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt({prompt.size}) + max_new_tokens"
                f"({max_new_tokens}) exceeds max_seq_len"
                f"({self.max_seq_len})")
        if n > 1 and not do_sample:
            raise ValueError("n>1 needs do_sample=True (greedy forks "
                             "would be identical streams)")
        if prefill_only and n > 1:
            raise ValueError(
                "prefill_only is incompatible with n>1: forks are "
                "created at prefill completion on the DECODE side of a "
                "migration, not the prefill side")
        if not 0.0 <= float(top_p) <= 1.0:
            raise ValueError(f"top_p={top_p} outside [0, 1]")
        now = self._now()
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      arrival=now,
                      deadline=(now + deadline_s
                                if deadline_s is not None else None),
                      do_sample=bool(do_sample),
                      temperature=float(temperature), top_k=int(top_k),
                      top_p=float(top_p), seed=seed, n=int(n),
                      logprobs=bool(logprobs),
                      request_id=(str(request_id)
                                  if request_id is not None else None),
                      speculative=(None if speculative is None
                                   else bool(speculative)),
                      prefill_only=bool(prefill_only))
        req.device_seed = (int(seed) & 0x7FFFFFFF if seed is not None
                           else int(self._seed_rng.integers(
                               1, 2 ** 31 - 1)))
        self._requests[req.req_id] = req
        self._rngs[req.req_id] = np.random.default_rng(seed)
        tier_restored = 0
        if self.cache.prefix_cache_enabled:
            # host-tier restore FIRST (round 20), so the pages it lands
            # are pinned by the acquire below like any shipped prefix;
            # best-effort — a miss/failure just means recompute
            if self.kvtier is not None:
                tier_restored = self.kvtier.restore(self.cache, prompt)
            req.cached_pages = self.cache.acquire_prefix(
                req.seq_id, prompt, prompt.size)
        self.scheduler.add(req)
        if self.trace.enabled:
            self.trace.begin(req.req_id, req.request_id)
            self.trace.mark(req.req_id, "queued_t0", now)
            if req.cached_pages:
                self.trace.span(req.req_id, "prefix_hit", now,
                                pages=req.cached_pages)
            if tier_restored:
                self.trace.span(req.req_id, "tier_restore_hit", now,
                                pages=tier_restored)
            elif (self.kvtier is not None and req.cached_pages
                  < (prompt.size - 1) // self.cache.page_size):
                self.trace.span(req.req_id, "tier_restore_miss", now)
            self.trace.flight.record(
                "admit", req_id=req.req_id,
                request_id=req.request_id,
                prompt_tokens=int(prompt.size),
                max_new_tokens=int(max_new_tokens))
        return req.req_id

    def step(self):
        """One scheduler iteration. Returns a list of event dicts
        ({"type": "token"|"finish", "req_id", ...})."""
        self._maybe_inject_fault()
        was_training = [m for m in (self.model, self.draft)
                        if m is not None
                        and getattr(m, "training", False)]
        for m in was_training:
            m.eval()
        try:
            return self._step_inner()
        finally:
            for m in was_training:
                m.train()

    def _step_inner(self):
        now = self._now()
        out = self.scheduler.schedule(now)
        if self.trace.enabled:
            # composition FIRST, duration at the end: a loop failure
            # mid-step leaves the failing step's batch shape in the
            # ring for the post-mortem dump
            self.trace.flight.record(
                "step_begin",
                decode=len(out.decode),
                prefill=(out.prefill[0].req_id
                         if out.prefill is not None else None),
                expired=len(out.expired),
                waiting=self.scheduler.queue_depth())
        events = []
        for r in out.expired:  # graceful: pages freed, partial output kept
            if self.cache.has_seq(r.seq_id):
                self.cache.free_seq(r.seq_id)
            self._free_draft_seq(r.seq_id)
            self.metrics.deadline_evictions.inc()
            self._record_finish(r, events)
        self.sweep_held_deadlines(now)
        if self.ragged:
            self._ragged_step(out, events)
        else:
            if out.decode:
                self._decode_batch(out.decode, events)
            if out.prefill is not None:
                req, start, end = out.prefill
                # the decode batch may have preempted the prefilling
                # request
                if req.state == RequestState.PREFILLING:
                    self._prefill_chunk(req, start, end, events)
        if not out.decode and out.prefill is None and not out.expired \
                and self.scheduler.waiting \
                and not self.scheduler.live_requests():
            # idle engine + blocked admission head: first give back any
            # prefix pins held by OTHER waiting requests (they re-match
            # at admission), then loud, not a silent spin — the request
            # can never fit
            req = self.scheduler.waiting[0]
            if not self._release_waiting_pins(exclude=req) \
                    and not self._release_chaos_spike():
                need = self.scheduler.worst_case_need(req)
                if need + self.scheduler.watermark_pages \
                        > self.cache.available_pages:
                    raise RuntimeError(
                        f"request {req.req_id} can never be admitted: "
                        f"needs {need} pages + "
                        f"{self.scheduler.watermark_pages} watermark > "
                        f"{self.cache.available_pages} available; grow "
                        "the cache budget or shrink the prompt")
        self.metrics.queue_depth.record(self.scheduler.queue_depth())
        self.metrics.page_occupancy.record(self.cache.occupancy())
        self.metrics.queue_depth_gauge.set(self.scheduler.queue_depth())
        self.metrics.page_occupancy_gauge.set(self.cache.occupancy())
        self.metrics.running_gauge.set(len(self.scheduler.running))
        if self.kvtier is not None:
            # drain deferred spills at the step boundary (the eviction
            # loop itself never serializes)
            self.kvtier.flush()
        self._sync_prefix_metrics()
        step_wall = self._now() - now
        self.metrics.step_duration_s.record(step_wall)
        if self.trace.enabled:
            self.trace.flight.record("step_end",
                                     wall_s=round(step_wall, 6),
                                     events=len(events))
        return events

    def run(self, max_steps=100000):
        """Step until every queued request finished; returns
        {req_id: {"tokens", "finish_reason", "preemptions"}}.

        On ANY failure the live requests' pages are returned to the free
        list (requests are requeued for recompute, generated tokens
        kept), so the engine stays reusable: a later run() retries them
        and — greedy or seeded — reproduces the uninterrupted streams.
        """
        steps = 0
        try:
            while not self.scheduler.all_done():
                self.step()
                steps += 1
                if steps > max_steps:
                    raise RuntimeError(
                        f"serving loop did not drain in {max_steps} "
                        "steps (starvation or a stuck request)")
        except Exception:
            self.release_live()
            raise
        self._release_chaos_spike()  # chaos residue dies with the run
        return self.results()

    def cancel(self, req_id):
        """Cancel a live request: frees its KV pages, purges it from
        every scheduler queue, and emits a ``finish`` event with reason
        ``"cancelled"`` (partial output is kept in results()). Returns
        True if the request was live, False for unknown/finished ids.

        NOT safe to call concurrently with step() — the front-end
        serializes both under one lock; direct users call it between
        steps.
        """
        req = self._requests.get(req_id)
        if req is None:
            return False
        if req.state == RequestState.FINISHED:
            # a held ("prefilled") request is finished but still owns
            # pages awaiting export — cancellation must release them
            return self.release_request(req_id)
        if self.cache.has_seq(req.seq_id):
            self.cache.free_seq(req.seq_id)
        self._free_draft_seq(req.seq_id)
        self.scheduler.remove(req)
        req.state = RequestState.FINISHED
        req.finish_reason = "cancelled"
        self.metrics.cancellations.inc()
        if self.trace.enabled:
            self.trace.flight.record("cancel", req_id=req_id)
        self._record_finish(req, [])
        return True

    @property
    def draining(self):
        return self._draining

    def start_drain(self):
        """Refuse new admissions; everything already queued (waiting/
        prefilling/running) keeps going to completion."""
        self._draining = True
        if self.trace.enabled:
            self.trace.flight.record(
                "drain", live=len(self.scheduler.live_requests()),
                waiting=self.scheduler.queue_depth())

    def resume_admissions(self):
        """Lift drain mode (the rolling-drain re-admit path): a drained
        engine accepts new requests again. Weight reloads happen while
        drained — weights are ARGUMENTS of the compiled step, so the
        update flows through with no recompile; the prefix cache must
        be flushed by the caller (stale K/V of the OLD weights)."""
        self._draining = False

    def drain(self, max_steps=100000):
        """start_drain() + run(): finish all in-flight work while
        rejecting admissions; returns results()."""
        self.start_drain()
        return self.run(max_steps)

    def set_weights(self, which, arrays, version):
        """Versioned weight hot-swap (round 21) — the ONE blessed
        mutation site of a serving pytree (graftlint
        ``weight-swap-lock``); all multi-threaded use goes through
        ``ServingFrontend.swap_weights``, whose lock is the one-step
        quiesce.

        Weights are ARGUMENTS of every compiled step (``warrs`` /
        ``dwarrs`` are rebuilt from ``_gen_state_tensors`` per
        dispatch), so swapping ``t._data`` here takes effect on the
        very next step with NO recompile and no jit-cache
        invalidation.  All-or-nothing: the full payload is validated
        (count + shape per tensor) before the first write, so a torn
        push (``distill_push_torn``) leaves the old version serving.

        Target swaps flush the prefix cache — every cached page holds
        K/V computed under the OLD weights — which also detaches and
        invalidates the attached KV tier (spilled chains of the old
        version must never restore).  Draft swaps skip the flush:
        draft K/V is disposable state and the draft only PROPOSES;
        the target's verify step decides every emitted token, so a
        mid-stream draft refresh changes acceptance rate, never
        output."""
        import jax.numpy as jnp
        if which not in ("target", "draft"):
            raise ValueError(
                f"unknown weight set {which!r}; 'target' or 'draft'")
        model = self.model if which == "target" else self.draft
        if model is None:
            raise ValueError("engine has no draft model")
        tensors = model._gen_state_tensors()
        if len(arrays) != len(tensors):
            self.metrics.weight_swap_rejects.inc()
            raise ValueError(
                f"torn weight payload: {len(arrays)} array(s) for "
                f"{len(tensors)} tensors")
        staged = []
        for i, (t, a) in enumerate(zip(tensors, arrays)):
            a = np.asarray(a)
            if tuple(a.shape) != tuple(np.shape(t._data)):
                self.metrics.weight_swap_rejects.inc()
                raise ValueError(
                    f"weight {i} shape {a.shape} != "
                    f"{tuple(np.shape(t._data))}")
            staged.append(jnp.asarray(a, dtype=t._data.dtype))
        for t, a in zip(tensors, staged):
            t._data = a
        if self._tp is not None:
            # swapped arrays arrive host-resident: re-commit them to
            # the mesh placement or the next step compiles against
            # unsharded operands (a silent program-class change)
            self._tp.shard_model_weights(
                model, replicate=(which == "draft"
                                  and model is not self.model))
        flushed = 0
        if which == "target":
            flushed = self.cache.clear_prefix()
        self.weight_version[which] = int(version)
        m = self.metrics
        m.weight_swaps.inc()
        (m.weight_version_target if which == "target"
         else m.weight_version_draft).set(int(version))
        if self.trace.enabled:
            self.trace.flight.record(
                "weight_swap", which=which, version=int(version),
                tensors=len(tensors), prefix_flushed=flushed)
        return flushed

    def release_live(self):
        """Error path: free every live request's pages and requeue the
        requests (front of queue, recompute-style — generated tokens
        kept) so a failed run() leaves the allocator clean and the
        engine reusable."""
        for r in self.scheduler.live_requests():
            if self.cache.has_seq(r.seq_id):
                self.cache.free_seq(r.seq_id)
            self._free_draft_seq(r.seq_id)
            self.scheduler.preempt(r)
        # WAITING requests hold pages too: add_request pins the matched
        # prefix (acquire_prefix) before the request is ever scheduled,
        # so a loop failure landing between admit and first schedule
        # would leak those pins forever. Free the seq and leave the
        # request queued — _admit re-matches the prefix on admission
        # (the recompute path) whenever the seq is gone.
        for r in list(self.scheduler.waiting):
            if self.cache.has_seq(r.seq_id):
                self.cache.free_seq(r.seq_id)
            self._free_draft_seq(r.seq_id)
        # WAITING requests hold pages too: add_request pins the matched
        # prefix (acquire_prefix) before the request is ever scheduled,
        # so a loop failure landing between admit and first schedule
        # would leak those pins forever. Free the seq and leave the
        # request queued — _admit re-matches the prefix on admission
        # (the recompute path) whenever the seq is gone.
        for rid in list(self._held):
            self.release_request(rid)
        self._release_chaos_spike()

    def _maybe_inject_fault(self):
        """Chaos fault hook, evaluated at the step BOUNDARY (before any
        device work or state mutation, so a raised step is safely
        retryable).  Three engine-level fault points ride it:
        ``step_latency`` (added per-step latency, via the injected
        sleeper), ``alloc_pressure`` (a chaos sequence grabs a fraction
        of the free pages for a few steps — exercising preemption and
        load shedding), and ``step_fault`` (raises FaultInjected).  The
        legacy PADDLE_TPU_SERVING_FAULT_* knobs alias into the same
        schedule (ChaosConfig.from_env)."""
        chaos = self.chaos
        cfg = chaos.cfg
        if not cfg.any_enabled and self._chaos_spike is None:
            return
        if chaos.fire("step_latency", cfg=cfg):
            chaos.sleep(cfg.step_latency_s)
        self._chaos_pressure_tick(chaos, cfg)
        if chaos.fire("step_fault", cfg=cfg):
            self.metrics.faults_injected.inc()
            if self.trace.enabled:
                self.trace.flight.record("fault",
                                         rate=cfg.rate("step_fault"))
            raise FaultInjected(
                "injected step fault "
                f"(chaos step_fault rate={cfg.rate('step_fault')})")

    _CHAOS_SEQ = "__chaos_pressure__"

    def _chaos_pressure_tick(self, chaos, cfg):
        """Allocator pressure spike: on fire, a chaos-owned sequence
        swallows ``alloc_pressure_frac`` of the current free pages for
        ``alloc_pressure_steps`` steps, then releases them.  The spike
        is accounted like any live sequence (conservation holds) and is
        itself the LAST thing released under terminal page pressure
        (``_release_chaos_spike``), so it degrades service — sheds,
        preemptions — without ever deadlocking it."""
        if self._chaos_spike is not None:
            sid, left = self._chaos_spike
            if left <= 1:
                self._release_chaos_spike()
            else:
                self._chaos_spike = (sid, left - 1)
            return
        if not chaos.fire("alloc_pressure", cfg=cfg):
            return
        pages = int(self.cache.free_pages * cfg.alloc_pressure_frac)
        if pages <= 0:
            return
        sid = self._CHAOS_SEQ
        self.cache.alloc_seq(sid)
        try:
            self.cache.append_slots(sid, pages * self.cache.page_size)
        except OutOfPages:  # pragma: no cover - sized from free_pages
            self.cache.free_seq(sid)
            return
        self._chaos_spike = (sid, max(1, cfg.alloc_pressure_steps))

    def _release_chaos_spike(self):
        """Give back the alloc-pressure spike's pages.  Returns True
        when pages were actually released."""
        if self._chaos_spike is None:
            return False
        sid, _ = self._chaos_spike
        self._chaos_spike = None
        if self.cache.has_seq(sid):
            self.cache.free_seq(sid)
            return True
        return False

    def chaos_idle_tick(self):
        """Idle-loop chaos upkeep (called by the front-end between
        steps when the scheduler is drained): the held-deadline sweep
        plus the alloc-pressure spike countdown — a spike must expire
        even when no step runs, or an idle engine would shed every new
        admission until traffic somehow restarted it."""
        released = self.sweep_held_deadlines()
        if self._chaos_spike is not None:
            sid, left = self._chaos_spike
            if left <= 1:
                self._release_chaos_spike()
            else:
                self._chaos_spike = (sid, left - 1)
        return released

    def sweep_held_deadlines(self, now=None):
        """Release HELD ("prefilled") requests whose deadline passed —
        the round-14 rule (anything that can drop a request must
        release held pages) enforced for timeouts: a migration that
        never came back must not pin pages forever.  Called per step
        and from the front-end's idle loop (a pure prefill replica
        idles between handoffs).  Returns the number released."""
        if not self._held:
            return 0
        now = self._now() if now is None else now
        expired = [rid for rid, r in self._held.items()
                   if r.deadline is not None and now >= r.deadline]
        for rid in expired:
            self.release_request(rid)
            self.metrics.held_expired.inc()
            if self.trace.enabled:
                self.trace.flight.record("held_expired", req_id=rid)
            _log.info(json.dumps({"event": "held_deadline_expired",
                                  "req_id": rid}))
        return len(expired)

    def results(self):
        return {rid: {"tokens": list(r.out_tokens),
                      "finish_reason": r.finish_reason,
                      "preemptions": r.preemptions}
                for rid, r in self._finished.items()}

    # -- internals ---------------------------------------------------------
    @staticmethod
    def _now():
        return time.perf_counter()

    def _bucket(self, n):
        b = 1
        while b < n:
            b <<= 1
        return min(b, self.scheduler.max_batch)

    def _alloc_with_preemption(self, req, n_tokens):
        """Allocate slots for req, preempting by page pressure (newest
        victim first) until it fits or no victim remains. Prefix pins
        held by WAITING requests are released before giving up — their
        cached pages become reclaimable and the requests simply
        re-match at admission."""
        while True:
            try:
                slots, copies = self.cache.append_slots(req.seq_id,
                                                        n_tokens)
            except OutOfPages:
                victim = self.scheduler.pick_victim(exclude=(req,))
                if victim is None:
                    if self._release_waiting_pins():
                        continue
                    if self._release_chaos_spike():
                        continue
                    raise RuntimeError(
                        f"KV cache too small: request {req.req_id} "
                        f"cannot fit even alone "
                        f"(allocatable={self.cache.allocatable_pages} "
                        f"pages of {self.cache.page_size} tokens)")
                self._preempt(victim)
                continue
            if copies:
                self.cache.apply_copies(copies)
                self.metrics.cow_copies.inc(len(copies))
            return slots

    def _release_waiting_pins(self, exclude=None):
        """Free the prefix-cache pins of WAITING (not-yet-admitted)
        requests so their cached pages become reclaimable under page
        pressure; the requests re-run the longest-prefix match when the
        scheduler admits them. Returns the number of pins released."""
        released = 0
        for r in self.scheduler.waiting:
            if r is exclude:
                continue
            if self.cache.has_seq(r.seq_id):
                self.cache.free_seq(r.seq_id)
                r.cached_pages = 0
                released += 1
        return released

    def _preempt(self, victim):
        if self.cache.has_seq(victim.seq_id):
            self.cache.free_seq(victim.seq_id)
        self._free_draft_seq(victim.seq_id)
        self.scheduler.preempt(victim)
        self.metrics.preemptions.inc()
        if self.trace.enabled:
            now = self._now()
            self.trace.span(victim.req_id, "preempted", now,
                            tokens_kept=len(victim.out_tokens))
            self.trace.mark(victim.req_id, "queued_t0", now)
            self.trace.flight.record("preempt", req_id=victim.req_id)

    def _free_draft_seq(self, seq_id):
        """Drop a lane's draft-cache state (request finished/cancelled/
        preempted). Draft K/V is disposable — the next speculative round
        rebuilds it by catchup prefill; output tokens never depend on
        it."""
        if self._draft_cache is not None \
                and self._draft_cache.has_seq(seq_id):
            self._draft_cache.free_seq(seq_id)

    def _spec_enabled(self, req):
        """Does this lane ride the draft-verify rounds? Engine-level
        config gates it; a request opts out with speculative=False."""
        return (self.spec_k > 0 and self.draft is not None
                and req.speculative is not False)

    def _decode_batch(self, reqs, events):
        spec, plain = [], []
        for r in reqs:
            (spec if self._spec_enabled(r) else plain).append(r)
        if spec:
            # lanes whose draft cache cannot be readied this round fall
            # back to the plain batch (output-identical, just slower)
            self._spec_round(spec, plain, events)
        if plain:
            self._plain_decode(plain, events)

    def _plain_decode(self, reqs, events):
        t0 = self._now()
        alloc = []
        for r in reqs:
            if r.state != RequestState.RUNNING:
                continue  # preempted by an earlier member's allocation
            slots = self._alloc_with_preemption(r, 1)
            alloc.append((r, int(slots[0])))
        active = [(r, s) for r, s in alloc
                  if r.state == RequestState.RUNNING]
        if not active:
            return
        host = self._host_sampling()
        b = self._build_decode_batch(active)
        sample_capable = (not host) and any(r.do_sample
                                            for r, _ in active)
        tok_d, lp_d = self._run_step(
            b["ids"], b["positions"], b["pt"], b["cl"], b["slot_map"],
            b["last_idx"],
            (b["do_sample"], b["temperature"], b["top_k"], b["top_p"],
             b["seeds"], b["steps"]), sample_capable)
        self.metrics.decode_steps.inc()
        self.metrics.batch_size.record(len(active))
        if host:
            logits = self._fetch_logits()
            for i, (r, _) in enumerate(active):
                self._emit_token(r, self._sample(r, logits[i]), events)
        else:
            toks = np.asarray(tok_d, np.int32)
            lps = np.asarray(lp_d, np.float32)
            self.metrics.fetch_bytes.inc(toks.nbytes + lps.nbytes)
            self.metrics.step_fetches.inc()
            for i, (r, _) in enumerate(active):
                self._emit_token(r, int(toks[i]), events,
                                 logprob=float(lps[i]))
        if self.trace.enabled:
            dur = self._now() - t0
            for r, _ in active:
                self.trace.run_span(r.req_id, "decode_round", t0, dur,
                                    batch=len(active))

    def _build_decode_batch(self, active):
        """Stage the decode batch into PERSISTENT per-bucket host
        buffers (allocated once per bucket, reused every step — no
        per-step np.zeros on the hot path). Padded lanes are explicitly
        reset each step: context 1, slots at the scratch page, neutral
        sampling params."""
        bb = self._bucket(len(active))
        b = self._decode_bufs.get(bb)
        if b is None:
            mp = self.max_pages_per_seq
            b = self._decode_bufs[bb] = {
                "ids": np.zeros((bb, 1), np.int32),
                "positions": np.zeros((bb, 1), np.int32),
                "pt": np.full((bb, mp), SCRATCH_PAGE, np.int32),
                "cl": np.ones(bb, np.int32),     # 1, not 0: keeps
                "slot_map": np.zeros((bb, 1), np.int32),  # softmax
                "last_idx": np.zeros(bb, np.int32),       # NaN-free
                "do_sample": np.zeros(bb, np.bool_),
                "temperature": np.ones(bb, np.float32),
                "top_k": np.zeros(bb, np.int32),
                "top_p": np.ones(bb, np.float32),
                "seeds": np.zeros(bb, np.int32),
                "steps": np.zeros(bb, np.int32),
            }
        n = len(active)
        b["ids"][n:] = 0
        b["positions"][n:] = 0
        b["pt"][n:] = SCRATCH_PAGE
        b["cl"][n:] = 1
        b["slot_map"][n:] = 0
        b["do_sample"][n:] = False
        b["temperature"][n:] = 1.0
        b["top_k"][n:] = 0
        b["top_p"][n:] = 1.0
        b["seeds"][n:] = 0
        b["steps"][n:] = 0
        for i, (r, slot) in enumerate(active):
            hist_len = r.prompt.size + len(r.out_tokens)
            b["ids"][i, 0] = r.out_tokens[-1]
            b["positions"][i, 0] = hist_len - 1
            b["pt"][i] = self.cache.page_table(r.seq_id,
                                              self.max_pages_per_seq)
            b["cl"][i] = hist_len
            b["slot_map"][i, 0] = slot
            b["do_sample"][i] = r.do_sample
            b["temperature"][i] = r.temperature
            b["top_k"][i] = r.top_k
            b["top_p"][i] = r.top_p
            b["seeds"][i] = r.device_seed
            b["steps"][i] = len(r.out_tokens)
        return b

    # -- speculative decoding (round 12) -----------------------------------
    def _draft_alloc(self, seq_id, n, protect=()):
        """Allocate ``n`` draft-cache slots, evicting OTHER lanes' draft
        state under pressure (their next round pays a catchup prefill;
        output tokens are unaffected — draft K/V is disposable). Lanes
        in ``protect`` are never evicted (they are mid-round: their
        page tables are about to enter a program). Returns None when
        the draft pool cannot serve."""
        dc = self._draft_cache
        while True:
            try:
                slots, copies = dc.append_slots(seq_id, n)
                if copies:  # pragma: no cover - draft seqs never fork
                    raise AssertionError("draft cache saw a CoW copy")
                return slots
            except OutOfPages:
                victims = [s for s in dc.live_seqs()
                           if s != seq_id and s not in protect]
                if not victims:
                    return None
                dc.free_seq(victims[0])

    def _draft_ready(self, req, protect=()):
        """Bring the draft cache up to date for ``req``: every history
        token but the last must have its draft K/V written (catchup
        runs the draft's chunked-prefill program — a lane's first
        speculative round after prefill/preemption/fork pays it once).
        False -> the lane falls back to plain decode this round."""
        dc = self._draft_cache
        sid = req.seq_id
        target = req.prompt.size + len(req.out_tokens) - 1
        if not dc.has_seq(sid):
            dc.alloc_seq(sid)
        have = dc.seq_len(sid)
        if have > target:  # pragma: no cover - defensive resync
            dc.free_tail(sid, target)
            have = target
        if have == target:
            return True
        hist = req.token_history()
        c = self.scheduler.prefill_chunk
        neutral = (np.zeros(1, np.bool_), np.ones(1, np.float32),
                   np.zeros(1, np.int32), np.ones(1, np.float32),
                   np.zeros(1, np.int32), np.zeros(1, np.int32))
        while have < target:
            n = min(c, target - have)
            slots = self._draft_alloc(sid, n, protect)
            if slots is None:
                return False
            ids = np.zeros((1, c), np.int32)
            ids[0, :n] = hist[have:have + n]
            positions = (have + np.arange(c, dtype=np.int32))[None, :]
            pt = dc.page_table(sid, self.max_pages_per_seq)[None, :]
            cl = np.asarray([have + n], np.int32)
            slot_map = np.zeros((1, c), np.int32)
            slot_map[0, :n] = slots
            self._run_draft_step(ids, positions, pt, cl, slot_map,
                                 np.asarray([n - 1], np.int32), neutral)
            have += n
        return True

    def _stage_draft_propose(self, active):
        """Build the bucketed draft arrays for the surviving verify
        lanes and run the fused k+1-step proposal scan (shared by the
        bucketed `_spec_round` and the ragged step — the draft program
        stays its own dispatch in both: different model, disposable
        K/V). ``active`` rows are ``(req, hist0, n_slots, tslots,
        dslots)``. Returns ``(props [bb, k+1] int32, samp,
        sample_capable)``."""
        k1 = self.spec_k + 1
        bb = self._bucket(len(active))
        mp = self.max_pages_per_seq
        dids = np.zeros((bb, 1), np.int32)
        dpos = np.zeros(bb, np.int32)
        dpt = np.full((bb, mp), SCRATCH_PAGE, np.int32)
        dcl = np.ones(bb, np.int32)
        dslot = np.zeros((bb, k1), np.int32)
        do_sample = np.zeros(bb, np.bool_)
        temperature = np.ones(bb, np.float32)
        top_k = np.zeros(bb, np.int32)
        top_p = np.ones(bb, np.float32)
        seeds = np.zeros(bb, np.int32)
        steps0 = np.zeros(bb, np.int32)
        for i, (r, hist0, n_slots, tslots, dslots) in enumerate(active):
            dids[i, 0] = r.out_tokens[-1]
            dpos[i] = hist0 - 1
            dpt[i] = self._draft_cache.page_table(r.seq_id, mp)
            dcl[i] = hist0
            dslot[i, :n_slots] = dslots
            do_sample[i] = r.do_sample
            temperature[i] = r.temperature
            top_k[i] = r.top_k
            top_p[i] = r.top_p
            seeds[i] = r.device_seed
            steps0[i] = len(r.out_tokens)
        samp = (do_sample, temperature, top_k, top_p, seeds, steps0)
        sample_capable = any(r.do_sample for r, *_ in active)
        props = np.asarray(self._run_draft_propose(
            dids, dpos, dpt, dcl, dslot, samp, sample_capable),
            np.int32)                                  # [bb, k+1]
        self.metrics.fetch_bytes.inc(props.nbytes)
        self.metrics.step_fetches.inc()
        return props, samp, sample_capable

    def _spec_round(self, lanes, plain, events):
        """One draft-propose / target-verify round over the speculative
        lanes: k+1 fused draft steps (ONE dispatch), ONE [B, k+1]
        target extend step, deterministic-sample acceptance, rollback
        of rejected slots. Lanes the draft cannot serve are demoted to
        ``plain`` (token-identical output, just one-token decode)."""
        k = self.spec_k
        k1 = k + 1
        t0 = self._now()
        protect = {r.seq_id for r in lanes}
        staged = []
        for r in lanes:
            if r.state != RequestState.RUNNING:
                continue  # preempted by an earlier member's catchup
            if not self._draft_ready(r, protect):
                self.metrics.spec_fallbacks.inc()
                plain.append(r)
                continue
            staged.append(r)
        alloc = []
        for r in staged:
            if r.state != RequestState.RUNNING:
                continue  # preempted by an earlier member's allocation
            hist0 = r.prompt.size + len(r.out_tokens)
            rem = r.max_new_tokens - len(r.out_tokens)
            # slots past the request's final fed position go to scratch
            # (they are never attended), keeping the round inside the
            # front-end's prompt+max_new page reservation envelope
            n_slots = min(k1, rem)
            tslots = self._alloc_with_preemption(r, n_slots)
            if r.state != RequestState.RUNNING:  # pragma: no cover
                continue
            dslots = self._draft_alloc(r.seq_id, n_slots, protect)
            if dslots is None:
                self.cache.free_tail(r.seq_id, hist0 - 1)
                self.metrics.spec_fallbacks.inc()
                plain.append(r)
                continue
            alloc.append((r, hist0, n_slots, tslots, dslots))
        active = [a for a in alloc
                  if a[0].state == RequestState.RUNNING]
        if not active:
            return
        bb = self._bucket(len(active))
        mp = self.max_pages_per_seq
        props, samp, sample_capable = self._stage_draft_propose(active)
        ids = np.zeros((bb, k1), np.int32)
        positions = np.zeros((bb, k1), np.int32)
        pt = np.full((bb, mp), SCRATCH_PAGE, np.int32)
        cl = np.ones(bb, np.int32)
        slot_map = np.zeros((bb, k1), np.int32)
        for i, (r, hist0, n_slots, tslots, dslots) in enumerate(active):
            ids[i, 0] = r.out_tokens[-1]
            ids[i, 1:] = props[i, :k]
            positions[i] = hist0 - 1 + np.arange(k1, dtype=np.int32)
            pt[i] = self.cache.page_table(r.seq_id, mp)
            cl[i] = hist0 - 1 + n_slots
            slot_map[i, :n_slots] = tslots
        host = self._host_sampling()
        toks, lps = self._run_step(
            ids, positions, pt, cl, slot_map, np.zeros(bb, np.int32),
            samp, (not host) and sample_capable, multi_pos=True)
        self.metrics.spec_rounds.inc()
        self.metrics.decode_steps.inc()
        self.metrics.batch_size.record(len(active))
        # count only proposals that COULD be accepted (a lane about to
        # hit max_new can use at most its remaining budget) so the
        # acceptance rate measures the draft, not the budget clip
        self.metrics.spec_draft_tokens.inc(
            sum(min(k, a[2]) for a in active))
        if host:
            logits = self._fetch_logits()              # [bb, k+1, V]
        else:
            toks = np.asarray(toks, np.int32)
            lps = np.asarray(lps, np.float32)
            self.metrics.fetch_bytes.inc(toks.nbytes + lps.nbytes)
            self.metrics.step_fetches.inc()
        accepted = 0
        for i, (r, hist0, n_slots, tslots, dslots) in enumerate(active):
            emitted = 0
            lane_accepted = 0
            for j in range(k1):
                if host:
                    # host oracle: numpy RNG draws happen one per
                    # EMITTED token, in stream order — identical
                    # consumption to the non-speculative loop
                    v = self._sample(r, logits[i, j])
                    lp = None
                else:
                    v = int(toks[i, j])
                    lp = float(lps[i, j])
                is_draft = j < k and v == int(props[i, j])
                if self.distill is not None:
                    # online distillation (round 21): the verify step
                    # computed the target's token for this history for
                    # free — log the hard-target pair BEFORE the emit
                    # appends v to the history
                    self.distill.log(r.prompt, r.out_tokens, v)
                    self.metrics.distill_pairs.inc()
                self._emit_token(r, v, events, logprob=lp)
                emitted += 1
                if is_draft:
                    accepted += 1
                    lane_accepted += 1
                if r.state == RequestState.FINISHED or not is_draft:
                    break  # mismatch emits the correction; j==k = bonus
            if r.state != RequestState.FINISHED:
                # rollback: accounting only — rejected slots' K/V stays
                # masked by context_len until overwritten
                new_len = hist0 + emitted - 1
                self.cache.free_tail(r.seq_id, new_len)
                self._draft_cache.free_tail(r.seq_id, new_len)
            if self.trace.enabled:
                self.trace.run_span(r.req_id, "spec_round", t0,
                                    self._now() - t0,
                                    batch=len(active),
                                    proposed=min(k, n_slots),
                                    accepted=lane_accepted,
                                    emitted=emitted)
        self.metrics.spec_accepted_tokens.inc(accepted)

    def _run_draft_step(self, ids, positions, pt, cl, slot_map,
                        last_idx, samp):
        """Draft catchup prefill: same compiled step class as the
        target, on the draft model/cache (sampling output unused)."""
        import jax
        import jax.numpy as jnp
        if self._draft_fn is None:
            # tp=None: the draft program never pins TP layouts — a
            # distinct draft's weights are replicated (byte-identical
            # program to TP=1), a self-draft's sharded tensors fall to
            # GSPMD auto.  Either way the verify step's deterministic-
            # sample matching keeps the EMITTED stream token-exact.
            self._draft_fn = jax.jit(
                functools.partial(_paged_step_pure, self.draft,
                                  self._draft_core, self._draft_window,
                                  None),
                static_argnums=(0, 1))
        dc = self._draft_cache
        dwarrs = [t._data for t in self.draft._gen_state_tensors()]
        k_ops, v_ops = dc.program_operands()
        _, _, _, k_pages, v_pages = self._draft_fn(
            False, False, dwarrs, jnp.asarray(ids),
            jnp.asarray(positions), jnp.asarray(pt), jnp.asarray(cl),
            jnp.asarray(slot_map), jnp.asarray(last_idx),
            tuple(jnp.asarray(a) for a in samp),
            k_ops, v_ops)
        dc.store_operands(k_pages, v_pages)
        self._count_dispatch(("draft_step", ids.shape))

    def _run_draft_propose(self, ids0, pos0, pt, cl0, slot_mat, samp,
                           sample_capable):
        """The fused k+1-step draft proposal scan: one dispatch per
        round, K/V written in place, proposals fetched as [B, k+1]
        int32 (the k+1-th output is the generation.py 'extra step'
        trick — it lands d_k's K/V so a full-accept round leaves no
        hole; the token itself is discarded)."""
        import jax
        import jax.numpy as jnp
        if self._propose_fn is None:
            self._propose_fn = jax.jit(
                functools.partial(_spec_draft_pure, self.draft,
                                  self._draft_core, self._draft_window),
                static_argnums=(0,))
        dc = self._draft_cache
        dwarrs = [t._data for t in self.draft._gen_state_tensors()]
        k_ops, v_ops = dc.program_operands()
        props, k_pages, v_pages = self._propose_fn(
            bool(sample_capable), dwarrs, jnp.asarray(ids0),
            jnp.asarray(pos0), jnp.asarray(pt), jnp.asarray(cl0),
            jnp.asarray(slot_mat),
            tuple(jnp.asarray(a) for a in samp),
            k_ops, v_ops)
        dc.store_operands(k_pages, v_pages)
        self._count_dispatch(("draft_propose", slot_mat.shape,
                              bool(sample_capable)))
        return props

    def _prefill_chunk(self, req, start, end, events):
        t0 = self._now()
        if self.trace.enabled:
            # first chunk of this prefill pass: close the queued span
            # (arrival -> admission, or requeue -> re-admission)
            q0 = self.trace.pop_mark(req.req_id, "queued_t0")
            if q0 is not None:
                self.trace.span(req.req_id, "queued", q0, t0 - q0)
        if not self.cache.has_seq(req.seq_id):
            self.cache.alloc_seq(req.seq_id)
        hist = req.token_history()
        chunk = hist[start:end]
        n = int(chunk.size)
        slots = self._alloc_with_preemption(req, n)
        c = self.scheduler.prefill_chunk
        ids = np.zeros((1, c), np.int32)
        ids[0, :n] = chunk
        positions = (start
                     + np.arange(c, dtype=np.int32))[None, :]
        pt = self.cache.page_table(req.seq_id,
                                   self.max_pages_per_seq)[None, :]
        cl = np.asarray([start + n], np.int32)
        slot_map = np.zeros((1, c), np.int32)  # padding -> scratch slots
        slot_map[0, :n] = slots
        last_idx = np.asarray([n - 1], np.int32)
        host = self._host_sampling()
        samp = (np.asarray([req.do_sample], np.bool_),
                np.asarray([req.temperature], np.float32),
                np.asarray([req.top_k], np.int32),
                np.asarray([req.top_p], np.float32),
                np.asarray([req.device_seed], np.int32),
                np.asarray([len(req.out_tokens)], np.int32))
        tok_d, lp_d = self._run_step(
            ids, positions, pt, cl, slot_map, last_idx, samp,
            (not host) and req.do_sample)
        self.metrics.prefill_chunks.inc()
        if self.trace.enabled:
            # a chunk that replays already-sampled tokens is recompute
            # work paid to preemption, not first-pass prefill — the
            # finish log's stall_s bucket
            self.trace.span(
                req.req_id,
                ("recompute" if (req.out_tokens or req.preemptions)
                 else "prefill_chunk"),
                t0, self._now() - t0, start=int(start), end=int(end),
                tokens=n)
        if self.cache.prefix_cache_enabled:
            # fresh full PROMPT pages now hold K/V: register them
            self.cache.commit_prefix(req.seq_id, req.prompt, end)
        self.scheduler.prefill_advanced(req, end)
        if req.state != RequestState.RUNNING:
            return  # more chunks to go
        if host:
            self._prefill_finish(req, events, True, 0, None, None)
        else:
            toks = np.asarray(tok_d, np.int32)
            lps = np.asarray(lp_d, np.float32)
            self.metrics.fetch_bytes.inc(toks.nbytes + lps.nbytes)
            self.metrics.step_fetches.inc()
            self._prefill_finish(req, events, False, 0, int(toks[0]),
                                 float(lps[0]))

    def _prefill_finish(self, req, events, host, row_idx, tok, lp):
        """Prefill-completion tail, shared by the bucketed chunk and
        the ragged step (``row_idx`` selects the request's last-token
        logits row in the step's logits — 0 for the bucketed [1, V]
        fetch, the packed token offset for the ragged [T, V] one).
        Fork BEFORE sampling (children share the prefix pages; the
        parent may finish — and free — immediately). A RECOMPUTE
        prefill (out_tokens non-empty after preemption) must NOT fork
        again: the children already exist."""
        children = []
        if req.n > 1 and not req.out_tokens:
            for i in range(1, req.n):
                children.append(self._fork(req, i))
        if host:
            row = self._fetch_logits()[row_idx]
            self._emit_token(req, self._sample(req, row), events)
            for child in children:
                self._emit_token(child, self._sample(child, row),
                                 events)
        else:
            self._emit_token(req, tok, events, logprob=lp)
            if children:
                # one fetched row, several seeds: children sample
                # eagerly with the SAME counter-RNG function; a child's
                # later recompute (token index >= 1) goes through the
                # compiled path with the same (seed, step) arguments
                row = self._fetch_logits()[row_idx]
                for child in children:
                    ctok, clp = _counter_sample_row(row, child)
                    self._emit_token(child, ctok, events, logprob=clp)
        if req.prefill_only and req.state == RequestState.RUNNING:
            # disagg handoff point: the first token is emitted (TTFT is
            # the prefill replica's to measure) and the request stops
            # BEFORE the first decode step — pages stay resident for
            # export_request until release_request/cancel frees them
            self._hold_prefilled(req, events)

    def _hold_prefilled(self, req, events):
        self.scheduler.finish(req, "prefilled")
        req.held = True
        self._held[req.req_id] = req
        self.metrics.prefills_held.inc()
        if self.trace.enabled:
            self.trace.mark(req.req_id, "held_t0", self._now())
        self._record_finish(req, events)

    # -- unified ragged step (round 22 / PR 18) ----------------------------
    def _ragged_step(self, out, events):
        """ONE token-packed dispatch for the whole step: plain decode
        lanes (q=1), speculative-verify lanes (q=k+1), and the prefill
        chunk ride a single compiled program over the
        ``ragged_paged_attention`` lane layout — one dispatch + one
        host fetch per step, the relay fixed-cost win (FEASIBILITY.md:
        per-dispatch overhead ~0.79 of a small step). Per-token
        counter-RNG keys are IDENTICAL to the bucketed path's
        ((seed, token-index) is schedule-independent), so streams are
        token-exact vs it even though preemption ORDER may differ —
        any valid schedule replays the same (weights, history, seed, t)
        function. The draft-proposal scan stays its own dispatch
        (different model, disposable K/V); draft catchup prefills ride
        ahead of it exactly as in `_spec_round`."""
        t0 = self._now()
        k = self.spec_k
        k1 = k + 1
        mp = self.max_pages_per_seq
        spec, plain = [], []
        for r in out.decode:
            (spec if self._spec_enabled(r) else plain).append(r)
        # 1. draft staging (catchup prefills are draft-model
        # dispatches); lanes the draft cannot serve demote to plain
        staged = []
        protect = {r.seq_id for r in spec}
        for r in spec:
            if r.state != RequestState.RUNNING:
                continue
            if not self._draft_ready(r, protect):
                self.metrics.spec_fallbacks.inc()
                plain.append(r)
                continue
            staged.append(r)
        spec_alloc = []
        for r in staged:
            if r.state != RequestState.RUNNING:
                continue  # preempted by an earlier member's allocation
            hist0 = r.prompt.size + len(r.out_tokens)
            rem = r.max_new_tokens - len(r.out_tokens)
            n_slots = min(k1, rem)
            tslots = self._alloc_with_preemption(r, n_slots)
            if r.state != RequestState.RUNNING:  # pragma: no cover
                continue
            dslots = self._draft_alloc(r.seq_id, n_slots, protect)
            if dslots is None:
                self.cache.free_tail(r.seq_id, hist0 - 1)
                self.metrics.spec_fallbacks.inc()
                plain.append(r)
                continue
            spec_alloc.append((r, hist0, n_slots, tslots, dslots))
        # 2. plain decode allocation
        plain_alloc = []
        for r in plain:
            if r.state != RequestState.RUNNING:
                continue
            slots = self._alloc_with_preemption(r, 1)
            plain_alloc.append((r, int(slots[0])))
        # 3. prefill-chunk allocation (it may preempt a staged decode
        # lane; the re-filter below drops that lane — its pages are
        # gone, and the recompute replays an identical stream)
        pf = None
        if out.prefill is not None:
            req, start, end = out.prefill
            if req.state == RequestState.PREFILLING:
                if self.trace.enabled:
                    q0 = self.trace.pop_mark(req.req_id, "queued_t0")
                    if q0 is not None:
                        self.trace.span(req.req_id, "queued", q0,
                                        t0 - q0)
                if not self.cache.has_seq(req.seq_id):
                    self.cache.alloc_seq(req.seq_id)
                chunk = req.token_history()[start:end]
                n = int(chunk.size)
                pslots = self._alloc_with_preemption(req, n)
                if req.state == RequestState.PREFILLING:
                    pf = (req, start, end, chunk, n, pslots)
        # 4. re-filter: every lane must still be live AFTER all
        # allocations — a preempted lane's page-table row is dead
        spec_active = [a for a in spec_alloc
                       if a[0].state == RequestState.RUNNING]
        plain_active = [(r, s) for r, s in plain_alloc
                        if r.state == RequestState.RUNNING]
        if not spec_active and not plain_active and pf is None:
            return
        # 5. draft proposals for the surviving verify lanes
        props = None
        if spec_active:
            props, _, _ = self._stage_draft_propose(spec_active)
        # 6. pack the token batch. Two static token capacities only
        # (see __init__): a step fits the small all-decode shape or
        # pads to the mixed one.
        n_tok = (sum(a[2] for a in spec_active) + len(plain_active)
                 + (pf[4] if pf is not None else 0))
        tcap = (self._ragged_tok_small
                if n_tok <= self._ragged_tok_small
                else self._ragged_tok_mixed)
        assert n_tok <= tcap, (n_tok, tcap)
        b = self._ragged_bufs.get(tcap)
        if b is None:
            nl = self._ragged_lanes
            b = self._ragged_bufs[tcap] = {
                "ids": np.zeros((1, tcap), np.int32),
                "positions": np.zeros((1, tcap), np.int32),
                "slot_map": np.zeros((1, tcap), np.int32),
                "pt": np.full((nl, mp), SCRATCH_PAGE, np.int32),
                "cl": np.ones(nl, np.int32),
                "ql": np.zeros(nl, np.int32),
                "qoff": np.zeros(nl, np.int32),
                "do_sample": np.zeros(tcap, np.bool_),
                "temperature": np.ones(tcap, np.float32),
                "top_k": np.zeros(tcap, np.int32),
                "top_p": np.ones(tcap, np.float32),
                "seeds": np.zeros(tcap, np.int32),
                "steps": np.zeros(tcap, np.int32),
            }
        else:
            # full padding reset: lane composition changes every step
            # (padded lanes keep context 1 / scratch pages / neutral
            # sampling — the NaN-free contract)
            b["ids"][:] = 0
            b["positions"][:] = 0
            b["slot_map"][:] = 0
            b["pt"][:] = SCRATCH_PAGE
            b["cl"][:] = 1
            b["ql"][:] = 0
            b["qoff"][:] = 0
            b["do_sample"][:] = False
            b["temperature"][:] = 1.0
            b["top_k"][:] = 0
            b["top_p"][:] = 1.0
            b["seeds"][:] = 0
            b["steps"][:] = 0
        lane = 0
        off = 0
        emit_spec = []                    # (req, hist0, n_slots, i, off)
        for i, (r, hist0, n_slots, tslots, dslots) in \
                enumerate(spec_active):
            b["pt"][lane] = self.cache.page_table(r.seq_id, mp)
            b["cl"][lane] = hist0 - 1 + n_slots
            b["ql"][lane] = n_slots
            b["qoff"][lane] = hist0 - 1
            sl = slice(off, off + n_slots)
            b["ids"][0, off] = r.out_tokens[-1]
            if n_slots > 1:
                b["ids"][0, off + 1:off + n_slots] = \
                    props[i, :n_slots - 1]
            b["positions"][0, sl] = hist0 - 1 + np.arange(
                n_slots, dtype=np.int32)
            b["slot_map"][0, sl] = tslots
            b["do_sample"][sl] = r.do_sample
            b["temperature"][sl] = r.temperature
            b["top_k"][sl] = r.top_k
            b["top_p"][sl] = r.top_p
            b["seeds"][sl] = r.device_seed
            # verify token j samples with counter key steps0+j — the
            # flattened fused_sample_multi key of the bucketed verify
            b["steps"][sl] = len(r.out_tokens) + np.arange(
                n_slots, dtype=np.int32)
            emit_spec.append((r, hist0, n_slots, i, off))
            lane += 1
            off += n_slots
        emit_plain = []                                  # (req, off)
        for r, slot in plain_active:
            hist_len = r.prompt.size + len(r.out_tokens)
            b["pt"][lane] = self.cache.page_table(r.seq_id, mp)
            b["cl"][lane] = hist_len
            b["ql"][lane] = 1
            b["qoff"][lane] = hist_len - 1
            b["ids"][0, off] = r.out_tokens[-1]
            b["positions"][0, off] = hist_len - 1
            b["slot_map"][0, off] = slot
            b["do_sample"][off] = r.do_sample
            b["temperature"][off] = r.temperature
            b["top_k"][off] = r.top_k
            b["top_p"][off] = r.top_p
            b["seeds"][off] = r.device_seed
            b["steps"][off] = len(r.out_tokens)
            emit_plain.append((r, off))
            lane += 1
            off += 1
        pf_off = None
        if pf is not None:
            req, start, end, chunk, n, pslots = pf
            b["pt"][lane] = self.cache.page_table(req.seq_id, mp)
            b["cl"][lane] = start + n
            b["ql"][lane] = n
            b["qoff"][lane] = start
            sl = slice(off, off + n)
            b["ids"][0, sl] = chunk
            b["positions"][0, sl] = start + np.arange(n,
                                                      dtype=np.int32)
            b["slot_map"][0, sl] = pslots
            # only the chunk's LAST token's sample is ever consumed
            # (at prefill completion); earlier tokens keep the neutral
            # params and their greedy output is discarded
            pf_off = off + n - 1
            b["do_sample"][pf_off] = req.do_sample
            b["temperature"][pf_off] = req.temperature
            b["top_k"][pf_off] = req.top_k
            b["top_p"][pf_off] = req.top_p
            b["seeds"][pf_off] = req.device_seed
            b["steps"][pf_off] = len(req.out_tokens)
            lane += 1
            off += n
        # 7. ONE dispatch, ONE [T]+[T] host fetch
        tok_d, lp_d = self._run_ragged_step(
            b["ids"], b["positions"], b["pt"], b["cl"], b["ql"],
            b["qoff"], b["slot_map"],
            (b["do_sample"], b["temperature"], b["top_k"], b["top_p"],
             b["seeds"], b["steps"]))
        if spec_active:
            self.metrics.spec_rounds.inc()
            self.metrics.spec_draft_tokens.inc(
                sum(min(k, a[2]) for a in spec_active))
        if spec_active or plain_active:
            self.metrics.decode_steps.inc()
            self.metrics.batch_size.record(
                len(spec_active) + len(plain_active))
        if pf is not None:
            self.metrics.prefill_chunks.inc()
        host = self._host_sampling()
        toks = lps = logits = None
        if host:
            logits = self._fetch_logits()                     # [T, V]
        else:
            toks = np.asarray(tok_d, np.int32)
            lps = np.asarray(lp_d, np.float32)
            self.metrics.fetch_bytes.inc(toks.nbytes + lps.nbytes)
            self.metrics.step_fetches.inc()
        # 8. host-side per-lane processing, bucketed event order:
        # verify lanes, plain lanes, then the prefill completion
        accepted = 0
        for r, hist0, n_slots, i, toff in emit_spec:
            emitted = 0
            lane_accepted = 0
            for j in range(n_slots):
                if host:
                    v = self._sample(r, logits[toff + j])
                    lp = None
                else:
                    v = int(toks[toff + j])
                    lp = float(lps[toff + j])
                is_draft = j < k and v == int(props[i, j])
                if self.distill is not None:
                    self.distill.log(r.prompt, r.out_tokens, v)
                    self.metrics.distill_pairs.inc()
                self._emit_token(r, v, events, logprob=lp)
                emitted += 1
                if is_draft:
                    accepted += 1
                    lane_accepted += 1
                if r.state == RequestState.FINISHED or not is_draft:
                    break  # mismatch emits the correction; j==k bonus
            if r.state != RequestState.FINISHED:
                new_len = hist0 + emitted - 1
                self.cache.free_tail(r.seq_id, new_len)
                self._draft_cache.free_tail(r.seq_id, new_len)
            if self.trace.enabled:
                self.trace.run_span(r.req_id, "spec_round", t0,
                                    self._now() - t0,
                                    batch=len(spec_active),
                                    proposed=min(k, n_slots),
                                    accepted=lane_accepted,
                                    emitted=emitted)
        if spec_active:
            self.metrics.spec_accepted_tokens.inc(accepted)
        for r, toff in emit_plain:
            if host:
                self._emit_token(r, self._sample(r, logits[toff]),
                                 events)
            else:
                self._emit_token(r, int(toks[toff]), events,
                                 logprob=float(lps[toff]))
            if self.trace.enabled:
                self.trace.run_span(r.req_id, "ragged_round", t0,
                                    self._now() - t0,
                                    batch=len(plain_active))
        if pf is not None:
            req, start, end, chunk, n, pslots = pf
            if self.trace.enabled:
                self.trace.span(
                    req.req_id,
                    ("recompute" if (req.out_tokens or req.preemptions)
                     else "prefill_chunk"),
                    t0, self._now() - t0, start=int(start),
                    end=int(end), tokens=n)
            if self.cache.prefix_cache_enabled:
                self.cache.commit_prefix(req.seq_id, req.prompt, end)
            self.scheduler.prefill_advanced(req, end)
            if req.state == RequestState.RUNNING:
                if host:
                    self._prefill_finish(req, events, True, pf_off,
                                         None, None)
                else:
                    self._prefill_finish(req, events, False, pf_off,
                                         int(toks[pf_off]),
                                         float(lps[pf_off]))
        if self.trace.enabled:
            self.trace.flight.record(
                "ragged_step", tokens=int(n_tok), cap=int(tcap),
                lanes=int(lane), spec=len(emit_spec),
                plain=len(emit_plain),
                prefill=(pf[0].req_id if pf is not None else None))

    # -- KV page migration (disaggregated serving, round 14) ---------------
    def export_request(self, req_id, skip_pages=0):
        """Export a HELD request's KV page chain for migration.
        Returns ``(meta, k_arrays, v_arrays)`` — the allocator payload
        plus the continuation fields (prompt/out_tokens/device_seed)
        the adopting engine needs for a token-exact splice.  Read-only:
        the request stays held until :meth:`release_request`."""
        req = self._held.get(req_id)
        if req is None:
            raise KeyError(
                f"export_request: request {req_id!r} is not held "
                "(not prefill_only, already released, or unknown)")
        t0 = self._now()
        meta, k, v = self.cache.export_pages(req.seq_id, skip_pages)
        meta.update(
            prompt=[int(t) for t in req.prompt],
            out_tokens=[int(t) for t in req.out_tokens],
            device_seed=int(req.device_seed),
            # trace context rides the export meta: the adopting engine
            # keys its timeline on the same X-Request-Id, so the router
            # can stitch both phases into one timeline
            request_id=req.request_id)
        self.metrics.pages_exported.inc(int(meta["n_pages"]))
        if self.trace.enabled:
            self.trace.span(req.req_id, "migration", t0,
                            self._now() - t0, direction="export",
                            pages=int(meta["n_pages"]),
                            skip_pages=int(skip_pages))
        return meta, k, v

    def release_request(self, req_id):
        """Free a held request's pages (migration committed on the
        destination, or abandoned). Idempotent: False when nothing was
        held under this id."""
        req = self._held.pop(req_id, None)
        if req is None:
            return False
        req.held = False
        if self.cache.has_seq(req.seq_id):
            self.cache.free_seq(req.seq_id)
        if self.trace.enabled:
            h0 = self.trace.pop_mark(req.req_id, "held_t0")
            if h0 is not None:
                self.trace.span(req.req_id, "held", h0,
                                self._now() - h0)
        return True

    def adopt_request(self, meta, k_arrays, v_arrays, *,
                      max_new_tokens, deadline_s=None, do_sample=False,
                      temperature=1.0, top_k=0, top_p=1.0, seed=None,
                      logprobs=False, request_id=None, speculative=None):
        """Register a migrated-in request: import its KV page chain
        (geometry-checked, shared prefix resolved against THIS
        allocator's radix tree) and enter it RUNNING — the next decode
        step continues the stream exactly where the prefill replica
        stopped (token t is pure in (weights, history, seed, t), and
        ``device_seed`` rides in ``meta``).  Raises GeometryMismatch /
        PrefixDrift / OutOfPages with no state left behind."""
        if self._draining:
            raise EngineDraining(
                "engine is draining: in-flight requests finish, new "
                "admissions are refused")
        if self.chaos.fire("shard_geometry_mismatch"):
            raise GeometryMismatch(
                "chaos: shard geometry mismatch (tp_degree skew)")
        prompt = np.asarray(meta["prompt"], np.int32).reshape(-1)
        out_tokens = [int(t) for t in meta["out_tokens"]]
        if prompt.size == 0 or not out_tokens:
            raise ValueError(
                "adopt_request needs a non-empty prompt and at least "
                "the prefill replica's first sampled token")
        if int(meta["seq_len"]) != prompt.size + len(out_tokens) - 1:
            raise ValueError(
                f"adopt_request: payload seq_len={meta['seq_len']} != "
                f"history-1 ({prompt.size}+{len(out_tokens)}-1) — the "
                "last sampled token must not have been fed yet")
        if len(out_tokens) >= int(max_new_tokens):
            raise ValueError(
                f"adopt_request: {len(out_tokens)} token(s) already "
                f"emitted >= max_new_tokens({max_new_tokens}) — "
                "nothing left to decode")
        if request_id is None:
            # trace context rides the export meta (round 16): the
            # adopted timeline keys on the SOURCE request's id so the
            # router stitches both phases
            request_id = meta.get("request_id")
        total = prompt.size + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt({prompt.size}) + max_new_tokens"
                f"({max_new_tokens}) exceeds max_seq_len"
                f"({self.max_seq_len})")
        now = self._now()
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      arrival=now,
                      deadline=(now + deadline_s
                                if deadline_s is not None else None),
                      do_sample=bool(do_sample),
                      temperature=float(temperature), top_k=int(top_k),
                      top_p=float(top_p), seed=seed, n=1,
                      logprobs=bool(logprobs),
                      request_id=(str(request_id)
                                  if request_id is not None else None),
                      speculative=(None if speculative is None
                                   else bool(speculative)),
                      adopted=True)
        req.out_tokens = out_tokens
        req.device_seed = int(meta["device_seed"]) & 0x7FFFFFFF
        # TTFT belongs to the prefill replica; tokens here are TPOT
        req.first_token_at = now
        req.last_token_at = now
        self.cache.import_pages(req.seq_id, meta, k_arrays, v_arrays,
                                prompt=prompt,
                                hist_len=prompt.size + len(out_tokens))
        self._requests[req.req_id] = req
        self._rngs[req.req_id] = np.random.default_rng(seed)
        self.scheduler.register_adopted(req)
        self.metrics.pages_imported.inc(int(meta["n_pages"]))
        self.metrics.adoptions.inc()
        if self.trace.enabled:
            self.trace.begin(req.req_id, req.request_id)
            self.trace.span(req.req_id, "migration", now,
                            self._now() - now, direction="import",
                            pages=int(meta["n_pages"]))
            self.trace.flight.record("adopt", req_id=req.req_id,
                                     request_id=req.request_id,
                                     pages=int(meta["n_pages"]))
        return req.req_id

    # -- fleet prefix transfer (round 18) ----------------------------------
    def export_prefix(self, prompt, skip_pages=0):
        """Serve this engine's cached prefix of ``prompt`` for a fleet
        prefix ship (the router moves it to the replica it is about to
        place a matching request on).  Read-only on refcounts; raises
        PrefixDrift when the local chain is shorter than the skip the
        router probed."""
        t0 = self._now()
        meta, k, v = self.cache.export_prefix_pages(prompt, skip_pages)
        self.metrics.prefix_pages_exported.inc(int(meta["n_pages"]))
        if self.trace.enabled:
            self.trace.flight.record(
                "prefix_export", pages=int(meta["n_pages"]),
                skip_pages=int(skip_pages),
                wall_s=round(self._now() - t0, 6))
        return meta, k, v

    def import_prefix(self, meta, k_arrays, v_arrays):
        """Land a shipped prefix payload in this engine's radix tree
        (pages enter CACHED at rc==0 — reclaimable capacity, exactly
        like a locally-prefilled prefix).  Returns the page count."""
        t0 = self._now()
        if self.chaos.fire("shard_geometry_mismatch"):
            raise GeometryMismatch(
                "chaos: shard geometry mismatch (tp_degree skew)")
        n = self.cache.import_prefix_pages(meta, k_arrays, v_arrays)
        self.metrics.prefix_pages_imported.inc(n)
        if self.trace.enabled:
            self.trace.flight.record(
                "prefix_import", pages=n,
                skip_pages=int(meta["skip_pages"]),
                wall_s=round(self._now() - t0, 6))
        return n

    def drop_prefix(self, prompt):
        """Router-driven dedup: evict this engine's unpinned cached
        chain for ``prompt`` (deepest-first).  Returns pages freed."""
        n = self.cache.drop_prefix(prompt)
        self.metrics.prefix_drops.inc(n)
        if self.trace.enabled and n:
            self.trace.flight.record("prefix_drop", pages=n)
        return n

    # -- hierarchical KV tier (round 20) -----------------------------------
    def restore_prefix(self, prompt):
        """Best-effort host-tier restore of ``prompt``'s missing prefix
        pages (the router's local-tier probe, between its device probe
        and the remote-donor loop).  Restored pages enter CACHED at
        rc==0 — shipped-prefix semantics, so admission accounting needs
        no new case.  Returns pages restored; 0 with no tier."""
        if self.kvtier is None:
            return 0
        return self.kvtier.restore(self.cache, prompt)

    def prewarm_prefix(self, max_chains=None):
        """Restore the hottest spilled chains into the device tree —
        the autoscaler's warm-up for a newly grown replica.  Returns
        total pages restored; strictly best-effort."""
        if self.kvtier is None:
            return 0
        return self.kvtier.prewarm(self.cache, max_chains)

    def tier_stats(self):
        """Host/disk tier occupancy + counters (``/healthz`` shape);
        None when no tier is attached."""
        return None if self.kvtier is None else self.kvtier.stats()

    def _fork(self, parent, i):
        child = Request(prompt=parent.prompt,
                        max_new_tokens=parent.max_new_tokens,
                        arrival=parent.arrival, deadline=parent.deadline,
                        do_sample=parent.do_sample,
                        temperature=parent.temperature,
                        top_k=parent.top_k, top_p=parent.top_p,
                        seed=(parent.seed or 0) + i, n=1,
                        logprobs=parent.logprobs,
                        request_id=parent.request_id)
        child.device_seed = (parent.device_seed + i) & 0x7FFFFFFF
        child.parent_id = parent.req_id
        child.first_token_at = None
        if self.trace.enabled:
            self.trace.begin(child.req_id, child.request_id)
            self.trace.span(child.req_id, "forked", self._now(),
                            parent=parent.req_id, index=i)
        self.cache.fork(parent.seq_id, child.seq_id)
        self._requests[child.req_id] = child
        self._rngs[child.req_id] = np.random.default_rng(child.seed)
        self.scheduler.register_fork(child)
        return child

    def _emit_token(self, req, tok, events, logprob=None):
        req.out_tokens.append(tok)
        now = self._now()
        if req.first_token_at is None:
            req.first_token_at = now
            self.metrics.ttft_s.record(now - req.arrival)
        else:
            self.metrics.inter_token_s.record(now - req.last_token_at)
        req.last_token_at = now
        self.metrics.tokens_generated.inc()
        ev = {"type": "token", "req_id": req.req_id, "token": tok}
        if req.logprobs and logprob is not None:
            ev["logprob"] = logprob
        self._event(ev, events)
        if self.eos is not None and tok == self.eos:
            self._finish(req, "stop", events)
        elif len(req.out_tokens) >= req.max_new_tokens:
            self._finish(req, "length", events)

    def _finish(self, req, reason, events):
        if self.cache.has_seq(req.seq_id):
            self.cache.free_seq(req.seq_id)
        self._free_draft_seq(req.seq_id)
        self.scheduler.finish(req, reason)
        self._record_finish(req, events)

    def _record_finish(self, req, events):
        self.metrics.requests_finished.inc()
        self._finished[req.req_id] = req
        tr = self.trace.finish(req.req_id)
        self._event({"type": "finish", "req_id": req.req_id,
                     "reason": req.finish_reason,
                     "n_tokens": len(req.out_tokens)}, events)
        if _log.isEnabledFor(logging.INFO):
            n = len(req.out_tokens)
            ttft = (req.first_token_at - req.arrival
                    if req.first_token_at is not None else None)
            tpot = ((req.last_token_at - req.first_token_at) / (n - 1)
                    if n > 1 else None)
            line = {
                "event": "request_finished", "req_id": req.req_id,
                "reason": req.finish_reason, "n_tokens": n,
                "prompt_tokens": int(req.prompt.size),
                "ttft_s": ttft, "tpot_s": tpot,
                "preemptions": req.preemptions,
                "cached_prompt_pages": req.cached_pages,
                "parent_id": req.parent_id,
                "request_id": req.request_id}
            if tr is not None:
                # span-derived phase decomposition: log scrapers get
                # queue/prefill/decode/stall without /debug/trace
                line["phases"] = tr.phase_breakdown()
                if tr.dropped:
                    line["trace_spans_dropped"] = tr.dropped
            _log.info(json.dumps(line))

    def _event(self, ev, events):
        events.append(ev)
        if self.on_event is not None:
            self.on_event(ev)

    def request(self, req_id):
        """Look up a Request by id (live or finished) — the front-end
        uses this to map forked children onto their parent's stream."""
        return self._requests.get(req_id)

    @staticmethod
    def _host_sampling():
        """Oracle escape hatch: PADDLE_TPU_SERVING_HOST_SAMPLE=1 keeps
        sampling on the host from fully-fetched logits (numpy RNG).
        Read per step so tests can flip it with monkeypatch."""
        return os.environ.get("PADDLE_TPU_SERVING_HOST_SAMPLE") == "1"

    def _sample(self, req, logits_row):
        """Host numpy sampling — the oracle path. Max-subtraction
        BEFORE exp is load-bearing: logits of ~1e3 otherwise overflow
        to inf/NaN (regression-tested)."""
        lg = np.asarray(logits_row, np.float32)
        if not req.do_sample:
            return int(lg.argmax())
        if req.temperature != 1.0:
            lg = lg / max(req.temperature, 1e-6)
        if req.top_k and req.top_k < lg.size:
            kth = np.partition(lg, -req.top_k)[-req.top_k]
            lg = np.where(lg < kth, -np.inf, lg)
        if 0.0 < req.top_p < 1.0:
            shifted = lg - lg.max()
            srt = np.sort(shifted)[::-1]
            p = np.exp(srt)
            p /= p.sum()
            keep = (np.cumsum(p) - p) < req.top_p  # keeps the crosser
            thr = srt[keep][-1]                    # smallest kept logit
            lg = np.where(shifted < thr, -np.inf, lg)
        lg = lg - lg.max()
        p = np.exp(lg)
        p /= p.sum()
        return int(self._rngs[req.req_id].choice(lg.size, p=p))

    @property
    def _last_logits_probe(self):
        """Row-0 logits of the last step, fetched on demand —
        parity-test observability (the hot path no longer fetches
        logits at all)."""
        if self._logits_dev is None:
            return None
        return np.asarray(self._logits_dev, np.float32)[0]

    def _fetch_logits(self):
        """Pull the last step's full [B, V] logits to the host (oracle
        sampling / fork seeding) and account the fetch."""
        out = np.asarray(self._logits_dev, np.float32)
        self.metrics.fetch_bytes.inc(out.nbytes)
        self.metrics.step_fetches.inc()
        return out

    def _sync_prefix_metrics(self):
        c, m = self.cache, self.metrics
        m.prefix_hit_pages.value = c.prefix_hit_pages
        m.prefix_miss_pages.value = c.prefix_miss_pages
        m.prefix_evictions.value = c.prefix_evictions
        total = c.prefix_hit_pages + c.prefix_miss_pages
        m.prefix_hit_rate.set(c.prefix_hit_pages / total if total
                              else 0.0)
        m.cached_pages_gauge.set(c.cached_pages)
        if self.kvtier is not None:
            st = self.kvtier.pool.stats()
            m.host_pool_pages.set(st["host_pool_pages"])
            m.host_pool_bytes.set(st["host_pool_bytes"])
            m.disk_pool_pages.set(st.get("disk_pool_pages", 0))
        if m.spec_draft_tokens.value:
            m.spec_acceptance_rate.set(m.spec_accepted_tokens.value
                                       / m.spec_draft_tokens.value)

    def _count_dispatch(self, key):
        """Account one device dispatch and its compiled program class
        (``key`` is the static shape signature that keys the jit trace
        cache). ``step_program_classes`` is the gauge the ragged path
        bounds at <= 2; the bucketed path grows one class per decode
        bucket plus the prefill and verify shapes. Draft-model programs
        (the propose scan is its own dispatch by design — different
        model, disposable K/V) count as dispatches but not as step
        classes."""
        self.metrics.step_dispatches.inc()
        if key[0].startswith("draft"):
            return
        if key not in self._program_classes:
            self._program_classes.add(key)
            self.metrics.step_program_classes.set(
                len(self._program_classes))

    def _tp_kernel_guard(self):
        """The loud Pallas guard (round 23): a TP step must never
        trace ``pallas_call`` into the SPMD program (no GSPMD
        partitioning rule — CLAUDE.md invariant), so when the mesh is
        active and ``PADDLE_TPU_PAGED_KERNEL=1`` asks for the kernel,
        the step refuses-and-falls-back to the jnp gather path —
        logged once, counted per step (``tp_kernel_fallbacks``).  The
        knob is re-read per step like ``_host_sampling`` so
        monkeypatch-mid-test workflows see honest accounting; the
        in-program bypass itself rides ``spmd=True`` through
        ``_paged_forward`` regardless of this metric."""
        if self._tp is None:
            return
        if os.environ.get("PADDLE_TPU_PAGED_KERNEL") != "1":
            return
        if not self._tp_kernel_warned:
            self._tp_kernel_warned = True
            _log.warning(json.dumps({
                "event": "tp_pallas_fallback",
                "tp_degree": self.tp_degree,
                "detail": "PADDLE_TPU_PAGED_KERNEL=1 ignored under "
                          "tensor parallelism: pallas_call has no "
                          "GSPMD partitioning rule; using the jnp "
                          "gather path"}))
        self.metrics.tp_kernel_fallbacks.inc()

    def _run_step(self, ids, positions, pt, cl, slot_map, last_idx,
                  samp, sample_capable, multi_pos=False):
        import jax
        import jax.numpy as jnp
        self._tp_kernel_guard()
        if self._step_fn is None:
            # bucketed shapes bound this single fn's trace cache to
            # 2*(log2(max_batch)+2) entries (the static sample_capable
            # and multi_pos flags at most double it each); weights ride
            # as arguments. The TP context rides the partial like
            # model/core — closed over, never traced — so the jit
            # signature and static argnums are the TP=1 ones.
            self._step_fn = jax.jit(
                functools.partial(_paged_step_pure, self.model,
                                  self._core, self.window, self._tp),
                static_argnums=(0, 1))
        warrs = [t._data for t in self.model._gen_state_tensors()]
        k_ops, v_ops = self.cache.program_operands()
        tok, lp, logits, k_pages, v_pages = self._step_fn(
            bool(sample_capable), bool(multi_pos), warrs,
            jnp.asarray(ids), jnp.asarray(positions), jnp.asarray(pt),
            jnp.asarray(cl), jnp.asarray(slot_map),
            jnp.asarray(last_idx),
            tuple(jnp.asarray(a) for a in samp),
            k_ops, v_ops)
        self.cache.store_operands(k_pages, v_pages)
        self._logits_dev = logits  # NOT fetched on the decode hot path
        self._count_dispatch(("step", ids.shape, bool(multi_pos),
                              bool(sample_capable)))
        return tok, lp

    def _run_ragged_step(self, ids, positions, pt, cl, ql, qoff,
                         slot_map, samp):
        import jax
        import jax.numpy as jnp
        self._tp_kernel_guard()
        if self._ragged_fn is None:
            # ONE jit fn; the token capacity in {small, mixed} bounds
            # its trace cache at two entries — the <= 2-program-class
            # contract. The sampler is always compiled sample-capable:
            # greedy lanes take the argmax/raw-logprob branch inside
            # fused_sample, so pinning the static flag costs an unused
            # sort, not exactness (and keeps greedy and sampled steps
            # in the SAME class).
            self._ragged_fn = jax.jit(
                functools.partial(_ragged_step_pure, self.model,
                                  self._core, self.window, self._tp))
        warrs = [t._data for t in self.model._gen_state_tensors()]
        k_ops, v_ops = self.cache.program_operands()
        tok, lp, logits, k_pages, v_pages = self._ragged_fn(
            warrs, jnp.asarray(ids), jnp.asarray(positions),
            jnp.asarray(pt), jnp.asarray(cl), jnp.asarray(ql),
            jnp.asarray(qoff), jnp.asarray(slot_map),
            tuple(jnp.asarray(a) for a in samp), k_ops, v_ops)
        self.cache.store_operands(k_pages, v_pages)
        self._logits_dev = logits          # [T, V], fetched on demand
        self._count_dispatch(("ragged", ids.shape[1]))
        return tok, lp


# -- the compiled step (weights as arguments; generation.py idiom) ---------

def _counter_sample_row(logits_row, req):
    """Eagerly sample ONE token from a fetched logits row with the same
    counter-RNG fused sampler the compiled program runs — fork children
    at prefill completion (one row, several seeds)."""
    import jax.numpy as jnp

    from .sampling import fused_sample
    tok, lp = fused_sample(
        jnp.asarray(logits_row, jnp.float32)[None],
        jnp.asarray([True]),
        jnp.asarray([req.temperature], jnp.float32),
        jnp.asarray([req.top_k], jnp.int32),
        jnp.asarray([req.top_p], jnp.float32),
        jnp.asarray([req.device_seed], jnp.int32),
        jnp.asarray([len(req.out_tokens)], jnp.int32))
    return int(np.asarray(tok)[0]), float(np.asarray(lp)[0])


def _paged_step_pure(model, core, window, tp, sample_capable,
                     multi_pos, warrs, ids, positions, pt, cl,
                     slot_map, last_idx, samp, k_pages, v_pages):
    tensors = model._gen_state_tensors()
    saved = [(t, t._data) for t in tensors]
    for t, arr in zip(tensors, warrs):
        t._data = arr
    try:
        return _paged_step_body(model, core, window, tp,
                                sample_capable, multi_pos, ids,
                                positions, pt, cl, slot_map, last_idx,
                                samp, k_pages, v_pages)
    finally:
        for t, arr in saved:
            t._data = arr


def _paged_forward(core, window, ids, positions, pt, cl, slot_map,
                   k_pages, v_pages, ragged=None, tp=None):
    """The transformer trunk over the paged cache: embed, attend (K/V
    scattered into the page pool), final norm. Shared by the target
    step program, the draft catchup step, the draft proposal scan, and
    the unified ragged step. ``ragged=(query_lens, q_offsets)`` flips
    attention to the token-packed lane layout: ids/positions/slot_map
    are [1, T] (the scatter is shape-agnostic) while pt/cl are the
    [L, P]/[L] PER-LANE arrays. Returns ``(hidden [B, S, D] jnp array,
    new_k, new_v)``.

    ``tp`` (a :class:`~.tp.TPContext`) makes the trunk ONE SPMD
    program over the mesh.  The constraints below are the whole
    exactness argument (tp.py module docstring): activations are
    pinned REPLICATED wherever a sharded dim would otherwise feed a
    contraction (GSPMD would partial-sum + all-reduce there — a
    different f32 summation order than TP=1), and q/k/v plus the page
    pools are pinned head-sharded so the attention inner loop is
    shard-local.  The MLP is inlined under TP because
    ``layer.mlp(...)`` offers no hook to replicate the swiglu output
    before down_proj's contraction — the inline mirrors
    ``down_proj(swiglu(gate_proj(x), up_proj(x)))`` exactly."""
    from ..core.autograd import no_grad
    from ..core.tensor import Tensor
    from ..incubate.nn.functional import (
        fused_rotary_position_embedding, swiglu)
    from .attention import (paged_attention, quantize_q8,
                            ragged_paged_attention)

    spmd = tp is not None
    b, s = ids.shape
    flat_slots = slot_map.reshape(-1)
    with no_grad():
        x = core.embed_tokens(Tensor(ids))
        if spmd:
            # the embedding table is sharded on its hidden column dim,
            # so the gathered rows come out hidden-sharded: replicate
            # before the first layernorm (its reduction runs over the
            # hidden dim)
            x = Tensor(tp.replicate(x._data))
        pos_t = Tensor(positions)
        new_k, new_v = [], []
        for layer, kp, vp in zip(core.layers, k_pages, v_pages):
            at = layer.self_attn
            nh, nkv, hd = at.num_heads, at.num_kv_heads, at.head_dim
            y = layer.input_layernorm(x)
            q = at.q_proj(y).reshape([b, s, nh, hd])
            k = at.k_proj(y).reshape([b, s, nkv, hd])
            v = at.v_proj(y).reshape([b, s, nkv, hd])
            if spmd:
                q = Tensor(tp.shard_heads(q._data))
                k = Tensor(tp.shard_heads(k._data))
                v = Tensor(tp.shard_heads(v._data))
            q, k, _ = fused_rotary_position_embedding(
                q, k, None, position_ids=pos_t,
                rotary_emb_base=at.cfg.rope_theta)
            if isinstance(kp, tuple):
                # int8 cache: quantize-on-append (deterministic
                # rounding — recompute regenerates identical pages),
                # codes and per-(slot, head) scales scattered side by
                # side; padded lanes land on the scratch page
                kq, ksc = kp
                vq, vsc = vp
                npg, ps, _, _ = kq.shape
                knq, kns = quantize_q8(k._data.reshape(b * s, nkv, hd))
                vnq, vns = quantize_q8(v._data.reshape(b * s, nkv, hd))
                kq = kq.reshape(npg * ps, nkv, hd).at[flat_slots].set(
                    knq).reshape(npg, ps, nkv, hd)
                ksc = ksc.reshape(npg * ps, nkv).at[flat_slots].set(
                    kns).reshape(npg, ps, nkv)
                vq = vq.reshape(npg * ps, nkv, hd).at[flat_slots].set(
                    vnq).reshape(npg, ps, nkv, hd)
                vsc = vsc.reshape(npg * ps, nkv).at[flat_slots].set(
                    vns).reshape(npg, ps, nkv)
                kp = (kq, ksc)
                vp = (vq, vsc)
            else:
                npg, ps, _, _ = kp.shape
                kp = kp.reshape(npg * ps, nkv, hd).at[flat_slots].set(
                    k._data.reshape(b * s, nkv, hd).astype(kp.dtype)
                ).reshape(npg, ps, nkv, hd)
                vp = vp.reshape(npg * ps, nkv, hd).at[flat_slots].set(
                    v._data.reshape(b * s, nkv, hd).astype(vp.dtype)
                ).reshape(npg, ps, nkv, hd)
            if spmd:
                # pin the freshly-scattered pools back to the head
                # sharding: the scatter is shard-aligned (values and
                # pools split on the same kv-head axis) and the pinned
                # outputs carry the layout into the NEXT step's
                # operands with no host round-trip
                kp = tp.shard_pool(kp)
                vp = tp.shard_pool(vp)
            new_k.append(kp)
            new_v.append(vp)
            if ragged is None:
                out = paged_attention(
                    q._data, kp, vp, pt, cl, positions[:, 0],
                    scale=1.0 / (hd ** 0.5), window=window, spmd=spmd)
            else:
                ql, qoff = ragged
                out = ragged_paged_attention(
                    q._data[0], kp, vp, pt, cl, ql, qoff,
                    scale=1.0 / (hd ** 0.5), window=window,
                    spmd=spmd)[None]
            ao = Tensor(out).reshape([b, s, nh * hd])
            if spmd:
                # o_proj contracts over the head dim — gather the
                # head-sharded attention rows first, then replicate
                # o_proj's column-sharded output before the residual
                ao = Tensor(tp.replicate(ao._data))
                o = at.o_proj(ao)
                h = x + Tensor(tp.replicate(o._data))
                h2 = layer.post_attention_layernorm(h)
                g = layer.mlp.gate_proj(h2)
                u = layer.mlp.up_proj(h2)
                a = swiglu(g, u)
                # down_proj contracts over the ffn dim gate/up sharded
                a = Tensor(tp.replicate(a._data))
                mo = layer.mlp.down_proj(a)
                x = h + Tensor(tp.replicate(mo._data))
            else:
                h = x + at.o_proj(ao)
                x = h + layer.mlp(layer.post_attention_layernorm(h))
        x = core.norm(x)
    return x._data, new_k, new_v


def _paged_step_body(model, core, window, tp, sample_capable,
                     multi_pos, ids, positions, pt, cl, slot_map,
                     last_idx, samp, k_pages, v_pages):
    import jax.numpy as jnp

    from ..core.autograd import no_grad
    from ..core.tensor import Tensor

    x, new_k, new_v = _paged_forward(core, window, ids, positions, pt,
                                     cl, slot_map, k_pages, v_pages,
                                     tp=tp)
    from .sampling import fused_sample, fused_sample_multi
    do_sample, temperature, top_k, top_p, seeds, steps = samp
    if multi_pos:
        # speculative verify: logits + the target's own deterministic
        # sample at EVERY position of the extend (one [B, S] fetch);
        # the non-speculative path never takes this branch, keeping its
        # fetch at <= B*8 bytes
        with no_grad():
            logits = model.lm_head(Tensor(x))._data
        if tp is not None:
            # lm_head shards the vocab columns: gather the partial
            # (column-sliced, never partially-summed) logits so fused
            # sampling runs replicated — identical to TP=1
            logits = tp.replicate(logits)
        logits = logits.astype(jnp.float32)              # [B, S, V]
        tokens, logprobs = fused_sample_multi(
            logits, do_sample, temperature, top_k, top_p, seeds, steps,
            sample_capable=sample_capable)
        return tokens, logprobs, logits, new_k, new_v
    b = ids.shape[0]
    h_last = x[jnp.arange(b), last_idx]                  # [B, D]
    with no_grad():
        logits = model.lm_head(Tensor(h_last[:, None, :]))._data[:, 0]
    if tp is not None:
        # the all-gather happens only at the sampled lane: h_last
        # already dropped the S axis, so this moves [B, V] per step
        logits = tp.replicate(logits)
    logits = logits.astype(jnp.float32)
    # fused on-device sampling: the host fetches [B] ids (+logprobs),
    # not [B, V] logits; sample_capable is STATIC (greedy-only batches
    # compile without the top-k/top-p sort)
    tokens, logprobs = fused_sample(
        logits, do_sample, temperature, top_k, top_p, seeds, steps,
        sample_capable=sample_capable)
    return tokens, logprobs, logits, new_k, new_v


# -- the unified ragged step (round 22 / PR 18) ----------------------------

def _ragged_step_pure(model, core, window, tp, warrs, ids, positions,
                      pt, cl, ql, qoff, slot_map, samp, k_pages,
                      v_pages):
    tensors = model._gen_state_tensors()
    saved = [(t, t._data) for t in tensors]
    for t, arr in zip(tensors, warrs):
        t._data = arr
    try:
        return _ragged_step_body(model, core, window, tp, ids,
                                 positions, pt, cl, ql, qoff, slot_map,
                                 samp, k_pages, v_pages)
    finally:
        for t, arr in saved:
            t._data = arr


def _ragged_step_body(model, core, window, tp, ids, positions, pt, cl,
                      ql, qoff, slot_map, samp, k_pages, v_pages):
    """Token-packed unified step: the trunk runs at [1, T], lm_head +
    fused sampling cover EVERY packed token (each with its own
    per-token counter key — a verify token j carries steps0+j, exactly
    fused_sample_multi's flattened key; a prefill chunk's non-final
    tokens carry neutral params and their samples are discarded), and
    the host fetch is [T] ids + [T] logprobs. Always compiled
    sample-capable: greedy lanes take fused_sample's argmax/raw-logprob
    branch, so values match the greedy-compiled bucketed programs
    bit-for-bit while greedy and sampled steps share ONE class."""
    import jax.numpy as jnp

    from ..core.autograd import no_grad
    from ..core.tensor import Tensor

    x, new_k, new_v = _paged_forward(core, window, ids, positions, pt,
                                     cl, slot_map, k_pages, v_pages,
                                     ragged=(ql, qoff), tp=tp)
    from .sampling import fused_sample
    do_sample, temperature, top_k, top_p, seeds, steps = samp
    with no_grad():
        logits = model.lm_head(Tensor(x))._data[0]           # [T, V]
    if tp is not None:
        # partial (vocab-column-sliced) logits -> replicated before the
        # fused per-token sampling, same as the bucketed step
        logits = tp.replicate(logits)
    logits = logits.astype(jnp.float32)
    tokens, logprobs = fused_sample(
        logits, do_sample, temperature, top_k, top_p, seeds, steps,
        sample_capable=True)
    return tokens, logprobs, logits, new_k, new_v


# -- the fused draft-proposal scan (speculative decoding, round 12) --------

def _spec_draft_pure(draft, core, window, sample_capable, dwarrs, ids0,
                     pos0, pt, cl0, slot_mat, samp, k_pages, v_pages):
    tensors = draft._gen_state_tensors()
    saved = [(t, t._data) for t in tensors]
    for t, arr in zip(tensors, dwarrs):
        t._data = arr
    try:
        return _spec_draft_body(draft, core, window, sample_capable,
                                ids0, pos0, pt, cl0, slot_mat, samp,
                                k_pages, v_pages)
    finally:
        for t, arr in saved:
            t._data = arr


def _spec_draft_body(draft, core, window, sample_capable, ids0, pos0,
                     pt, cl0, slot_mat, samp, k_pages, v_pages):
    """k+1 chained draft steps inside ONE compiled program
    (``lax.scan``): step j feeds the previous token at position
    ``pos0 + j`` (slot ``slot_mat[:, j]``, context ``cl0 + j``) and
    samples the next proposal with the SAME counter key the target's
    verify step will use for that position — correlated Gumbel noise
    is what lets a well-matched draft accept at the argmax-agreement
    rate even on sampled lanes. Returns ``(proposals [B, k+1] int32,
    new_k, new_v)``."""
    import jax
    import jax.numpy as jnp

    from ..core.autograd import no_grad
    from ..core.tensor import Tensor
    from .sampling import fused_sample

    do_sample, temperature, top_k, top_p, seeds, steps0 = samp
    n_steps = slot_mat.shape[1]

    def step(carry, xs):
        j, slots = xs
        kps, vps, tok = carry
        x, nk, nv = _paged_forward(core, window, tok,
                                   (pos0 + j)[:, None], pt, cl0 + j,
                                   slots[:, None], kps, vps)
        with no_grad():
            logits = draft.lm_head(Tensor(x[:, -1:]))._data[:, 0]
        nxt, _ = fused_sample(
            logits.astype(jnp.float32), do_sample, temperature, top_k,
            top_p, seeds, steps0 + j, sample_capable=sample_capable)
        return (nk, nv, nxt[:, None]), nxt

    (new_k, new_v, _), toks = jax.lax.scan(
        step, (list(k_pages), list(v_pages), ids0),
        (jnp.arange(n_steps, dtype=jnp.int32),
         jnp.swapaxes(slot_mat, 0, 1)))
    return jnp.swapaxes(toks, 0, 1), new_k, new_v
