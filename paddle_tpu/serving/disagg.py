"""Disaggregated prefill/decode serving — split-phase routing with KV
page migration.

Production TPU serving separates the two generation phases because
their compute profiles differ (PAPERS.md Gemma-on-TPU): prefill is a
throughput-shaped batch matmul burst that sets TTFT, decode is a
latency-shaped steady stream that sets TPOT — on a symmetric fleet
they contend for the same step loop, so a TTFT-heavy burst stalls
every running stream.  :class:`DisaggRouter` splits them across
replica ROLES (advertised in ``/healthz``):

1. **Prefill** — admissions route to the least-loaded ``prefill``
   replica as ``prefill_only`` requests: chunked prefill runs to
   completion, the FIRST token is sampled (TTFT is the prefill
   replica's number) and the request is HELD — finish reason
   ``"prefilled"``, pages kept resident for export.  A prefill-only
   reservation is ``prompt+1`` pages, never ``prompt+max_new``, so a
   dedicated prefill replica admits bursts a mixed replica would shed.
2. **Migration** — the held sequence's KV page chain moves to the
   least-loaded ``decode`` replica
   (:meth:`PagedKVCache.export_pages` / ``import_pages``; in-process:
   array handoff, HTTP: the ``/v1/_pages`` endpoint).  The radix
   prefix tree is the TRANSFER INDEX: the destination is probed first
   and already-resident shared prefix pages are skipped — only the
   uncached suffix crosses the wire.  ``PrefixDrift`` (the
   destination's tree changed between probe and import) re-exports
   with the corrected skip and retries, bounded by
   ``PADDLE_TPU_SERVING_MIGRATE_RETRIES``.
3. **Decode** — the destination adopts the sequence
   (``adopt_request``: import + enter RUNNING, no prefill) and the
   router splices the two streams token-exactly: token ``t`` is pure
   in ``(weights, history, seed, t)`` (the PR-3 contract), the
   ``device_seed`` rides in the export meta, so the handoff point is
   invisible in the token stream — testable against a single-engine
   ``engine.run`` oracle in greedy AND seeded-sampled modes.

Failure at ANY point falls back to re-prefill on a survivor through
the existing failover path (delivered tokens spliced out); a
degenerate fleet — no routable prefill or no routable decode replica,
or an ``n>1`` fork request — routes mixed-mode through the base
:class:`ServingRouter` placement, so the disagg tier degrades to the
round-11 symmetric fleet, never to an outage.

Env knobs: ``PADDLE_TPU_SERVING_MIGRATE_RETRIES`` (PrefixDrift
re-export attempts per destination, default 2);
``PADDLE_TPU_SERVING_ROLE`` (a front-end's advertised role).
"""
from __future__ import annotations

import json
import logging
import os
import time

from .frontend import ROLES, Rejected, Unavailable
from .kv_cache import GeometryMismatch, PrefixDrift
from .pagewire import WireFormatError
from .replica import ReplicaFailed
from .router import RouterStream, ServingRouter

__all__ = ["DisaggRouter", "DisaggStream"]

_log = logging.getLogger("paddle_tpu.serving")

# kwargs that continue a migrated request on the decode replica
# (everything else — n, prefill_only — is placement-time only)
_ADOPT_KEYS = ("do_sample", "temperature", "top_k", "top_p", "seed",
               "logprobs", "request_id", "deadline_s", "speculative")


class DisaggStream(RouterStream):
    """One client stream spanning the prefill replica, the migration,
    and the decode replica.  The ``"prefilled"`` finish event is the
    handoff trigger, never a client event; everything else behaves
    like :class:`RouterStream` (splice bookkeeping carries across
    phases, so failover-replayed tokens are dropped exactly once)."""

    def __init__(self, router, req_id, prompt, kwargs, n):
        super().__init__(router, req_id, prompt, kwargs, n)
        self.phase = None        # prefill | decode | mixed
        self.migrations = 0

    def events(self, timeout=120.0, idle_s=None):
        while not self.done:
            try:
                migrate = False
                for ev in self._inner.events(timeout=timeout,
                                             idle_s=idle_s):
                    if ev["type"] == "idle":
                        yield ev
                        continue
                    idx = ev.get("index", 0)
                    if self._finished[idx]:
                        continue
                    if ev["type"] == "token":
                        if self._skip[idx] > 0:
                            self._skip[idx] -= 1   # splice: drop replay
                            continue
                        self._delivered[idx] += 1
                        self.router._token_delivered(self.replica_idx)
                        yield ev
                    elif ev["type"] == "finish":
                        if ev.get("reason") == "prefilled":
                            # handoff boundary — the decode stream
                            # continues this sample, the client never
                            # sees a finish here
                            migrate = True
                            break
                        self._finished[idx] = True
                        yield ev
                if migrate:
                    self.router._migrate(self)
                    continue
                break
            except TimeoutError:
                raise
            except RuntimeError as exc:  # replica death, either phase
                self.router._failover(self, exc)
        self.router._stream_done(self)


class DisaggRouter(ServingRouter):
    """A :class:`ServingRouter` that routes by replica role and splices
    prefill → decode via KV page migration.  Same client surface
    (``submit``/``cancel``/``health``/``prometheus``/``drain``), so a
    ``ServingServer`` fronts a disaggregated fleet unchanged."""

    stream_cls = DisaggStream

    def __init__(self, replicas, *, roles=None, migrate_retries=None,
                 **kw):
        super().__init__(replicas, **kw)
        if roles is not None:
            roles = list(roles)
            if len(roles) != len(self.replicas):
                raise ValueError(
                    f"{len(roles)} role(s) for {len(self.replicas)} "
                    "replica(s)")
            for r in roles:
                if r not in ROLES:
                    raise ValueError(
                        f"unknown role {r!r}; one of {ROLES}")
            self.roles = roles
        if migrate_retries is None:
            migrate_retries = int(os.environ.get(
                "PADDLE_TPU_SERVING_MIGRATE_RETRIES", "2") or 2)
        self.migrate_retries = max(1, int(migrate_retries))

    # -- role-aware placement ----------------------------------------------
    def _role_idxs(self, roles, exclude=()):
        return [i for i in self._routable(exclude)
                if self.roles[i] in roles]

    def _by_load(self, idxs):
        loads = self._loads(idxs)
        return sorted(idxs, key=lambda i: (loads[i], i))

    def _place(self, stream, exclude):
        """Disagg placement: least-loaded PREFILL replica, prefill-only
        admission.  Falls back to the base (mixed) placement on a
        degenerate fleet — no routable prefill or decode replica — and
        for n>1 fork requests (forks are created at prefill completion,
        which disagg moves across replicas)."""
        prefills = self._role_idxs(("prefill",), exclude)
        decodes = self._role_idxs(("decode",), exclude)
        if not prefills or not decodes \
                or int(stream.kwargs.get("n", 1)) > 1:
            stream.phase = "mixed"
            return super()._place(stream, exclude)
        stream.phase = "prefill"
        sheds = []
        ship_tried = False
        for idx in self._by_load(prefills):
            if not ship_tried:
                # fleet prefix cache (round 18): prefill replicas are
                # prefix-cache servers — a prefill placed on a cold
                # replica pulls the cached prefix from wherever the
                # fleet (prefill, decode or mixed) holds it and
                # chunk-prefills only the uncovered suffix
                ship_tried = True
                self._maybe_ship_prefix(stream, idx)
            try:
                inner = self.replicas[idx].submit(
                    stream.prompt, prefill_only=True, **stream.kwargs)
            except Rejected as e:
                sheds.append(e)
                continue
            except Unavailable:
                continue
            except ReplicaFailed as e:
                with self._lock:
                    self._down.add(idx)
                self._record_replica_failure(idx, e)
                _log.warning(json.dumps(
                    {"event": "router_replica_down", "replica": idx,
                     "cause": str(e)}))
                continue
            stream._inner = inner
            stream.replica_idx = idx
            self._breakers[idx].record_success()
            self.metrics.routed_total.inc(policy="disagg_prefill",
                                          replica=idx)
            if self.trace.enabled:
                self.trace.span(stream.req_id, "routed",
                                time.perf_counter(), replica=idx,
                                policy="disagg_prefill")
            if self.policy == "cache_aware" or self.prefix_fleet:
                self._record(stream.prompt, idx)
            return stream
        # every prefill replica shed or died: serve the request
        # mixed-mode on the rest of the fleet rather than 429ing work
        # the decode side could absorb
        stream.phase = "mixed"
        try:
            return super()._place(
                stream, exclude=set(exclude) | set(prefills))
        except (Rejected, Unavailable) as exc:
            if sheds:
                self.metrics.router_shed_total.inc()
                agg = Rejected(
                    "all replicas shed: " + "; ".join(
                        map(str, sheds + (
                            [exc] if isinstance(exc, Rejected) else []))))
                agg.retry_after = max(
                    float(getattr(e, "retry_after", 1))
                    for e in sheds + [exc])
                raise agg from exc
            raise

    # -- the migration (prefill -> decode handoff) -------------------------
    def _adopt_kwargs(self, stream):
        kw = {"max_new_tokens": stream.kwargs["max_new_tokens"]}
        for key in _ADOPT_KEYS:
            if stream.kwargs.get(key) is not None:
                kw[key] = stream.kwargs[key]
        return kw

    def _chaos_migration_fault(self, stream, dst_idx, point):
        """Evaluate one migration fault point; a firing is visible as
        a ``chaos`` span on the request's router timeline (plus the
        flight-ring record the injector makes)."""
        if not self.chaos.fire(point, to_replica=dst_idx,
                               request_id=stream.request_id):
            return False
        if self.trace.enabled:
            self.trace.span(stream.req_id, "chaos",
                            time.perf_counter(), point=point,
                            to_replica=dst_idx)
        return True

    def _migrate(self, stream):
        """Move the held sequence to a decode replica and swap the
        stream's inner phase.  Destination failures try the next
        decode replica; exhausting them falls back to a full
        re-prefill on any survivor (delivered tokens spliced); SOURCE
        failures raise so the caller's failover path re-prefills with
        the source marked down."""
        src_idx = stream.replica_idx
        src = self.replicas[src_idx]
        kwargs = self._adopt_kwargs(stream)
        mig_t0 = time.perf_counter()
        # decode replicas first, mixed as migration-capable spill
        order = self._by_load(
            self._role_idxs(("decode",), exclude={src_idx})) \
            + self._by_load(
                self._role_idxs(("mixed",), exclude={src_idx}))
        backoff = self.chaos.backoff()
        for dst_idx in order:
            dst = self.replicas[dst_idx]
            try:
                skip = dst.probe_pages(stream.prompt)
            except Exception:
                continue
            inner = None
            meta = None
            drift_left = self.migrate_retries
            transient = 0  # ReplicaFailed retries (bounded backoff)
            while True:
                # export MUST work: failures here are source failures
                # and escalate to the caller's failover path (the
                # chaos migrate_export_fail point models a partial
                # export — the source is treated as sick)
                try:
                    meta, k, v = src.export_pages(stream._inner, skip)
                except (KeyError, WireFormatError) as e:
                    # KeyError: nothing held; WireFormatError: the
                    # export was garbage but the source still holds
                    # pages — release before abandoning it (round-14)
                    try:
                        src.release_pages(stream._inner)
                    except Exception:  # pragma: no cover - src dying
                        pass
                    raise RuntimeError(
                        f"source replica {src_idx} lost the held "
                        f"pages: {e}") from e
                if self._chaos_migration_fault(stream, dst_idx,
                                               "migrate_export_fail"):
                    # the stream abandons the source: release its held
                    # pages NOW (best effort — the round-14 rule:
                    # anything that drops a request releases its
                    # pages; the held-deadline sweep is the backstop)
                    try:
                        src.release_pages(stream._inner)
                    except Exception:  # pragma: no cover - src dying
                        pass
                    raise RuntimeError(
                        "chaos: partial export from source replica "
                        f"{src_idx}")
                try:
                    if self._chaos_migration_fault(
                            stream, dst_idx, "migrate_import_bounce"):
                        raise GeometryMismatch(
                            "chaos: destination bounced the import")
                    if self._chaos_migration_fault(
                            stream, dst_idx, "migrate_transfer_kill"):
                        raise ReplicaFailed(
                            "chaos: destination died mid-transfer")
                    inner = dst.adopt(meta, k, v, **kwargs)
                    break
                except PrefixDrift as e:
                    drift_left -= 1
                    if drift_left <= 0:
                        break
                    skip = e.cached_pages  # re-export the right suffix
                except (Rejected, Unavailable, GeometryMismatch):
                    break
                except ReplicaFailed as e:
                    # transient destination failure: bounded retry with
                    # exponential backoff + jitter.  Retrying is safe —
                    # a failed adopt leaves no destination state (the
                    # import is transactional: GeometryMismatch/
                    # PrefixDrift/OutOfPages roll back) and the export
                    # is read-only.  Exhausting the budget marks the
                    # destination down and tries the next one.
                    if transient < backoff.retries:
                        self.metrics.retries_total.inc(op="migrate")
                        self.chaos.sleep(backoff.delay(transient))
                        transient += 1
                        continue
                    with self._lock:
                        self._down.add(dst_idx)
                    self._record_replica_failure(dst_idx, e)
                    _log.warning(json.dumps(
                        {"event": "router_replica_down",
                         "replica": dst_idx, "cause": str(e)}))
                    break
            if inner is None:
                continue
            try:
                src.release_pages(stream._inner)
            except Exception:  # pragma: no cover - source died after
                pass           # export; its pages die with it
            if hasattr(stream._inner, "close"):
                stream._inner.close()
            stream._inner = inner
            stream.replica_idx = dst_idx
            stream.phase = "decode"
            stream.migrations += 1
            self._breakers[dst_idx].record_success()
            n_pages = int(meta["n_pages"])
            self.metrics.migrations_total.inc()
            self.metrics.migrated_pages_total.inc(n_pages)
            self.metrics.routed_total.inc(policy="disagg_decode",
                                          replica=dst_idx)
            if self.prefix_fleet:
                # the adopted prompt pages committed into the decode
                # replica's tree: it is a prefix donor now
                self._record(stream.prompt, dst_idx)
            if self.trace.enabled:
                self.trace.span(
                    stream.req_id, "migration", mig_t0,
                    time.perf_counter() - mig_t0, pages=n_pages,
                    skip_pages=int(meta["skip_pages"]),
                    from_replica=src_idx, to_replica=dst_idx)
                self.trace.flight.record(
                    "migrate", from_replica=src_idx,
                    to_replica=dst_idx, pages=n_pages,
                    request_id=stream.request_id)
            _log.info(json.dumps({
                "event": "router_migrate", "from": src_idx,
                "to": dst_idx, "pages": n_pages,
                "skipped_cached_pages": int(meta["skip_pages"]),
                "request_id": stream.request_id,
                "router_req_id": stream.req_id}))
            return
        # no decode replica could adopt: re-prefill the whole request
        # on any survivor (zero lost tokens — splice covers the replay)
        try:
            src.release_pages(stream._inner)
        except Exception:
            pass
        self.metrics.migration_fallbacks_total.inc()
        if self.trace.enabled:
            self.trace.span(stream.req_id, "migration", mig_t0,
                            time.perf_counter() - mig_t0,
                            fallback=True, from_replica=src_idx)
            self.trace.flight.record("migrate_fallback",
                                     from_replica=src_idx,
                                     request_id=stream.request_id)
        _log.warning(json.dumps({
            "event": "router_migrate_fallback", "from": src_idx,
            "request_id": stream.request_id,
            "router_req_id": stream.req_id}))
        stream._skip = [d if not f else 0
                        for d, f in zip(stream._delivered,
                                        stream._finished)]
        stream.phase = "mixed"
        try:
            # base placement, NOT self._place: a second disagg attempt
            # would hold-and-migrate again and could loop forever on a
            # fleet whose decode side keeps refusing
            super()._place(stream, exclude=())
        except (Rejected, Unavailable) as e:
            raise RuntimeError(
                f"migration fallback failed for request "
                f"{stream.request_id or stream.req_id}: {e}") from e
