"""Hierarchical KV-cache tiers (round 20): host-RAM and disk page
pools behind the pagewire, with prefix restore and replica pre-warm.

At fleet scale the prefix working set dwarfs device HBM: the radix
tree's LRU eviction (``PagedKVCache._evict_lru_leaf``) used to simply
discard an rc-0 cached page, and every later miss paid full prompt
recompute.  This module keeps those pages alive in cheaper tiers:

- :class:`HostPagePool` — a byte-budgeted host-RAM LRU of spilled
  pages (``PADDLE_TPU_SERVING_HOST_POOL_MB``), SHARED freely across
  engines in one process (host RAM is a per-machine resource; the
  payload geometry is validated per-cache at restore, so dtype-skewed
  engines sharing a pool simply miss each other's entries).
- :class:`DiskPagePool` — an optional file-backed tier UNDER the host
  pool (``PADDLE_TPU_SERVING_DISK_POOL_MB`` / ``_DISK_POOL_DIR``):
  pages evicted from the RAM budget demote to disk instead of
  vanishing; a disk hit promotes back through RAM.
- :class:`KVTier` — the per-engine binding (pool + chaos injector +
  metrics + trace) whose :meth:`spill`/:meth:`restore`/:meth:`prewarm`
  are the ONLY blessed entry points into the pools (graftlint
  ``kvtier-blessed-access`` forbids reaching around them).

Spill path: ``_evict_lru_leaf`` hands the victim node over BEFORE
unlinking it.  The device bytes must be captured synchronously (the
page re-enters the free list and can be reused within the same
allocator call), via the SAME fused one-program gather the prefix
ships use; serialization + CRC + LRU insertion are deferred to
:meth:`KVTier.flush`, which the engine drains at step boundaries — the
allocator's eviction loop never serializes or touches the pool lock.
Each spilled page is stored as a standalone pagewire PREFIX payload
(``meta["kind"] == "prefix"``, one page, full token chain as the
prompt) keyed by its token chain, so restore re-enters through
``import_prefix_pages`` with the exact CACHED-rc==0 semantics of a
remote-donor ship — router code, admission accounting and drift
handling need no new cases.

Restore path: a prefix probe that misses device pages walks the host
tier chain-key by chain-key past the device match, concatenates the
per-page payloads, and lands them through the fused scatter.  Probe
order across the stack is local device → local host tier → remote
donor → recompute (the router consults the tier between its device
probe and the donor loop).

The contract is STRICTLY best-effort (the round-18 rule): any spill
or restore failure, geometry/dtype mismatch, CRC-detected corruption,
or capacity shed degrades to the recompute the engine would have done
anyway — never a failed or blocked request, never an exception out of
the blessed entry points.

Weight reloads: spilled K/V was computed under the OLD weights, so
``PagedKVCache.clear_prefix`` (the reload flush) also invalidates the
attached tier — stale pages must never restore after a reload.

Nothing here imports jax at module scope; the only device work is the
cache's own fused gather/scatter.
"""
from __future__ import annotations

import hashlib
import logging
import os
import tempfile
import threading
import time
from collections import OrderedDict

import numpy as np

from .pagewire import WireFormatError, deserialize_pages, serialize_pages

__all__ = ["DiskPagePool", "HostPagePool", "KVTier", "chain_key",
           "host_pool_from_env"]

_log = logging.getLogger("paddle_tpu.serving")

# tier sizing/behavior knobs (docs/ENV_KNOBS.md)
_ENV_HOST_MB = "PADDLE_TPU_SERVING_HOST_POOL_MB"
_ENV_DISK_MB = "PADDLE_TPU_SERVING_DISK_POOL_MB"
_ENV_DISK_DIR = "PADDLE_TPU_SERVING_DISK_POOL_DIR"
_ENV_PREWARM = "PADDLE_TPU_SERVING_HOST_POOL_PREWARM"

# deferred spills buffered before an inline flush (bounds the host RAM
# the un-serialized numpy payloads can pin if the owner never flushes)
_MAX_PENDING = 32


def chain_key(tokens):
    """Canonical pool key for a page chain: the raw little-endian int32
    bytes of the FULL token prefix up to and including the page (the
    radix path from the root).  Pure function of the tokens, so every
    engine sharing a pool computes identical keys."""
    return np.ascontiguousarray(
        np.asarray(tokens, np.int32).reshape(-1)).tobytes()


class DiskPagePool:
    """File-backed page tier under a :class:`HostPagePool`.

    One file per spilled page (the serialized pagewire payload,
    verbatim), LRU-evicted to a byte budget.  NOT independently
    thread-safe: every call happens under the owning HostPagePool's
    lock — the pool is the single writer/reader of this directory.
    """

    def __init__(self, dir_path=None, budget_bytes=64 * 2 ** 20):
        if dir_path is None:
            dir_path = tempfile.mkdtemp(prefix="pdtpu_kvtier_")
            self._owns_dir = True
        else:
            os.makedirs(dir_path, exist_ok=True)
            self._owns_dir = False
        self.dir = dir_path
        self.budget_bytes = int(budget_bytes)
        self._entries: OrderedDict[bytes, tuple[str, int]] = OrderedDict()
        self.bytes_used = 0
        self.write_errors = 0

    @property
    def pages(self):
        return len(self._entries)

    def _path(self, key):
        return os.path.join(self.dir,
                            hashlib.sha1(key).hexdigest() + ".ptkv")

    def put(self, key, payload):
        """Store one payload; evicts LRU files past the budget.  A
        payload larger than the whole budget is shed (False)."""
        if len(payload) > self.budget_bytes:
            return False
        if key in self._entries:
            self._entries.move_to_end(key)
            return True
        path = self._path(key)
        try:
            with open(path, "wb") as f:
                f.write(payload)
        except OSError:
            self.write_errors += 1
            return False
        self._entries[key] = (path, len(payload))
        self.bytes_used += len(payload)
        while self.bytes_used > self.budget_bytes:
            self.pop(next(iter(self._entries)))
        return True

    def get(self, key):
        ent = self._entries.get(key)
        if ent is None:
            return None
        path, nbytes = ent
        try:
            with open(path, "rb") as f:
                payload = f.read()
        except OSError:
            self.pop(key)
            return None
        if len(payload) != nbytes:  # torn write / external truncation
            self.pop(key)
            return None
        self._entries.move_to_end(key)
        return payload

    def pop(self, key):
        ent = self._entries.pop(key, None)
        if ent is None:
            return False
        path, nbytes = ent
        self.bytes_used -= nbytes
        try:
            os.remove(path)
        except OSError:
            pass
        return True

    def clear(self):
        for key in list(self._entries):
            self.pop(key)


class HostPagePool:
    """Byte-budgeted host-RAM LRU of spilled prefix pages, optionally
    backed by a :class:`DiskPagePool`.  Thread-safe and shareable
    across engines; all consistency-relevant state lives behind the
    lock and is exposed read-only via :meth:`snapshot` (the chaos
    cross-tier conservation check)."""

    def __init__(self, budget_bytes, disk=None):
        self.budget_bytes = int(budget_bytes)
        if self.budget_bytes < 0:
            raise ValueError(
                f"host pool budget must be >= 0, got {budget_bytes}")
        self.disk = disk
        self._lock = threading.RLock()
        # key -> payload bytes, LRU order (oldest first)
        self._entries: OrderedDict[bytes, bytes] = OrderedDict()
        self.bytes_used = 0
        # chain heat for pre-warm (hits survive demotion/eviction so a
        # re-spilled hot chain keeps its rank)
        self._hits: dict[bytes, int] = {}
        # counters (exported via snapshot/stats; engines mirror the
        # ones they care about into their own ServingMetrics)
        self.spilled_pages = 0
        self.restored_pages = 0
        self.demoted_pages = 0
        self.shed_pages = 0
        self.dropped_pages = 0

    # -- blessed write path ------------------------------------------------
    def put(self, key, payload):
        """Insert one spilled page payload.  Returns True when the
        payload is resident SOMEWHERE (RAM or disk) afterwards; False
        when it was shed (over-budget with no disk tier, or larger
        than every budget)."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            if len(payload) > self.budget_bytes:
                if self.disk is not None and self.disk.put(key, payload):
                    self.demoted_pages += 1
                    return True
                self.shed_pages += 1
                return False
            self._entries[key] = payload
            self.bytes_used += len(payload)
            self.spilled_pages += 1
            while self.bytes_used > self.budget_bytes:
                old_key, old_payload = self._entries.popitem(last=False)
                self.bytes_used -= len(old_payload)
                if self.disk is not None \
                        and self.disk.put(old_key, old_payload):
                    self.demoted_pages += 1
                else:
                    self.dropped_pages += 1
            return True

    def get(self, key):
        """Fetch a payload (RAM first, then disk).  A disk hit promotes
        back into RAM (which may demote the RAM LRU tail).  Returns
        None on a miss."""
        with self._lock:
            payload = self._entries.get(key)
            if payload is not None:
                self._entries.move_to_end(key)
                self._hits[key] = self._hits.get(key, 0) + 1
                return payload
            if self.disk is None:
                return None
            payload = self.disk.get(key)
            if payload is None:
                return None
            self._hits[key] = self._hits.get(key, 0) + 1
            if len(payload) <= self.budget_bytes:
                self.disk.pop(key)
                self._entries[key] = payload
                self.bytes_used += len(payload)
                while self.bytes_used > self.budget_bytes:
                    old_key, old_payload = self._entries.popitem(
                        last=False)
                    self.bytes_used -= len(old_payload)
                    if not self.disk.put(old_key, old_payload):
                        self.dropped_pages += 1
                    else:
                        self.demoted_pages += 1
            return payload

    def contains(self, key):
        """Residency probe with NO LRU/heat mutation (reservation-math
        safe, like ``PagedKVCache.probe_prefix``)."""
        with self._lock:
            if key in self._entries:
                return True
            return (self.disk is not None
                    and key in self.disk._entries)

    def pop(self, key):
        """Drop one entry from whichever tier holds it (the restore
        path's corrupt-payload disposal)."""
        with self._lock:
            payload = self._entries.pop(key, None)
            if payload is not None:
                self.bytes_used -= len(payload)
                self.dropped_pages += 1
                return True
            if self.disk is not None and self.disk.pop(key):
                self.dropped_pages += 1
                return True
            return False

    def clear(self):
        """Flush every tier (the weight-reload invalidation: spilled
        K/V of the OLD weights must never restore)."""
        with self._lock:
            self._entries.clear()
            self.bytes_used = 0
            self._hits.clear()
            if self.disk is not None:
                self.disk.clear()

    # -- blessed read-only views -------------------------------------------
    @property
    def pages(self):
        with self._lock:
            n = len(self._entries)
            if self.disk is not None:
                n += self.disk.pages
            return n

    def hottest(self, n):
        """The ``n`` hottest resident chain keys for pre-warm, deepest
        chains preferred: a key that is a strict prefix of another
        selected key is redundant (restoring the deeper chain pulls
        the whole path)."""
        with self._lock:
            resident = list(self._entries)
            if self.disk is not None:
                resident += list(self.disk._entries)
        resident.sort(key=lambda k: (self._hits.get(k, 0), len(k)),
                      reverse=True)
        picked = []
        for key in resident:
            if len(picked) >= int(n):
                break
            if any(p.startswith(key) for p in picked):
                continue
            picked = [p for p in picked if not key.startswith(p)]
            picked.append(key)
        return picked

    def stats(self):
        """Occupancy + counters (/healthz advertisement shape)."""
        with self._lock:
            out = {"host_pool_pages": len(self._entries),
                   "host_pool_bytes": self.bytes_used,
                   "host_pool_budget_bytes": self.budget_bytes,
                   "spilled_pages": self.spilled_pages,
                   "restored_pages": self.restored_pages,
                   "demoted_pages": self.demoted_pages,
                   "shed_pages": self.shed_pages,
                   "dropped_pages": self.dropped_pages}
            if self.disk is not None:
                out["disk_pool_pages"] = self.disk.pages
                out["disk_pool_bytes"] = self.disk.bytes_used
                out["disk_pool_budget_bytes"] = self.disk.budget_bytes
            return out

    def snapshot(self):
        """Consistency view for :func:`..chaos.verify_tier_conservation`
        — entry sizes per tier, so the invariant check never reaches
        into pool internals itself."""
        with self._lock:
            snap = {"entries": [(k, len(p))
                                for k, p in self._entries.items()],
                    "bytes_used": self.bytes_used,
                    "budget_bytes": self.budget_bytes,
                    "disk": None}
            if self.disk is not None:
                snap["disk"] = {
                    "entries": [(k, path, nbytes) for k, (path, nbytes)
                                in self.disk._entries.items()],
                    "bytes_used": self.disk.bytes_used,
                    "budget_bytes": self.disk.budget_bytes}
            return snap


def host_pool_from_env():
    """Build the host (and optional disk) tier from the env knobs;
    None when ``PADDLE_TPU_SERVING_HOST_POOL_MB`` is unset or 0."""
    try:
        host_mb = float(os.environ.get(_ENV_HOST_MB) or 0)
    except ValueError:
        host_mb = 0.0
    if host_mb <= 0:
        return None
    disk = None
    try:
        disk_mb = float(os.environ.get(_ENV_DISK_MB) or 0)
    except ValueError:
        disk_mb = 0.0
    if disk_mb > 0:
        disk = DiskPagePool(os.environ.get(_ENV_DISK_DIR) or None,
                            budget_bytes=int(disk_mb * 2 ** 20))
    return HostPagePool(int(host_mb * 2 ** 20), disk=disk)


def _prewarm_chains_default():
    try:
        return int(os.environ.get(_ENV_PREWARM) or 4)
    except ValueError:
        return 4


class KVTier:
    """Per-engine tier binding: one shared :class:`HostPagePool` plus
    the owning engine's chaos injector / metrics / trace.  The three
    public methods — :meth:`spill` (allocator hook), :meth:`restore`
    and :meth:`prewarm` — are the blessed pool entry points and NEVER
    raise: every failure degrades to the eviction/recompute the engine
    would have done anyway."""

    def __init__(self, pool, *, chaos=None, metrics=None, trace=None,
                 max_pending=_MAX_PENDING):
        self.pool = pool
        self.chaos = chaos
        self.metrics = metrics
        self.trace = trace
        self.max_pending = int(max_pending)
        # deferred spills: (key, meta, k_arrays, v_arrays) awaiting
        # serialization — appended by the allocator's eviction loop,
        # drained by flush() at step boundaries
        self._pending = []

    # -- spill (called from PagedKVCache._evict_lru_leaf) ------------------
    def spill(self, cache, node):
        """Capture an about-to-be-evicted rc-0 cached page.  Called
        with the radix tree still intact (the chain walk needs the
        victim's ancestors); the caller unlinks and frees the page
        right after, whatever happens here."""
        try:
            self._spill_inner(cache, node)
        except Exception:
            if self.metrics is not None:
                self.metrics.tier_spill_dropped.inc()

    def _spill_inner(self, cache, node):
        # full token chain root -> victim (each node's key is its
        # page's token tuple)
        parts = []
        walk = node
        while walk is not None and walk.key is not None:
            parts.append(walk.key)
            walk = walk.parent
        parts.reverse()
        tokens = [int(t) for chunk in parts for t in chunk]
        key = chain_key(tokens)
        if self.pool.contains(key):
            return  # restored earlier and re-evicted: already spilled
        # the device bytes must be captured NOW — the page re-enters
        # the free list and can be reused within this allocator call
        k, v = cache._fetch_pages([node.page])
        meta = dict(cache.geometry(), kind="prefix",
                    skip_pages=len(parts) - 1, n_pages=1,
                    cached_pages=len(parts), prompt=tokens)
        self._pending.append((key, meta, k, v))
        if len(self._pending) >= self.max_pending:
            self.flush()

    def flush(self):
        """Drain deferred spills: serialize (+CRC) and insert into the
        pool.  The engine calls this once per step; restore/prewarm
        call it first so their probes see every spilled page."""
        if not self._pending:
            return 0
        pending, self._pending = self._pending, []
        chaos, cfg = self.chaos, None
        if chaos is not None:
            cfg = chaos.cfg
        landed = 0
        for key, meta, k, v in pending:
            t0 = time.perf_counter()
            try:
                if chaos is not None \
                        and chaos.fire("tier_spill_fail", cfg=cfg):
                    raise RuntimeError("chaos: tier spill dropped")
                if chaos is not None \
                        and chaos.fire("tier_slow_io", cfg=cfg):
                    chaos.sleep(cfg.tier_slow_io_s)
                if not self.pool.put(key, serialize_pages(meta, k, v)):
                    raise RuntimeError("host pool shed the payload")
            except Exception:
                if self.metrics is not None:
                    self.metrics.tier_spill_dropped.inc()
                continue
            landed += 1
            if self.metrics is not None:
                self.metrics.tier_spill_pages.inc()
                self.metrics.tier_spill_s.record(
                    time.perf_counter() - t0)
        return landed

    # -- restore -----------------------------------------------------------
    def restore(self, cache, prompt):
        """Extend ``prompt``'s device-resident prefix chain from the
        host tier.  Returns the number of pages restored (0 on a miss
        or ANY failure — the caller's recompute covers it)."""
        try:
            return self._restore_inner(cache, prompt)
        except Exception:
            self._count_miss()
            return 0

    def _restore_inner(self, cache, prompt):
        if not cache.prefix_cache_enabled:
            return 0
        self.flush()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ps = cache.page_size
        cap = prompt.size // ps
        have = cache.probe_prefix(prompt, prompt.size + 1)
        if have >= cap:
            return 0  # fully device-resident: nothing to restore
        chaos, cfg = self.chaos, None
        if chaos is not None:
            cfg = chaos.cfg
            if chaos.fire("tier_restore_fail", cfg=cfg):
                self._count_miss()
                return 0
            if chaos.fire("tier_slow_io", cfg=cfg):
                chaos.sleep(cfg.tier_slow_io_s)
        t0 = time.perf_counter()
        # walk the tier chain-key by chain-key past the device match
        k_parts, v_parts = [], []
        depth = have
        while depth < cap:
            key = chain_key(prompt[:(depth + 1) * ps])
            payload = self.pool.get(key)
            if payload is None:
                break
            if chaos is not None \
                    and chaos.fire("tier_corrupt_payload", cfg=cfg):
                # at-rest bit-rot model: flip one byte in the array
                # region so the wire CRC (not a shape check) catches it
                payload = bytearray(payload)
                payload[-1] ^= 0xFF
                payload = bytes(payload)
            try:
                meta, k, v, _ = deserialize_pages(payload)
                cache.check_geometry(meta)
            except (WireFormatError, ValueError):
                # corrupt or mis-shaped at rest: dispose of the entry
                # and restore what we already have
                self.pool.pop(key)
                if self.metrics is not None:
                    self.metrics.tier_corrupt_dropped.inc()
                break
            k_parts.append(k)
            v_parts.append(v)
            depth += 1
        if not k_parts:
            self._count_miss()
            return 0
        # concatenate the single-page payloads into ONE import (one
        # fused scatter), entering with the same CACHED-rc==0 import
        # semantics as a remote-donor ship
        n = len(k_parts)
        k_cat = [np.concatenate([part[i] for part in k_parts])
                 for i in range(len(k_parts[0]))]
        v_cat = [np.concatenate([part[i] for part in v_parts])
                 for i in range(len(v_parts[0]))]
        meta = dict(cache.geometry(), kind="prefix", skip_pages=have,
                    n_pages=n, cached_pages=have,
                    prompt=[int(t) for t in prompt[:(have + n) * ps]])
        imported = cache.import_prefix_pages(meta, k_cat, v_cat)
        dt = time.perf_counter() - t0
        if self.metrics is not None:
            m = self.metrics
            m.tier_restore_pages.inc(imported)
            m.tier_restore_hits.inc()
            m.tier_restore_s.record(dt)
            self._sync_hit_rate()
        self.pool.restored_pages += imported
        if self.trace is not None and self.trace.enabled:
            self.trace.flight.record("tier_restore", pages=int(imported),
                                     skip_pages=int(have),
                                     wall_s=round(dt, 6))
        return imported

    def _count_miss(self):
        if self.metrics is not None:
            self.metrics.tier_restore_misses.inc()
            self._sync_hit_rate()

    def _sync_hit_rate(self):
        m = self.metrics
        hits = m.tier_restore_hits.value
        total = hits + m.tier_restore_misses.value
        if total:
            m.tier_restore_hit_rate.set(hits / total)

    # -- pre-warm (autoscaler grow hook) -----------------------------------
    def prewarm(self, cache, max_chains=None):
        """Restore the hottest spilled chains into ``cache`` — the
        newly-grown-replica warm-up.  Returns total pages restored;
        best-effort per chain."""
        try:
            n = (_prewarm_chains_default() if max_chains is None
                 else int(max_chains))
            if n <= 0:
                return 0
            self.flush()
            restored = 0
            for key in self.pool.hottest(n):
                restored += self.restore(
                    cache, np.frombuffer(key, np.int32))
            return restored
        except Exception:
            return 0

    # -- lifecycle ---------------------------------------------------------
    def invalidate(self):
        """Drop everything (weight reload: spilled K/V of the OLD
        weights must never restore).  Clears the SHARED pool — every
        engine on it reloads together in a rolling drain, and a stale
        entry served to any of them would be silent corruption."""
        self._pending = []
        try:
            self.pool.clear()
        except Exception:  # pragma: no cover - clear is in-memory
            pass

    def stats(self):
        out = self.pool.stats()
        out["pending_spills"] = len(self._pending)
        return out
