"""Metrics-driven fleet autoscaling for the (disaggregated) router.

A policy loop over the observability the serving tier already exports —
per-replica outstanding page reservations (``replica.load()``, the same
number ``/healthz`` shows as ``reserved_pages``) and the cumulative
Prometheus TTFT histograms in the router's merged ``/metrics`` — that
grows the fleet through a replica-factory callback and shrinks it
through the existing rolling-drain path
(:meth:`ServingRouter.retire_replica`: drain → zero lost requests →
close), per role and with hysteresis:

- **Scale up** a role when its mean reserved pages per routable replica
  stays above ``up_pages`` for ``up_window_s`` seconds, or when the
  fraction of requests whose TTFT exceeded ``ttft_slo_s`` in the last
  window stays above ``slo_breach_frac`` — sustained pressure, not a
  blip.  A role below its ``min`` floor is repaired immediately (no
  hysteresis: a dead-fleet window is an outage, not noise).
- **Scale down** when the role's mean load stays below ``down_pages``
  for ``down_window_s`` seconds and it sits above its ``min``; the
  least-loaded replica is retired through the rolling drain, so no
  in-flight request is lost and no admission 5xxs.
- **Breaker-fed pressure** (round 19, PR-10 follow-on): the router's
  per-replica circuit breakers and shed/failover counters feed the
  pressure signal — a fleet where breakers are opening or admissions
  are shedding is BROWNING OUT even while its mean reserved pages look
  fine (capacity exists, it just isn't healthy), so it grows before
  the SLOs blow.  ``breaker_frac`` (open breakers / non-retired
  replicas ≥ ``PADDLE_TPU_SERVING_AUTOSCALE_BREAKER_FRAC``) or a
  shed+failover window delta ≥ ``PADDLE_TPU_SERVING_AUTOSCALE_SHED_N``
  counts as sustained pressure through the same hysteresis window.
- **Drain-by-health rotation** (round 19): a FLAPPING replica — its
  breaker has opened ``PADDLE_TPU_SERVING_AUTOSCALE_FLAP_OPENS`` times
  — is rotated out rather than retried into: a replacement is
  provisioned FIRST, then the flapper drains out through
  ``retire_replica`` (its supervised process is reaped by the
  backend).  With :class:`~paddle_tpu.serving.fleet
  .ProcessReplicaBackend` as the factory (``backend=``), scale-ups
  spawn real replica server processes and retirements reap them.

Everything is deterministic and unit-testable: the loop never reads
wall time directly — ``clock=`` injects the time source (tests use a
fake clock plus scripted replica loads), ``tick()`` runs one
evaluation synchronously, and ``start()`` merely calls ``tick()`` on
``interval_s`` in a daemon thread.

Env knobs (constructor args win; see docs/ENV_KNOBS.md):
``PADDLE_TPU_SERVING_AUTOSCALE_S`` (loop interval, 0/unset = manual
ticks only), ``PADDLE_TPU_SERVING_AUTOSCALE_UP_PAGES``,
``PADDLE_TPU_SERVING_AUTOSCALE_DOWN_PAGES``,
``PADDLE_TPU_SERVING_AUTOSCALE_UP_S``,
``PADDLE_TPU_SERVING_AUTOSCALE_DOWN_S``,
``PADDLE_TPU_SERVING_AUTOSCALE_TTFT_SLO_S`` (unset disables the TTFT
signal), ``PADDLE_TPU_SERVING_AUTOSCALE_MIN`` /
``PADDLE_TPU_SERVING_AUTOSCALE_MAX`` (an integer for every role, or
``"prefill:1,decode:2"``), and the round-19 breaker-fed signals:
``PADDLE_TPU_SERVING_AUTOSCALE_BREAKER_FRAC`` (open-breaker fraction
counted as pressure; 0 disables), ``PADDLE_TPU_SERVING_AUTOSCALE_SHED_N``
(shed+failover window delta counted as pressure; 0 disables),
``PADDLE_TPU_SERVING_AUTOSCALE_FLAP_OPENS`` (breaker opens before a
replica is rotated out; 0 disables rotation).
"""
from __future__ import annotations

import json
import logging
import os
import re
import threading
import time

__all__ = ["FleetAutoscaler", "parse_role_spec"]

_log = logging.getLogger("paddle_tpu.serving")

_TTFT_BUCKET_RE = re.compile(
    r'^paddle_tpu_serving_ttft_s_bucket\{[^}]*le="([^"]+)"[^}]*\} '
    r'(\d+)$', re.M)


def parse_role_spec(spec, default):
    """``"3"`` → every role 3; ``"prefill:1,decode:2"`` → per-role
    with ``default`` for unnamed roles."""
    if spec is None or spec == "":
        return {"__default__": int(default)}
    if isinstance(spec, int):
        return {"__default__": int(spec)}
    if isinstance(spec, dict):
        out = {str(k): int(v) for k, v in spec.items()}
        out.setdefault("__default__", int(default))
        return out
    spec = str(spec)
    if ":" not in spec:
        return {"__default__": int(spec)}
    out = {"__default__": int(default)}
    for part in spec.split(","):
        role, _, n = part.partition(":")
        role, n = role.strip(), n.strip()
        if not role or not n:
            raise ValueError(f"bad role spec segment {part!r}")
        out[role] = int(n)
    return out


def _env_float(name, default):
    v = os.environ.get(name)
    return float(v) if v not in (None, "") else float(default)


class FleetAutoscaler:
    """Grows/shrinks a router's fleet per role from scripted-testable
    signals.  ``factory(role)`` must return an UNSTARTED replica
    (``router.add_replica`` starts it when the router is live)."""

    def __init__(self, router, factory=None, *, backend=None,
                 clock=None, interval_s=None,
                 min_per_role=None, max_per_role=None, up_pages=None,
                 down_pages=None, up_window_s=None, down_window_s=None,
                 ttft_slo_s=None, slo_breach_frac=0.1,
                 breaker_frac=None, shed_window_n=None,
                 flap_opens=None, deployer=None):
        self.router = router
        self.backend = backend
        # versioned deployment (round 21): freshly grown replicas are
        # built from the ORIGINAL weights — resync them to the
        # registry's latest published versions before traffic lands
        self.deployer = deployer
        if factory is None and backend is not None:
            # real provisioning (round 19): the backend spawns replica
            # server processes; retire_replica -> replica.close() reaps
            factory = backend.provision
        if factory is None:
            raise ValueError("need a replica factory or a backend")
        self.factory = factory
        self.clock = clock if clock is not None else time.monotonic
        self.interval_s = (
            _env_float("PADDLE_TPU_SERVING_AUTOSCALE_S", 0.0)
            if interval_s is None else float(interval_s))
        self.min_per_role = parse_role_spec(
            min_per_role
            if min_per_role is not None
            else os.environ.get("PADDLE_TPU_SERVING_AUTOSCALE_MIN"), 0)
        self.max_per_role = parse_role_spec(
            max_per_role
            if max_per_role is not None
            else os.environ.get("PADDLE_TPU_SERVING_AUTOSCALE_MAX"), 8)
        self.up_pages = (
            _env_float("PADDLE_TPU_SERVING_AUTOSCALE_UP_PAGES", 48.0)
            if up_pages is None else float(up_pages))
        self.down_pages = (
            _env_float("PADDLE_TPU_SERVING_AUTOSCALE_DOWN_PAGES", 8.0)
            if down_pages is None else float(down_pages))
        self.up_window_s = (
            _env_float("PADDLE_TPU_SERVING_AUTOSCALE_UP_S", 10.0)
            if up_window_s is None else float(up_window_s))
        self.down_window_s = (
            _env_float("PADDLE_TPU_SERVING_AUTOSCALE_DOWN_S", 60.0)
            if down_window_s is None else float(down_window_s))
        if ttft_slo_s is None:
            env = os.environ.get(
                "PADDLE_TPU_SERVING_AUTOSCALE_TTFT_SLO_S")
            ttft_slo_s = float(env) if env not in (None, "") else None
        self.ttft_slo_s = ttft_slo_s
        self.slo_breach_frac = float(slo_breach_frac)
        # breaker-fed signals (round 19)
        self.breaker_frac = (
            _env_float("PADDLE_TPU_SERVING_AUTOSCALE_BREAKER_FRAC",
                       0.34)
            if breaker_frac is None else float(breaker_frac))
        self.shed_window_n = (
            _env_float("PADDLE_TPU_SERVING_AUTOSCALE_SHED_N", 3.0)
            if shed_window_n is None else float(shed_window_n))
        self.flap_opens = int(
            _env_float("PADDLE_TPU_SERVING_AUTOSCALE_FLAP_OPENS", 3.0)
            if flap_opens is None else flap_opens)
        self._since: dict[tuple, float] = {}  # (role, dir) -> held since
        self._ttft_prev: dict[str, int] = {}  # le -> cumulative count
        self._shed_prev = 0.0    # shed+failover counters, last tick
        self._rotated: dict[int, int] = {}  # replica -> opens baseline
        self._stop = threading.Event()
        self._thread = None

    # -- limits ------------------------------------------------------------
    def _router(self):
        """The router to police this tick.  A RouterSupervisor's
        ``active`` may change across takeovers — resolve late so the
        policy loop follows the promotion instead of scaling a dead
        router."""
        return getattr(self.router, "active", None) or self.router

    def _limit(self, table, role):
        return int(table.get(role, table["__default__"]))

    def managed_roles(self):
        roles = {r for r in self._router().roles}
        roles |= {r for r in self.min_per_role if r != "__default__"}
        roles |= {r for r in self.max_per_role if r != "__default__"}
        return sorted(roles)

    # -- signals -----------------------------------------------------------
    def _role_state(self, role):
        """(routable indexes, mean reserved pages) for a role."""
        router = self._router()
        idxs = [i for i in router._routable()
                if router.roles[i] == role]
        loads = []
        for i in idxs:
            try:
                loads.append(float(router.replicas[i].load()))
            except Exception:
                loads.append(0.0)
        mean = sum(loads) / len(loads) if loads else 0.0
        return idxs, loads, mean

    def ttft_breach_frac(self):
        """Fraction of requests finishing prefill ABOVE the TTFT SLO in
        the window since the last call, from the cumulative
        ``ttft_s_bucket`` histogram lines of the router's merged
        /metrics (summed across replicas — cumulative buckets are the
        aggregatable form, which is why round 11 switched to them).
        None when the signal is disabled or the window saw no
        traffic."""
        if self.ttft_slo_s is None:
            return None
        try:
            text = self._router().prometheus()
        except Exception:
            return None
        totals: dict[str, int] = {}
        for le, count in _TTFT_BUCKET_RE.findall(text):
            totals[le] = totals.get(le, 0) + int(count)
        prev, self._ttft_prev = self._ttft_prev, totals
        d_inf = totals.get("+Inf", 0) - prev.get("+Inf", 0)
        if d_inf <= 0:
            return None
        # the tightest bucket bound covering the SLO (conservative:
        # requests inside it count as within-SLO)
        bounds = sorted((float(le), le) for le in totals
                        if le != "+Inf")
        le_slo = None
        for bound, le in bounds:
            if bound >= self.ttft_slo_s:
                le_slo = le
                break
        if le_slo is None:
            return 0.0  # SLO beyond the largest bucket: nothing breaches
        d_ok = totals.get(le_slo, 0) - prev.get(le_slo, 0)
        return max(0.0, 1.0 - d_ok / d_inf)

    def fleet_pressure(self):
        """The breaker-fed health signal (round 19): ``(open-breaker
        fraction over non-retired replicas, shed+failover delta since
        the last call)``.  Either crossing its threshold marks the
        fleet BROWNING OUT — unhealthy capacity is pressure even when
        mean load is not."""
        router = self._router()
        total = opens = 0
        for i in range(len(router.replicas)):
            if i in router._retired:
                continue
            total += 1
            try:
                if router._breakers[i].state == "open":
                    opens += 1
            except IndexError:  # pragma: no cover - grow race
                continue
        frac = opens / total if total else 0.0
        now_count = float(router.metrics.router_shed_total.value
                          + router.metrics.failovers_total.total)
        delta = max(0.0, now_count - self._shed_prev)
        self._shed_prev = now_count
        return frac, delta

    # -- policy ------------------------------------------------------------
    def _held_for(self, key, condition, now, window):
        """Hysteresis: True once ``condition`` has held continuously
        for ``window`` seconds (tracked via first-seen timestamps)."""
        if not condition:
            self._since.pop(key, None)
            return False
        since = self._since.setdefault(key, now)
        return (now - since) >= window

    def tick(self):
        """One policy evaluation.  Returns the scale events applied:
        ``[("up"|"down", role, replica_idx), ...]``."""
        now = self.clock()
        breach = self.ttft_breach_frac()
        brk_frac, shed_delta = self.fleet_pressure()
        browning = (brk_frac >= self.breaker_frac > 0) or (
            self.shed_window_n > 0 and shed_delta >= self.shed_window_n)
        events = []
        self._rotate_flappers(events)
        for role in self.managed_roles():
            idxs, loads, mean = self._role_state(role)
            n = len(idxs)
            lo = self._limit(self.min_per_role, role)
            hi = self._limit(self.max_per_role, role)
            if n < lo:
                # below the floor: repair immediately, no hysteresis
                idx = self._try_scale_up(role)
                if idx is not None:
                    events.append(("up", role, idx))
                self._since.pop((role, "up"), None)
                continue
            pressured = mean > self.up_pages or browning or (
                breach is not None and breach > self.slo_breach_frac)
            if n < hi and self._held_for((role, "up"), pressured, now,
                                         self.up_window_s):
                idx = self._try_scale_up(role)
                if idx is not None:
                    events.append(("up", role, idx))
                self._since.pop((role, "up"), None)
                continue
            idle = mean < self.down_pages and not pressured
            if n > lo and self._held_for((role, "down"), idle, now,
                                         self.down_window_s):
                victim = min(zip(loads, idxs))[1]
                self._scale_down(role, victim)
                events.append(("down", role, victim))
                self._since.pop((role, "down"), None)
        return events

    def _try_scale_up(self, role):
        """Chaos-hardened scale-up: a crashing replica factory (bad
        weights path, OOM, chaos test double) must not kill the policy
        loop or block the OTHER roles' evaluations this tick — log it
        and let the hysteresis retry next tick."""
        try:
            return self._scale_up(role)
        except Exception:
            _log.exception("autoscale replica factory failed for "
                           "role %r", role)
            return None

    def _scale_up(self, role):
        router = self._router()
        replica = self.factory(role)
        i = router.add_replica(replica, role=role)
        router.metrics.autoscale_events.inc(direction="up", role=role)
        _log.info(json.dumps({"event": "autoscale_up", "role": role,
                              "replica": i}))
        self._prewarm(replica, i)
        self._sync_weights(replica, i)
        return i

    def _sync_weights(self, replica, idx):
        """Versioned deployment (round 21): bring a freshly grown
        replica up to the registry's latest published weight versions
        (its factory built it from the original checkpoint).  Strictly
        best-effort — no deployer, an unversioned replica, or any
        failure leaves the replica serving its build-time weights,
        which is what scale-up meant before the deployer existed."""
        dep = self.deployer
        if dep is None:
            return
        try:
            synced = dep.sync_replica(replica)
        except Exception:  # best-effort: never fail a scale-up
            return
        if synced:
            _log.info(json.dumps({"event": "autoscale_weight_sync",
                                  "replica": idx,
                                  "synced": synced}))

    def _prewarm(self, replica, idx):
        """Hierarchical KV tier (round 20): a freshly grown replica
        starts with a cold device tree, but if its engine shares (or
        inherited) a host pool the hottest spilled chains can be
        restored BEFORE traffic lands.  Strictly best-effort — a
        tierless/older replica or any failure is simply a cold start,
        which is what scale-up meant before tiers existed."""
        fn = getattr(replica, "prewarm_prefix", None)
        if fn is None:
            return
        try:
            restored = int(fn())
        except Exception:
            return
        if restored:
            router = self._router()
            router.metrics.prewarm_restored_pages_total.inc(restored)
            _log.info(json.dumps({"event": "autoscale_prewarm",
                                  "replica": idx,
                                  "pages": restored}))

    def _scale_down(self, role, i):
        # rolling drain: zero lost requests, zero 5xx — retire blocks
        # this tick until the replica finished its in-flight work
        router = self._router()
        router.retire_replica(i)
        router.metrics.autoscale_events.inc(direction="down",
                                            role=role)
        _log.info(json.dumps({"event": "autoscale_down", "role": role,
                              "replica": i}))

    def _rotate_flappers(self, events):
        """Drain-by-health (round 19): a replica whose breaker has
        opened ``flap_opens`` times is flaky in a way retries make
        WORSE — rotate it out.  Replacement first (capacity never dips
        below the pre-rotation level; a failed factory aborts the
        rotation and the flapper keeps limping), then the flapper
        drains out through the rolling-retire path and its supervised
        process is reaped by ``replica.close()``."""
        if self.flap_opens <= 0:
            return
        router = self._router()
        # NOT _routable(): a flapper with an OPEN breaker is excluded
        # from routing — which is exactly why it needs rotating out
        for i in range(len(router.replicas)):
            if i in router._retired or i in router._down:
                continue
            try:
                opens = router._breakers[i].opens - self._rotated.get(
                    i, 0)
            except IndexError:  # pragma: no cover - shrink race
                continue
            if opens < self.flap_opens:
                continue
            role = router.roles[i]
            new_idx = self._try_scale_up(role)
            if new_idx is None:
                continue  # factory failed: retry next tick
            self._rotated[i] = router._breakers[i].opens
            router.retire_replica(i)
            router.metrics.autoscale_events.inc(direction="rotate",
                                                role=role)
            events.append(("rotate", role, i))
            _log.warning(json.dumps({
                "event": "autoscale_rotate_flapper", "role": role,
                "replica": i, "replacement": new_idx,
                "breaker_opens": opens}))

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Spin the policy loop (daemon) at ``interval_s``; a
        non-positive interval means manual ``tick()`` only."""
        if self.interval_s <= 0 or self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="serving-autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # pragma: no cover - loop must not die
                _log.exception("autoscaler tick failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
