"""Replica abstraction for the multi-replica serving tier.

A *replica* is one serving engine behind a uniform surface the
:class:`~paddle_tpu.serving.router.ServingRouter` can route to, health-
check, drain, and fail over from. Two implementations:

- :class:`InProcessReplica` — a :class:`ServingFrontend` wrapped
  directly (engine loop thread in this process). The default; fully
  testable on the CPU mesh, and the shape a TPU pod-slice deployment
  uses when one process owns several per-chip engines.
- :class:`HTTPReplica` — a client to a REMOTE ``ServingServer``
  (``/v1/completions`` SSE + ``/healthz`` + ``/metrics``), for the
  one-server-per-host topology. Stream parsing mirrors
  ``bench_serving.py --server``'s load generator; keepalive comment
  frames are consumed transparently.

Uniform surface::

    start()                      # idempotent
    submit(prompt, **kw) -> stream   (stream.events(timeout, idle_s))
    cancel_stream(stream)        # give the pages back
    health() -> dict             # {"status": ok|draining|failed|...}
    load() -> float              # outstanding page reservations
    prometheus() -> str          # text exposition (router merges)
    drain(timeout) / resume()    # rolling-drain primitive
    fail(exc)                    # fault hook (in-process only)

Failure signalling: a replica whose stream dies raises
:class:`ReplicaFailed` (HTTP transport errors, SSE truncation) or
``RuntimeError`` (the in-process engine loop died) from the stream
iterator — the router catches both and fails the request over.
"""
from __future__ import annotations

import http.client
import json
import socket
import time

import numpy as np

from .frontend import Rejected, ServingFrontend, Unavailable

__all__ = ["HTTPReplica", "InProcessReplica", "ReplicaFailed"]


class ReplicaFailed(RuntimeError):
    """The replica died mid-request (transport error, loop crash,
    truncated stream) — the router's signal to fail over."""


class InProcessReplica:
    """A ServingFrontend-wrapped engine living in this process."""

    kind = "inproc"

    def __init__(self, engine, *, max_queued=64, poll_interval_s=0.001,
                 name=None):
        self.frontend = ServingFrontend(
            engine, max_queued=max_queued,
            poll_interval_s=poll_interval_s)
        self.engine = engine
        self.name = name
        self._started = False

    def start(self):
        if not self._started:
            self.frontend.start()
            self._started = True
        return self

    def submit(self, prompt, **kw):
        return self.frontend.submit(prompt, **kw)

    def cancel_stream(self, stream):
        return self.frontend.cancel(stream.req_id)

    def health(self):
        return self.frontend.health()

    def load(self):
        return float(self.frontend.load())

    def prometheus(self):
        return self.frontend.prometheus()

    @property
    def state(self):
        return self.frontend.state

    def drain(self, timeout=120.0):
        return self.frontend.drain(timeout)

    def resume(self):
        self.frontend.resume()
        return self

    def reload(self, update_fn=None):
        """Weight-reload re-admit (call after :meth:`drain`): apply
        ``update_fn(model)`` if given — weights are ARGUMENTS of the
        compiled step, so the new values flow through with no recompile
        — flush the prefix cache (its K/V was computed under the OLD
        weights), and restart the loop."""
        if update_fn is not None:
            update_fn(self.engine.model)
        self.engine.cache.clear_prefix()
        return self.resume()

    def fail(self, exc=None):
        """Kill hook (router fault injection / tests): fail the loop
        as if it crashed — live pages released, open streams erred."""
        self.frontend.fail(exc or ReplicaFailed("replica killed"))

    def close(self, timeout=120.0):
        return self.frontend.close(timeout)


class _HTTPStream:
    """SSE consumer over one in-flight ``/v1/completions`` request —
    presents the same ``events(timeout, idle_s)`` surface as
    :class:`~paddle_tpu.serving.frontend.RequestStream`."""

    def __init__(self, conn, resp, req_id, n):
        self._conn = conn
        self._resp = resp
        self.req_id = req_id
        self.n = int(n)
        self._closed = False

    def events(self, timeout=120.0, idle_s=None):
        finishes = 0
        last = time.monotonic()
        sock_wait = idle_s if idle_s is not None else timeout
        try:
            self._conn.sock.settimeout(min(sock_wait, timeout))
        except (AttributeError, OSError):
            pass
        while finishes < self.n:
            try:
                raw = self._resp.fp.readline()
            except (socket.timeout, TimeoutError):
                if idle_s is not None \
                        and time.monotonic() - last < timeout:
                    yield {"type": "idle"}
                    continue
                raise TimeoutError(
                    f"replica stream {self.req_id}: no event within "
                    f"{timeout}s") from None
            except OSError as e:
                raise ReplicaFailed(
                    f"replica stream broke: {e!r}") from e
            if not raw:  # EOF before [DONE]: replica went away
                raise ReplicaFailed(
                    "replica stream ended without [DONE]")
            line = raw.strip()
            if not line or line.startswith(b":"):  # SSE keepalive
                continue
            if not line.startswith(b"data: "):
                continue
            if line == b"data: [DONE]":
                if finishes < self.n:
                    raise ReplicaFailed(
                        f"[DONE] after {finishes}/{self.n} finishes")
                break
            last = time.monotonic()
            ch = json.loads(line[6:])["choices"][0]
            if "token_id" in ch:
                ev = {"type": "token", "index": ch["index"],
                      "token": int(ch["token_id"])}
                if ch.get("logprob") is not None:
                    ev["logprob"] = float(ch["logprob"])
                yield ev
            if ch.get("finish_reason"):
                finishes += 1
                yield {"type": "finish", "index": ch["index"],
                       "reason": ch["finish_reason"]}
        self.close()

    def result(self, timeout=120.0):
        out = [{"tokens": [], "finish_reason": None}
               for _ in range(self.n)]
        for ev in self.events(timeout=timeout):
            slot = out[ev["index"]]
            if ev["type"] == "token":
                slot["tokens"].append(ev["token"])
            elif ev["type"] == "finish":
                slot["finish_reason"] = ev["reason"]
        return out

    def close(self):
        """Hang up. On an unfinished stream the remote server detects
        the disconnect (keepalive/next write) and cancels the request,
        freeing its pages. Both the response object and the connection
        must close — the response keeps the socket fd alive otherwise
        (CLAUDE.md round-9: ``sock.makefile`` refcount)."""
        if self._closed:
            return
        self._closed = True
        for obj in (self._resp, self._conn):
            try:
                obj.close()
            except OSError:
                pass


class HTTPReplica:
    """Client to a remote ``ServingServer``."""

    kind = "http"

    def __init__(self, host, port, *, timeout_s=120.0, name=None):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.name = name or f"{host}:{port}"

    def start(self):
        return self  # remote lifecycle is the remote operator's

    # -- requests ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, **kw):
        body = {"prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
                "max_tokens": int(max_new_tokens), "stream": True}
        if kw.get("do_sample"):
            body["temperature"] = float(kw.get("temperature", 1.0))
        for key in ("top_k", "top_p", "seed", "n", "deadline_s",
                    "speculative"):
            if kw.get(key) is not None:
                body[key] = kw[key]
        if kw.get("logprobs"):
            body["logprobs"] = True
        headers = {"Content-Type": "application/json"}
        if kw.get("request_id"):
            headers["X-Request-Id"] = str(kw["request_id"])
        try:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
            conn.request("POST", "/v1/completions", json.dumps(body),
                         headers)
            resp = conn.getresponse()
        except OSError as e:
            raise ReplicaFailed(
                f"replica {self.name} unreachable: {e!r}") from e
        if resp.status == 200:
            return _HTTPStream(conn, resp,
                               req_id=f"{self.name}/{id(resp):x}",
                               n=int(kw.get("n", 1)))
        payload = resp.read()
        retry_after = resp.getheader("Retry-After")
        conn.close()
        try:
            msg = json.loads(payload)["error"]["message"]
        except (ValueError, KeyError):
            msg = payload.decode(errors="replace")
        if resp.status == 429:
            exc = Rejected(f"replica {self.name}: {msg}")
            exc.retry_after = float(retry_after or 1)
            raise exc
        if resp.status == 503:
            raise Unavailable(f"replica {self.name}: {msg}")
        if resp.status == 400:
            raise ValueError(msg)
        raise ReplicaFailed(
            f"replica {self.name}: HTTP {resp.status}: {msg}")

    def cancel_stream(self, stream):
        stream.close()
        return True

    # -- observability -----------------------------------------------------
    def _get(self, path):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=10.0)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def health(self):
        try:
            status, data = self._get("/healthz")
        except OSError as e:
            return {"status": "unreachable", "error": repr(e)}
        try:
            out = json.loads(data)
        except ValueError:
            out = {"status": "failed"}
        if status != 200 and out.get("status") not in ("draining",):
            out.setdefault("status", "failed")
        return out

    @property
    def state(self):
        return self.health().get("status", "failed")

    def load(self):
        h = self.health()
        if "reserved_pages" in h:
            return float(h["reserved_pages"])
        return float(h.get("waiting", 0) + h.get("live", 0))

    def prometheus(self):
        try:
            status, data = self._get("/metrics")
        except OSError:
            return ""
        return data.decode() if status == 200 else ""

    # -- lifecycle (router-side only for remote replicas) ------------------
    def drain(self, timeout=120.0):
        """Remote drain is the remote operator's call; the router-side
        drain only stops routing here. Returns True when the remote
        reports idle (nothing waiting/live) within the timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            h = self.health()
            if h.get("status") == "unreachable":
                return False
            if not (h.get("waiting", 0) or h.get("live", 0)):
                return True
            time.sleep(0.05)
        return False

    def resume(self):
        return self

    def close(self, timeout=0.0):
        return True
