"""Replica abstraction for the multi-replica serving tier.

A *replica* is one serving engine behind a uniform surface the
:class:`~paddle_tpu.serving.router.ServingRouter` can route to, health-
check, drain, and fail over from. Two implementations:

- :class:`InProcessReplica` — a :class:`ServingFrontend` wrapped
  directly (engine loop thread in this process). The default; fully
  testable on the CPU mesh, and the shape a TPU pod-slice deployment
  uses when one process owns several per-chip engines.
- :class:`HTTPReplica` — a client to a REMOTE ``ServingServer``
  (``/v1/completions`` SSE + ``/healthz`` + ``/metrics``), for the
  one-server-per-host topology. Stream parsing mirrors
  ``bench_serving.py --server``'s load generator; keepalive comment
  frames are consumed transparently.

Uniform surface::

    start()                      # idempotent
    submit(prompt, **kw) -> stream   (stream.events(timeout, idle_s))
    cancel_stream(stream)        # give the pages back
    health() -> dict             # {"status": ok|draining|failed|...}
    load() -> float              # outstanding page reservations
    prometheus() -> str          # text exposition (router merges)
    drain(timeout) / resume()    # rolling-drain primitive
    fail(exc)                    # fault hook (in-process only)

Failure signalling: a replica whose stream dies raises
:class:`ReplicaFailed` (HTTP transport errors, SSE truncation) or
``RuntimeError`` (the in-process engine loop died) from the stream
iterator — the router catches both and fails the request over.
"""
from __future__ import annotations

import http.client
import json
import socket
import time

import numpy as np

from .chaos import ChaosConfig, ChaosInjector
from .frontend import Rejected, ServingFrontend, Unavailable

__all__ = ["HTTPReplica", "InProcessReplica", "ReplicaFailed"]


class ReplicaFailed(RuntimeError):
    """The replica died mid-request (transport error, loop crash,
    truncated stream) — the router's signal to fail over."""


class InProcessReplica:
    """A ServingFrontend-wrapped engine living in this process."""

    kind = "inproc"

    def __init__(self, engine, *, max_queued=64, poll_interval_s=0.001,
                 name=None, role=None):
        self.frontend = ServingFrontend(
            engine, max_queued=max_queued,
            poll_interval_s=poll_interval_s, role=role)
        self.engine = engine
        self.name = name
        self._started = False

    @property
    def role(self):
        return self.frontend.role

    def start(self):
        if not self._started:
            self.frontend.start()
            self._started = True
        return self

    def submit(self, prompt, **kw):
        return self.frontend.submit(prompt, **kw)

    def cancel_stream(self, stream):
        return self.frontend.cancel_stream(stream)

    def cancel_request(self, req_id):
        """Cancel an engine request by bare id — the recovered
        router's orphan-release path (round 19): a dead router's
        in-flight request has no stream object left to hand over, only
        the journaled id.  Pages (live AND held) free under the
        front-end lock."""
        return self.frontend.cancel(req_id)

    def health(self):
        return self.frontend.health()

    def load(self):
        return float(self.frontend.load())

    def prometheus(self):
        return self.frontend.prometheus()

    @property
    def state(self):
        return self.frontend.state

    def drain(self, timeout=120.0):
        return self.frontend.drain(timeout)

    def resume(self):
        self.frontend.resume()
        return self

    def reload(self, update_fn=None):
        """Weight-reload re-admit (call after :meth:`drain`): apply
        ``update_fn(model)`` if given — weights are ARGUMENTS of the
        compiled step, so the new values flow through with no recompile
        — flush the prefix cache (its K/V was computed under the OLD
        weights), and restart the loop."""
        if update_fn is not None:
            update_fn(self.engine.model)
        self.engine.cache.clear_prefix()
        return self.resume()

    def fail(self, exc=None):
        """Kill hook (router fault injection / tests): fail the loop
        as if it crashed — live pages released, open streams erred."""
        self.frontend.fail(exc or ReplicaFailed("replica killed"))

    def close(self, timeout=120.0):
        return self.frontend.close(timeout)

    # -- observability (round 16) ------------------------------------------
    def debug_trace(self, request_id=None):
        return self.frontend.debug_trace(request_id=request_id)

    def debug_flight(self):
        return self.frontend.debug_flight()

    # -- KV page migration (disagg tier) -----------------------------------
    def probe_pages(self, prompt):
        return self.frontend.probe_prefix(prompt)

    def export_pages(self, stream, skip_pages=0):
        return self.frontend.export_request(stream.req_id, skip_pages)

    def release_pages(self, stream):
        return self.frontend.release_request(stream.req_id)

    def adopt(self, meta, k_arrays, v_arrays, *, max_new_tokens, **kw):
        return self.frontend.adopt(meta, k_arrays, v_arrays,
                                   max_new_tokens=max_new_tokens, **kw)

    # -- fleet prefix transfer (round 18) ----------------------------------
    def cache_dtype(self):
        """The engine's resolved KV dtype — the router's dtype-skew
        guard reads it BEFORE scheduling a prefix ship (a mismatched
        payload would only bounce on GeometryMismatch later)."""
        return self.engine.cache_dtype

    def tp_degree(self):
        """The engine's tensor-parallel shard degree (round 23) —
        the router's tp-skew guard reads it before scheduling a
        transfer; per-shard pagewire payloads only splice between
        equal degrees."""
        return getattr(self.engine, "tp_degree", 1)

    def export_prefix(self, prompt, skip_pages=0):
        return self.frontend.export_prefix(prompt, skip_pages)

    def import_prefix(self, meta, k_arrays, v_arrays):
        return self.frontend.import_prefix(meta, k_arrays, v_arrays)

    def drop_prefix(self, prompt):
        return self.frontend.drop_prefix(prompt)

    # -- hierarchical KV tier (round 20) -----------------------------------
    def restore_prefix(self, prompt):
        return self.frontend.restore_prefix(prompt)

    def prewarm_prefix(self, max_chains=None):
        return self.frontend.prewarm_prefix(max_chains)

    # -- versioned live weight deployment (round 21) -----------------------
    def weight_version(self, which="target"):
        return self.frontend.weight_version(which)

    def swap_weights(self, which, arrays, version):
        """The deployer's per-replica hop: quiesce-swap under the
        front-end lock (the blessed path — graftlint
        ``weight-swap-lock``)."""
        return self.frontend.swap_weights(which, arrays, version)


class _HTTPStream:
    """SSE consumer over one in-flight ``/v1/completions`` request —
    presents the same ``events(timeout, idle_s)`` surface as
    :class:`~paddle_tpu.serving.frontend.RequestStream`."""

    def __init__(self, conn, resp, req_id, n, chaos=None):
        self._conn = conn
        self._resp = resp
        self.req_id = req_id
        self.n = int(n)
        self._closed = False
        self._chaos = chaos
        self.remote_id = None  # "cmpl-<engine req_id>" from the chunks

    @property
    def remote_req_id(self):
        """The REMOTE engine's integer request id (parsed from the SSE
        chunk ids) — what /v1/_pages/export needs to find the held
        pages on the remote server."""
        if self.remote_id is None:
            return None
        tail = self.remote_id.rsplit("-", 1)[-1]
        return int(tail) if tail.isdigit() else None

    def events(self, timeout=120.0, idle_s=None):
        finishes = 0
        last = time.monotonic()
        sock_wait = idle_s if idle_s is not None else timeout
        try:
            self._conn.sock.settimeout(min(sock_wait, timeout))
        except (AttributeError, OSError):
            pass
        while finishes < self.n:
            if self._chaos is not None \
                    and self._chaos.fire("http_midstream_eof",
                                         stream=self.req_id):
                # the transport died mid-decode: hang up for real so
                # the remote cancels the request (pages freed), then
                # signal the router's failover path
                self.close()
                raise ReplicaFailed(
                    "chaos: replica stream EOF mid-decode")
            try:
                raw = self._resp.fp.readline()
            except (socket.timeout, TimeoutError):
                if idle_s is not None \
                        and time.monotonic() - last < timeout:
                    yield {"type": "idle"}
                    continue
                raise TimeoutError(
                    f"replica stream {self.req_id}: no event within "
                    f"{timeout}s") from None
            except (OSError, AttributeError, ValueError) as e:
                # AttributeError/ValueError: the response was close()d
                # under us (router-crash teardown closes a dead
                # router's sockets mid-read) — same signal as a broken
                # transport: fail over
                raise ReplicaFailed(
                    f"replica stream broke: {e!r}") from e
            if not raw:  # EOF before [DONE]: replica went away
                raise ReplicaFailed(
                    "replica stream ended without [DONE]")
            line = raw.strip()
            if not line or line.startswith(b":"):  # SSE keepalive
                continue
            if not line.startswith(b"data: "):
                continue
            if line == b"data: [DONE]":
                if finishes < self.n:
                    raise ReplicaFailed(
                        f"[DONE] after {finishes}/{self.n} finishes")
                break
            last = time.monotonic()
            obj = json.loads(line[6:])
            if obj.get("id"):
                self.remote_id = obj["id"]
            ch = obj["choices"][0]
            if "token_id" in ch:
                ev = {"type": "token", "index": ch["index"],
                      "token": int(ch["token_id"])}
                if ch.get("logprob") is not None:
                    ev["logprob"] = float(ch["logprob"])
                yield ev
            if ch.get("finish_reason"):
                finishes += 1
                yield {"type": "finish", "index": ch["index"],
                       "reason": ch["finish_reason"]}
        self.close()

    def result(self, timeout=120.0):
        out = [{"tokens": [], "finish_reason": None}
               for _ in range(self.n)]
        for ev in self.events(timeout=timeout):
            slot = out[ev["index"]]
            if ev["type"] == "token":
                slot["tokens"].append(ev["token"])
            elif ev["type"] == "finish":
                slot["finish_reason"] = ev["reason"]
        return out

    def close(self):
        """Hang up. On an unfinished stream the remote server detects
        the disconnect (keepalive/next write) and cancels the request,
        freeing its pages. Both the response object and the connection
        must close — the response keeps the socket fd alive otherwise
        (CLAUDE.md round-9: ``sock.makefile`` refcount)."""
        if self._closed:
            return
        self._closed = True
        for obj in (self._resp, self._conn):
            try:
                obj.close()
            except OSError:
                pass


class HTTPReplica:
    """Client to a remote ``ServingServer``."""

    kind = "http"

    def __init__(self, host, port, *, timeout_s=120.0, name=None,
                 role=None, chaos=None):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.name = name or f"{host}:{port}"
        self._role = role  # None -> lazily read from /healthz
        self._cache_dtype = None  # lazily read from /healthz
        self._tp_degree = None  # lazily read from /healthz
        # chaos layer (round 17): network fault injection (connect
        # refused / mid-stream EOF / slow reads) + the retry knobs for
        # the idempotent hops below
        if isinstance(chaos, ChaosInjector):
            self.chaos = chaos
        else:
            assert chaos is None or isinstance(chaos, ChaosConfig)
            self.chaos = ChaosInjector(chaos, name=f"http:{self.name}")
        self.retry_count = 0  # transport retries (router /metrics)

    def _chaos_connect(self):
        """The connect-refused fault point, evaluated before any real
        socket work (the raise matches a dead listener's errno path)."""
        if self.chaos.fire("http_connect", replica=self.name):
            raise ConnectionRefusedError(
                f"chaos: connection to {self.name} refused")

    def _chaos_slow_read(self):
        if self.chaos.fire("http_slow_read", replica=self.name):
            self.chaos.sleep(self.chaos.cfg.slow_read_s)

    @property
    def role(self):
        """The remote front-end's advertised role (cached; the remote
        sets it at start-up and it never changes mid-life)."""
        if self._role is None:
            self._role = self.health().get("role", "mixed")
        return self._role

    def cache_dtype(self):
        """The remote engine's advertised KV dtype (cached — fixed for
        the engine's lifetime); None when the advertisement is
        unreachable, in which case the router falls back to the
        GeometryMismatch bounce."""
        if self._cache_dtype is None:
            self._cache_dtype = self.health().get("cache_dtype")
        return self._cache_dtype

    def tp_degree(self):
        """The remote engine's advertised tensor-parallel degree
        (cached — fixed for the engine's lifetime); None when the
        advertisement is unreachable, in which case the router falls
        back to the GeometryMismatch bounce."""
        if self._tp_degree is None:
            self._tp_degree = self.health().get("tp_degree")
        return self._tp_degree

    def start(self):
        return self  # remote lifecycle is the remote operator's

    # -- requests ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, **kw):
        body = {"prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
                "max_tokens": int(max_new_tokens), "stream": True}
        if kw.get("do_sample"):
            body["temperature"] = float(kw.get("temperature", 1.0))
        for key in ("top_k", "top_p", "seed", "n", "deadline_s",
                    "speculative", "prefill_only"):
            if kw.get(key) is not None:
                body[key] = kw[key]
        if kw.get("logprobs"):
            body["logprobs"] = True
        headers = {"Content-Type": "application/json"}
        if kw.get("request_id"):
            headers["X-Request-Id"] = str(kw["request_id"])
        try:
            self._chaos_connect()
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
            conn.request("POST", "/v1/completions", json.dumps(body),
                         headers)
            self._chaos_slow_read()
            resp = conn.getresponse()
        except OSError as e:
            raise ReplicaFailed(
                f"replica {self.name} unreachable: {e!r}") from e
        if resp.status == 200:
            return _HTTPStream(conn, resp,
                               req_id=f"{self.name}/{id(resp):x}",
                               n=int(kw.get("n", 1)),
                               chaos=self.chaos)
        payload = resp.read()
        retry_after = resp.getheader("Retry-After")
        conn.close()
        try:
            msg = json.loads(payload)["error"]["message"]
        except (ValueError, KeyError):
            msg = payload.decode(errors="replace")
        if resp.status == 429:
            exc = Rejected(f"replica {self.name}: {msg}")
            exc.retry_after = float(retry_after or 1)
            raise exc
        if resp.status == 503:
            raise Unavailable(f"replica {self.name}: {msg}")
        if resp.status == 400:
            raise ValueError(msg)
        raise ReplicaFailed(
            f"replica {self.name}: HTTP {resp.status}: {msg}")

    def cancel_stream(self, stream):
        stream.close()
        return True

    def cancel_request(self, req_id):
        """Best-effort orphan release by remote request id
        (``/v1/_pages/release`` frees HELD pages).  A RUNNING remote
        request cannot be cancelled without its connection — the dead
        router's sockets closing (disconnect-cancel) and the
        held-deadline sweep are the backstops."""
        try:
            status, data = self._post_json("/v1/_pages/release",
                                           {"req_id": int(req_id)})
        except (OSError, ReplicaFailed, ValueError, TypeError):
            return False
        try:
            return status == 200 and bool(
                json.loads(data).get("released"))
        except ValueError:
            return False

    # -- KV page migration (disagg tier, /v1/_pages) -----------------------
    def _retrying(self, fn, what):
        """Bounded retry with exponential backoff + jitter for the
        IDEMPOTENT hops (probe/export/release/healthz/metrics — reads
        and at-most-once releases; ``submit``/``adopt`` are NOT routed
        here, the router's failover/re-prefill contract covers those).
        Transport errors only; HTTP status handling stays with the
        caller.  Sleeps go through the chaos sleeper."""
        backoff = self.chaos.backoff()
        attempt = 0
        while True:
            try:
                return fn()
            except OSError as e:
                if attempt >= backoff.retries:
                    raise ReplicaFailed(
                        f"replica {self.name} unreachable after "
                        f"{attempt} retr"
                        f"{'y' if attempt == 1 else 'ies'} "
                        f"({what}): {e!r}") from e
                self.retry_count += 1
                self.chaos.sleep(backoff.delay(attempt))
                attempt += 1

    def _post_json(self, path, obj, timeout=None):
        def once():
            self._chaos_connect()
            conn = http.client.HTTPConnection(
                self.host, self.port,
                timeout=timeout or self.timeout_s)
            try:
                conn.request("POST", path, json.dumps(obj),
                             {"Content-Type": "application/json"})
                self._chaos_slow_read()
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
        return self._retrying(once, f"POST {path}")

    def probe_pages(self, prompt):
        status, data = self._post_json(
            "/v1/_pages/probe",
            {"prompt": [int(t) for t in np.asarray(prompt).reshape(-1)]})
        if status != 200:
            raise ReplicaFailed(
                f"replica {self.name}: probe HTTP {status}")
        return int(json.loads(data)["cached_pages"])

    def export_pages(self, stream, skip_pages=0):
        rid = stream.remote_req_id
        if rid is None:
            raise ReplicaFailed(
                f"replica {self.name}: stream carried no chunk id — "
                "cannot address its held pages")
        status, data = self._post_json(
            "/v1/_pages/export",
            {"req_id": rid, "skip_pages": int(skip_pages)})
        if status != 200:
            raise ReplicaFailed(
                f"replica {self.name}: export HTTP {status}: "
                f"{data[:200]!r}")
        from .pagewire import deserialize_pages
        meta, k, v, _ = deserialize_pages(data)
        return meta, k, v

    def release_pages(self, stream):
        rid = stream.remote_req_id
        if rid is None:
            return False
        status, data = self._post_json("/v1/_pages/release",
                                       {"req_id": rid})
        return status == 200 and bool(json.loads(data).get("released"))

    def adopt(self, meta, k_arrays, v_arrays, *, max_new_tokens, **kw):
        """POST the page payload to the remote ``/v1/_pages`` endpoint;
        the response IS the SSE continuation stream."""
        from .kv_cache import GeometryMismatch, PrefixDrift
        from .pagewire import serialize_pages
        request = {"max_tokens": int(max_new_tokens)}
        if kw.get("do_sample"):
            request["temperature"] = float(kw.get("temperature", 1.0))
        for key in ("top_k", "top_p", "seed", "deadline_s",
                    "speculative"):
            if kw.get(key) is not None:
                request[key] = kw[key]
        if kw.get("logprobs"):
            request["logprobs"] = True
        if kw.get("request_id"):
            request["request_id"] = str(kw["request_id"])
        payload = serialize_pages(meta, k_arrays, v_arrays,
                                  request=request)
        try:
            self._chaos_connect()
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
            conn.request("POST", "/v1/_pages", payload,
                         {"Content-Type":
                          "application/x-paddle-tpu-kv-pages"})
            self._chaos_slow_read()
            resp = conn.getresponse()
        except OSError as e:
            raise ReplicaFailed(
                f"replica {self.name} unreachable: {e!r}") from e
        if resp.status == 200:
            return _HTTPStream(conn, resp,
                               req_id=f"{self.name}/{id(resp):x}", n=1,
                               chaos=self.chaos)
        data = resp.read()
        conn.close()
        try:
            err = json.loads(data)["error"]
        except (ValueError, KeyError):
            err = {"message": data.decode(errors="replace")}
        msg = err.get("message", "")
        if resp.status == 409:
            if "cached_pages" in err:
                raise PrefixDrift(int(meta.get("skip_pages", 0)),
                                  int(err["cached_pages"]))
            raise GeometryMismatch(f"replica {self.name}: {msg}")
        if resp.status == 429:
            exc = Rejected(f"replica {self.name}: {msg}")
            exc.retry_after = float(
                resp.getheader("Retry-After") or 1)
            raise exc
        if resp.status == 503:
            raise Unavailable(f"replica {self.name}: {msg}")
        if resp.status == 400:
            raise ValueError(msg)
        raise ReplicaFailed(
            f"replica {self.name}: adopt HTTP {resp.status}: {msg}")

    # -- fleet prefix transfer (round 18, /v1/_pages/prefix) ---------------
    def export_prefix(self, prompt, skip_pages=0):
        """Fetch the remote's cached prefix payload.  The
        ``prefix_wire_truncate`` chaos point clips the received bytes
        (a torn transfer), which deserialization rejects — the router's
        recompute fallback covers it."""
        from .kv_cache import PrefixDrift
        from .pagewire import deserialize_pages
        status, data = self._post_json(
            "/v1/_pages/prefix/export",
            {"prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
             "skip_pages": int(skip_pages)})
        if status == 409:
            try:
                err = json.loads(data)["error"]
            except (ValueError, KeyError):
                err = {}
            raise PrefixDrift(int(skip_pages),
                              int(err.get("cached_pages", 0)))
        if status != 200:
            raise ReplicaFailed(
                f"replica {self.name}: prefix export HTTP {status}: "
                f"{data[:200]!r}")
        if self.chaos.fire("prefix_wire_truncate", replica=self.name):
            data = data[:max(0, len(data) // 2)]
        meta, k, v, _ = deserialize_pages(data)
        return meta, k, v

    def import_prefix(self, meta, k_arrays, v_arrays):
        """POST a prefix payload to the remote tree; returns the
        imported page count.  409 maps back to PrefixDrift (with the
        remote's true cached count) or GeometryMismatch, 429 to
        Rejected — the same bounce contract as adoption."""
        from .kv_cache import GeometryMismatch, PrefixDrift
        from .pagewire import serialize_pages
        payload = serialize_pages(meta, k_arrays, v_arrays)

        def once():
            self._chaos_connect()
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
            try:
                conn.request("POST", "/v1/_pages/prefix", payload,
                             {"Content-Type":
                              "application/x-paddle-tpu-kv-pages"})
                self._chaos_slow_read()
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
        status, data = self._retrying(once, "POST /v1/_pages/prefix")
        if status == 200:
            return int(json.loads(data).get("imported_pages", 0))
        try:
            err = json.loads(data)["error"]
        except (ValueError, KeyError):
            err = {"message": data.decode(errors="replace")}
        msg = err.get("message", "")
        if status == 409:
            if "cached_pages" in err:
                raise PrefixDrift(int(meta.get("skip_pages", 0)),
                                  int(err["cached_pages"]))
            raise GeometryMismatch(f"replica {self.name}: {msg}")
        if status == 429:
            exc = Rejected(f"replica {self.name}: {msg}")
            exc.retry_after = 1.0
            raise exc
        if status == 503:
            raise Unavailable(f"replica {self.name}: {msg}")
        if status == 400:
            raise ValueError(msg)
        raise ReplicaFailed(
            f"replica {self.name}: prefix import HTTP {status}: {msg}")

    def drop_prefix(self, prompt):
        status, data = self._post_json(
            "/v1/_pages/prefix/drop",
            {"prompt": [int(t) for t in np.asarray(prompt).reshape(-1)]})
        if status != 200:
            raise ReplicaFailed(
                f"replica {self.name}: prefix drop HTTP {status}")
        return int(json.loads(data).get("dropped_pages", 0))

    # -- hierarchical KV tier (round 20) -----------------------------------
    def restore_prefix(self, prompt):
        """Ask the remote to restore ``prompt``'s prefix from its OWN
        host tier.  Strictly best-effort (the tier contract): any
        transport/HTTP failure is a 0-page miss, never an error."""
        try:
            status, data = self._post_json(
                "/v1/_pages/prefix/restore",
                {"prompt":
                 [int(t) for t in np.asarray(prompt).reshape(-1)]})
            if status != 200:
                return 0
            return int(json.loads(data).get("restored_pages", 0))
        except (OSError, ReplicaFailed, ValueError, TypeError, KeyError):
            return 0

    def prewarm_prefix(self, max_chains=None):
        """Ask the remote to pre-warm its hottest spilled chains
        (autoscaler grow hook).  Best-effort: 0 on any failure."""
        try:
            body = {}
            if max_chains is not None:
                body["max_chains"] = int(max_chains)
            status, data = self._post_json("/v1/_pages/prefix/prewarm",
                                           body)
            if status != 200:
                return 0
            return int(json.loads(data).get("restored_pages", 0))
        except (OSError, ReplicaFailed, ValueError, TypeError, KeyError):
            return 0

    # -- versioned live weight deployment (round 21) -----------------------
    def weight_version(self, which="target"):
        """FRESH /healthz read EVERY call, deliberately unlike
        ``cache_dtype`` (cached forever — fixed for an engine's life):
        the weight version is mutable mid-life, and a cached value
        here is exactly the stale-advertisement hazard the
        ``deploy_stale_version`` chaos point models.  None when
        unreachable or the remote predates versioning."""
        wv = self.health().get("weight_version")
        if not isinstance(wv, dict):
            return None
        v = wv.get(which)
        return int(v) if v is not None else None

    def swap_weights(self, which, arrays, version):
        """Push a weight payload to the remote's quiesce-swap endpoint
        (npz-over-JSON — sized for draft-scale sets, the online-distill
        case; fleet-scale target pushes ride a shared registry dir +
        in-process deployers).  Raises on any failure: the deployer
        degrades that replica to the old version."""
        import base64
        import io
        buf = io.BytesIO()
        np.savez(buf, **{f"w{i}": np.asarray(a)
                         for i, a in enumerate(arrays)})
        status, data = self._post_json(
            "/v1/_deploy/swap",
            {"which": str(which), "version": int(version),
             "npz_b64": base64.b64encode(buf.getvalue()).decode()})
        if status != 200:
            try:
                msg = json.loads(data)["error"]["message"]
            except (ValueError, KeyError, TypeError):
                msg = data[:200]
            raise ReplicaFailed(
                f"replica {self.name}: swap HTTP {status}: {msg}")
        return int(json.loads(data).get("prefix_flushed", 0))

    # -- observability -----------------------------------------------------
    def _get(self, path):
        def once():
            self._chaos_connect()
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=10.0)
            try:
                conn.request("GET", path)
                self._chaos_slow_read()
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                conn.close()
        return self._retrying(once, f"GET {path}")

    def health(self):
        try:
            status, data = self._get("/healthz")
        except (OSError, ReplicaFailed) as e:
            return {"status": "unreachable", "error": repr(e)}
        try:
            out = json.loads(data)
        except ValueError:
            out = {"status": "failed"}
        if status != 200 and out.get("status") not in ("draining",):
            out.setdefault("status", "failed")
        return out

    @property
    def state(self):
        return self.health().get("status", "failed")

    def load(self):
        h = self.health()
        if "reserved_pages" in h:
            return float(h["reserved_pages"])
        return float(h.get("waiting", 0) + h.get("live", 0))

    def prometheus(self):
        try:
            status, data = self._get("/metrics")
        except (OSError, ReplicaFailed):
            return ""
        return data.decode() if status == 200 else ""

    def debug_trace(self, request_id=None):
        """The remote /debug/trace timelines (the X-Request-Id string
        is the cross-replica stitch key)."""
        from urllib.parse import quote
        path = "/debug/trace"
        if request_id is not None:
            path += f"?request_id={quote(str(request_id), safe='')}"
        status, data = self._get(path)
        if status != 200:
            raise ReplicaFailed(
                f"replica {self.name}: trace HTTP {status}")
        return json.loads(data)

    def debug_flight(self):
        status, data = self._get("/debug/flight")
        if status != 200:
            raise ReplicaFailed(
                f"replica {self.name}: flight HTTP {status}")
        return json.loads(data)

    # -- lifecycle (router-side only for remote replicas) ------------------
    def drain(self, timeout=120.0):
        """Remote drain is the remote operator's call; the router-side
        drain only stops routing here. Returns True when the remote
        reports idle (nothing waiting/live) within the timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            h = self.health()
            if h.get("status") == "unreachable":
                return False
            if not (h.get("waiting", 0) or h.get("live", 0)):
                return True
            self.chaos.sleep(0.05)
        return False

    def resume(self):
        return self

    def close(self, timeout=0.0):
        return True
