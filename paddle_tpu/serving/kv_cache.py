"""Block-paged KV cache — the serving engine's memory subsystem.

Reference capability: vLLM's PagedAttention block manager and the TPU
ragged-paged-attention cache layout (PAPERS.md "Ragged Paged Attention");
Paddle analogue: FastDeploy/paddle.inference KV cache management.

Design (SURVEY.md §7 static-shape stance):
- K/V live in per-layer device buffers of shape
  ``[num_pages, page_size, n_kv_heads, head_dim]`` — FIXED shape for the
  whole engine lifetime, so every compiled step program sees the same
  cache operands and the jit cache stays bounded.
- The HOST owns all bookkeeping (free list, per-sequence page tables,
  refcounts): allocation never traces, and the device only ever sees
  int32 page-table/slot arrays as program ARGUMENTS.
- Page 0 is a reserved SCRATCH page: padded batch lanes write their
  garbage K/V there and padded page-table entries point at it, so every
  lane of a fixed-shape program has defined (masked-out) memory to touch.
- Copy-on-fork for n>1 sampling: ``fork()`` shares pages by refcount;
  the first append into a SHARED partial tail page triggers a
  copy-on-write (the allocator returns the page copies for the engine to
  apply on device before scattering new K/V).
- Radix-tree prefix caching (``prefix_cache=True``; vLLM automatic
  prefix caching / SGLang RadixAttention capability): FULL pages of
  PROMPT tokens are registered in a hash-keyed radix tree
  (``commit_prefix``) when their K/V lands on device, and a later
  sequence with the same token prefix shares them
  (``acquire_prefix`` — refcount bump, zero device work). Page
  refcounts count the SEQUENCES mapping a page; a cached page whose
  refcount drops to 0 stays resident (CACHED, reclaimable) instead of
  returning to the free list, and is LRU-evicted leaf-first only when
  the allocator actually needs the page. The last prompt token is never
  served from cache (its logits must come out of a real prefill step),
  so a lookup is capped at ``(hist_len - 1)`` tokens.

Page lifecycle with the prefix cache on::

    FREE ──append_slots──► ACTIVE (rc>0) ──commit_prefix──► ACTIVE+cached
      ▲                      │ free_seq                        │ free_seq
      │                      ▼                                 ▼ (rc→0)
      └────────── rc==0, not cached                CACHED (rc==0, in tree)
      ▲                                                        │
      └───────────── LRU leaf eviction (append_slots pressure)─┘

Sizing: pass ``num_pages`` directly or an ``hbm_budget_bytes`` — the
constructor derives the page count from the per-page byte cost across
all layers (both K and V), the way an engine start-up would budget VMEM/
HBM headroom left over after weights.

int8 quantized pages (``dtype="int8"``, round 15): each page stores
int8 CODES plus a float32 per-(slot, kv-head) absmax scale — the same
recipe the generation path proved at delta-NLL ~1e-3
(``generation._quantize_q8`` / BENCH_kv8_quality.json). Scales live in
separate ``k_scales``/``v_scales`` buffers of shape
``[num_pages, page_size, n_kv_heads]`` so the attention einsums can
stream the codes and fold the scales in post-dot; sizing accounts for
them (``page_bytes_per_page`` adds 4 bytes per slot per head), so an
``hbm_budget_bytes`` cache honestly yields ``2*D/(D+4)``× the bf16 page
count.  Quantization happens ON APPEND inside the compiled step
(deterministic rounding — preemption recompute and failover re-prefill
regenerate bit-identical pages) and export/import/migration carry the
scale arrays alongside the codes (each of the k/v array lists holds the
``n_layers`` code arrays followed by the ``n_layers`` scale arrays).
"""
from __future__ import annotations

import math
from collections import deque

import numpy as np

__all__ = ["PagedKVCache", "OutOfPages", "SCRATCH_PAGE",
           "GeometryMismatch", "PrefixDrift"]

# page 0 is never handed to a sequence: padded lanes scatter/gather there
SCRATCH_PAGE = 0


class OutOfPages(RuntimeError):
    """Raised by the allocator when the free list cannot cover a request
    — the scheduler's signal to preempt or defer admission."""

    def __init__(self, needed, free):
        super().__init__(
            f"paged KV cache exhausted: need {needed} page(s), "
            f"{free} free")
        self.needed = needed
        self.free = free


class GeometryMismatch(ValueError):
    """A page-migration payload does not match this allocator's cache
    geometry (layers / kv heads / head dim / page size / dtype) — K/V
    bytes from a differently-shaped cache can never be spliced in."""


class PrefixDrift(RuntimeError):
    """The importing allocator's radix tree no longer matches the page
    count the exporter skipped: the shared prefix grew (another request
    committed more pages) or shrank (LRU eviction) between the probe
    and the import.  Carries ``cached_pages`` — the pages the importer
    ACTUALLY holds — so the migration driver can re-export the right
    suffix and retry."""

    def __init__(self, skip_pages, cached_pages):
        super().__init__(
            f"prefix drift: exporter skipped {skip_pages} cached "
            f"page(s) but the importer matched {cached_pages}")
        self.skip_pages = skip_pages
        self.cached_pages = cached_pages


class _RadixNode:
    """One FULL page of prompt tokens in the prefix tree. ``key`` is the
    page's token tuple (dict-hashed under the parent — the radix edge),
    so chains of nodes spell out token prefixes page by page."""

    __slots__ = ("key", "page", "parent", "children", "last_used")

    def __init__(self, key, page, parent, last_used):
        self.key = key
        self.page = page
        self.parent = parent
        self.children = {}
        self.last_used = last_used


class PagedKVCache:
    """Fixed-size-page KV pool with a free-list allocator, per-sequence
    page tables, and refcounted copy-on-fork sharing.

    Host bookkeeping is transactional: an allocation either fully
    succeeds or raises :class:`OutOfPages` with no state mutated, so the
    engine can preempt and retry safely.
    """

    def __init__(self, n_layers, n_kv_heads, head_dim, *, page_size=16,
                 num_pages=None, hbm_budget_bytes=None, dtype="float32",
                 prefix_cache=False, tp_degree=1):
        import jax.numpy as jnp
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_layers = int(n_layers)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        # tensor-parallel geometry (round 23): drives the migration
        # contract (geometry dict + per-shard wire payload lists) only
        # — device placement is the engine's tp.TPContext's job, the
        # cache stays jax-sharding-agnostic
        self.tp_degree = int(tp_degree or 1)
        if self.tp_degree < 1 or self.n_kv_heads % self.tp_degree:
            raise ValueError(
                f"tp_degree={tp_degree} must divide n_kv_heads="
                f"{n_kv_heads}")
        self.dtype = jnp.dtype(dtype)
        # int8 = quantized codes + per-(slot, head) f32 scales; any other
        # integer dtype would silently astype-truncate K/V to garbage
        if self.dtype.kind in "iu" and str(self.dtype) != "int8":
            raise ValueError(
                f"unsupported cache dtype {dtype!r}: use a float dtype "
                "or 'int8' (quantized codes + scales)")
        self.quantized = str(self.dtype) == "int8"
        per_page = self.page_bytes_per_page(
            n_layers, n_kv_heads, head_dim, page_size, self.dtype)
        if num_pages is None:
            if hbm_budget_bytes is None:
                raise ValueError(
                    "size the cache with either num_pages or "
                    "hbm_budget_bytes")
            num_pages = int(hbm_budget_bytes) // per_page
        num_pages = int(num_pages)
        # scratch + at least one allocatable page
        if num_pages < 2:
            raise ValueError(
                f"cache budget yields {num_pages} page(s); need >= 2 "
                f"({per_page} bytes/page across {n_layers} layers)")
        self.num_pages = num_pages
        self.bytes_total = num_pages * per_page
        # device buffers: per layer, [num_pages, page_size, n_kv, hd]
        shape = (num_pages, self.page_size, self.n_kv_heads, self.head_dim)
        self.k_pages = [jnp.zeros(shape, self.dtype)
                        for _ in range(self.n_layers)]
        self.v_pages = [jnp.zeros(shape, self.dtype)
                        for _ in range(self.n_layers)]
        if self.quantized:
            sshape = (num_pages, self.page_size, self.n_kv_heads)
            self.k_scales = [jnp.zeros(sshape, jnp.float32)
                             for _ in range(self.n_layers)]
            self.v_scales = [jnp.zeros(sshape, jnp.float32)
                             for _ in range(self.n_layers)]
        else:
            self.k_scales = None
            self.v_scales = None
        # host bookkeeping
        self._free = deque(range(1, num_pages))  # page 0 = scratch
        self._rc = np.zeros(num_pages, np.int32)
        self._tables: dict[object, list[int]] = {}
        self._lens: dict[object, int] = {}
        # prefix cache (radix tree over full prompt-token pages)
        self.prefix_cache_enabled = bool(prefix_cache)
        self._prefix_root = _RadixNode(None, None, None, 0)
        self._cached: dict[int, _RadixNode] = {}  # page -> tree node
        self._clock = 0
        self.prefix_hit_pages = 0
        self.prefix_miss_pages = 0
        self.prefix_evictions = 0
        # page-transfer fast path (round 18): ONE compiled gather (and
        # ONE compiled scatter) across every pool per export/import,
        # instead of 2*n_layers(+scales) separate dispatches; indexes
        # are padded to powers of two onto the scratch page so the jit
        # trace cache stays bounded at log2(num_pages) entries
        self._gather_fn = None
        self._scatter_fn = None
        # hierarchical KV tier (round 20): when attached, LRU-evicted
        # rc-0 cached pages spill their wire payload to the host tier
        # instead of vanishing (kvtier.KVTier; strictly best-effort)
        self._tier = None

    def attach_tier(self, tier):
        """Bind a :class:`~.kvtier.KVTier` so prefix-cache evictions
        spill to the host tier.  ``None`` detaches."""
        self._tier = tier

    # -- sizing helpers ---------------------------------------------------
    @staticmethod
    def page_bytes_per_page(n_layers, n_kv_heads, head_dim, page_size,
                            dtype):
        """Bytes one page costs across every layer's K and V buffers.
        int8 pages carry their f32 scale rows (4 bytes per slot per kv
        head, K and V each) so ``hbm_budget_bytes`` sizing honestly
        reflects the quantized capacity."""
        import jax.numpy as jnp
        dt = jnp.dtype(dtype)
        per_slot_head = int(head_dim) * dt.itemsize
        if str(dt) == "int8":
            per_slot_head += 4  # the float32 absmax scale
        return (2 * int(n_layers) * int(page_size) * int(n_kv_heads)
                * per_slot_head)

    def pages_for(self, n_tokens):
        """Pages a sequence of n_tokens occupies."""
        return math.ceil(max(int(n_tokens), 0) / self.page_size)

    # -- observability ----------------------------------------------------
    @property
    def allocatable_pages(self):
        return self.num_pages - 1  # minus scratch

    @property
    def free_pages(self):
        return len(self._free)

    @property
    def cached_pages(self):
        """Pages registered in the prefix tree (shared or reclaimable)."""
        return len(self._cached)

    @property
    def reclaimable_pages(self):
        """Cached pages no live sequence maps (rc==0) — evictable
        leaf-first, so all of them can be turned into free pages."""
        return sum(1 for p in self._cached if self._rc[p] == 0)

    @property
    def prefix_tree_depth(self):
        """Deepest chain in the radix tree, in pages — /healthz
        advertises it next to ``cached_pages`` so a router can see how
        much reusable prefix a replica actually holds."""
        best = 0
        stack = [(self._prefix_root, 0)]
        while stack:
            node, d = stack.pop()
            if d > best:
                best = d
            stack.extend((c, d + 1) for c in node.children.values())
        return best

    @property
    def available_pages(self):
        """Pages an allocation can actually obtain: the free list plus
        LRU-evictable cached pages. Equals ``free_pages`` with the
        prefix cache off — admission/watermark math uses this."""
        return len(self._free) + self.reclaimable_pages

    @property
    def used_pages(self):
        return self.allocatable_pages - len(self._free)

    def occupancy(self):
        return self.used_pages / max(self.allocatable_pages, 1)

    def has_seq(self, seq_id):
        return seq_id in self._tables

    def seq_len(self, seq_id):
        return self._lens[seq_id]

    def live_seqs(self):
        return list(self._tables)

    # -- sequence lifecycle -----------------------------------------------
    def alloc_seq(self, seq_id):
        """Register an empty sequence (pages arrive via append_slots)."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        self._tables[seq_id] = []
        self._lens[seq_id] = 0

    def fork(self, parent_id, child_id):
        """Copy-on-fork: the child SHARES the parent's pages (refcounts
        bumped); the first append into the shared partial tail page
        copy-on-writes it. O(pages) host work, zero device copies."""
        if child_id in self._tables:
            raise ValueError(f"sequence {child_id!r} already allocated")
        table = self._tables[parent_id]
        for p in table:
            self._rc[p] += 1
        self._tables[child_id] = list(table)
        self._lens[child_id] = self._lens[parent_id]

    def free_seq(self, seq_id):
        """Release a sequence's pages (refcounted). Unknown ids raise —
        the double-free guard the allocator invariants tests pin. Pages
        registered in the prefix tree stay resident (CACHED) at rc==0
        instead of returning to the free list; eviction reclaims them
        under pressure."""
        if seq_id not in self._tables:
            raise KeyError(
                f"free_seq: unknown (or already freed) sequence "
                f"{seq_id!r}")
        for p in self._tables.pop(seq_id):
            self._rc[p] -= 1
            if self._rc[p] < 0:  # pragma: no cover - internal invariant
                raise AssertionError(f"page {p} refcount underflow")
            if self._rc[p] == 0 and p not in self._cached:
                self._free.append(p)
        del self._lens[seq_id]

    # -- allocation --------------------------------------------------------
    def append_slots(self, seq_id, n_tokens):
        """Reserve flat slot ids (page * page_size + offset) for the next
        ``n_tokens`` of ``seq_id``, allocating pages as needed.

        Returns ``(slots int32 [n_tokens], copies list[(src, dst)])``:
        ``copies`` is non-empty when a shared partial tail page had to be
        copy-on-written — the engine MUST ``apply_copies(copies)`` on the
        device buffers before scattering the new K/V.

        Transactional for SEQUENCE state: raises :class:`OutOfPages`
        (no sequence state touched) when free + reclaimable-cached pages
        cannot cover the need. When the free list alone falls short but
        reclaimable cached pages exist, the LRU cached leaves are
        evicted here — a cache-internal mutation, invisible to every
        live sequence.
        """
        if n_tokens <= 0:
            raise ValueError(f"append_slots: n_tokens={n_tokens}")
        table = self._tables[seq_id]
        ln = self._lens[seq_id]
        off = ln % self.page_size
        cow = (off != 0 and table and self._rc[table[-1]] > 1)
        new_pages = self.pages_for(ln + n_tokens) - self.pages_for(ln)
        need = new_pages + (1 if cow else 0)
        if need > self.available_pages:
            raise OutOfPages(need, self.available_pages)
        while need > len(self._free):
            if not self._evict_lru_leaf():  # pragma: no cover - guarded
                raise OutOfPages(need, self.available_pages)
        copies = []
        if cow:
            fresh = self._free.popleft()
            self._rc[fresh] = 1
            self._rc[table[-1]] -= 1  # shared page: rc stays >= 1
            copies.append((table[-1], fresh))
            table[-1] = fresh
        slots = np.empty(n_tokens, np.int32)
        for i in range(n_tokens):
            pos = ln + i
            if pos % self.page_size == 0:
                page = self._free.popleft()
                self._rc[page] = 1
                table.append(page)
            slots[i] = table[pos // self.page_size] * self.page_size \
                + pos % self.page_size
        self._lens[seq_id] = ln + n_tokens
        return slots, copies

    def free_tail(self, seq_id, new_len):
        """Roll a sequence BACK to ``new_len`` tokens — the speculative-
        decoding rejection path: slots written for rejected draft tokens
        are released by accounting alone (the K/V bytes stay in place,
        masked by context_len, and are overwritten when the sequence
        grows again). Pages that fall entirely beyond the new length are
        refcount-released; refcount-safe under prefix-cache sharing
        (cached pages stay RESIDENT at rc==0, exactly like free_seq) and
        n>1 forks (shared pages are only decref'd — the co-owner keeps
        them; spec writes CoW the shared tail first, so a rolled-back
        page is never one the sibling still reads through this table).
        """
        if seq_id not in self._tables:
            raise KeyError(f"free_tail: unknown sequence {seq_id!r}")
        new_len = int(new_len)
        ln = self._lens[seq_id]
        if new_len < 0 or new_len > ln:
            raise ValueError(
                f"free_tail: new_len={new_len} outside [0, {ln}]")
        table = self._tables[seq_id]
        keep = self.pages_for(new_len)
        for p in table[keep:]:
            self._rc[p] -= 1
            if self._rc[p] < 0:  # pragma: no cover - internal invariant
                raise AssertionError(f"page {p} refcount underflow")
            if self._rc[p] == 0 and p not in self._cached:
                self._free.append(p)
        del table[keep:]
        self._lens[seq_id] = new_len

    def apply_copies(self, copies):
        """Perform pending copy-on-write page copies on the device
        buffers (one batched gather-scatter per layer; quantized caches
        copy the scale rows along with the codes)."""
        if not copies:
            return
        import jax.numpy as jnp
        srcs = jnp.asarray([s for s, _ in copies], jnp.int32)
        dsts = jnp.asarray([d for _, d in copies], jnp.int32)
        self.k_pages = [kp.at[dsts].set(kp[srcs]) for kp in self.k_pages]
        self.v_pages = [vp.at[dsts].set(vp[srcs]) for vp in self.v_pages]
        if self.quantized:
            self.k_scales = [ks.at[dsts].set(ks[srcs])
                             for ks in self.k_scales]
            self.v_scales = [vs.at[dsts].set(vs[srcs])
                             for vs in self.v_scales]

    def program_operands(self):
        """The per-layer K/V operands a compiled step program consumes:
        plain arrays for float caches, ``(codes, scales)`` tuples for
        int8 — the shape :func:`~.attention.paged_attention` and the
        engine's scatter path branch on. Returns ``(k_ops, v_ops)``."""
        if not self.quantized:
            return self.k_pages, self.v_pages
        return ([tuple(p) for p in zip(self.k_pages, self.k_scales)],
                [tuple(p) for p in zip(self.v_pages, self.v_scales)])

    def store_operands(self, new_k, new_v):
        """Write a step program's updated K/V operands back (the inverse
        of :meth:`program_operands`)."""
        if not self.quantized:
            self.k_pages = list(new_k)
            self.v_pages = list(new_v)
            return
        self.k_pages = [p for p, _ in new_k]
        self.k_scales = [s for _, s in new_k]
        self.v_pages = [p for p, _ in new_v]
        self.v_scales = [s for _, s in new_v]

    def page_table(self, seq_id, max_pages):
        """Padded int32 page-table row for the fixed-shape step program
        (padding points at the scratch page; masked by context_len)."""
        table = self._tables[seq_id]
        if len(table) > max_pages:
            raise ValueError(
                f"sequence {seq_id!r} spans {len(table)} pages > "
                f"max_pages_per_seq {max_pages}")
        row = np.full(max_pages, SCRATCH_PAGE, np.int32)
        row[:len(table)] = table
        return row

    def refcount(self, page):
        return int(self._rc[page])

    def pages_held(self, seq_id):
        """Pages currently mapped by seq_id (0 for unknown sequences) —
        admission accounting for admitted-but-unallocated requests."""
        return len(self._tables.get(seq_id, ()))

    # -- prefix cache (radix tree over full prompt-token pages) ------------
    def _prefix_cap_pages(self, prompt_len, hist_len):
        """Pages of ``prompt`` a lookup may serve from cache. The last
        HISTORY token is never cached-over (its logits must come from a
        real prefill step), and only prompt tokens are ever in the
        tree."""
        return max(0, min(int(prompt_len), int(hist_len) - 1)) \
            // self.page_size

    def _walk(self, tokens, cap_pages):
        """Longest-prefix match: the chain of tree nodes whose pages
        spell out ``tokens``'s leading full pages (up to cap_pages)."""
        node = self._prefix_root
        chain = []
        ps = self.page_size
        for i in range(cap_pages):
            child = node.children.get(
                tuple(int(t) for t in tokens[i * ps:(i + 1) * ps]))
            if child is None:
                break
            chain.append(child)
            node = child
        return chain

    def probe_prefix(self, prompt, hist_len=None):
        """Lookup-only longest-prefix match: how many of ``prompt``'s
        pages the cache could serve right now. No refcount or LRU
        mutation — safe for reservation math (the front-end's
        uncached-page accounting)."""
        if not self.prefix_cache_enabled:
            return 0
        if hist_len is None:
            hist_len = len(prompt)
        return len(self._walk(
            prompt, self._prefix_cap_pages(len(prompt), hist_len)))

    def acquire_prefix(self, seq_id, prompt, hist_len):
        """Register ``seq_id`` with its longest cached prompt prefix
        PINNED (refcount bump per matched page — eviction cannot touch
        them while the sequence lives). Creates the sequence, so call it
        INSTEAD of :meth:`alloc_seq`; with the cache disabled it is
        exactly alloc_seq. Returns the number of cached pages mapped;
        the sequence's length starts at ``matched * page_size`` and the
        prefill path must skip those tokens."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        if not self.prefix_cache_enabled:
            self._tables[seq_id] = []
            self._lens[seq_id] = 0
            return 0
        cap = self._prefix_cap_pages(len(prompt), hist_len)
        chain = self._walk(prompt, cap)
        self._clock += 1
        for node in chain:
            node.last_used = self._clock
            self._rc[node.page] += 1
        self._tables[seq_id] = [n.page for n in chain]
        self._lens[seq_id] = len(chain) * self.page_size
        return len(chain)

    def record_prefix_stats(self, prompt, hist_len, hit_pages):
        """Account one request's hit/miss page counts — called by the
        scheduler ONCE per prefill, when the request actually starts
        (pins made at submit/admission may be refreshed before then, so
        counting at acquire time would double-count)."""
        cap = self._prefix_cap_pages(len(prompt), hist_len)
        self.prefix_hit_pages += hit_pages
        self.prefix_miss_pages += max(0, cap - hit_pages)

    def commit_prefix(self, seq_id, prompt, upto):
        """Insert ``seq_id``'s now-prefilled FULL prompt pages into the
        tree (tokens ``[0, min(upto, len(prompt)))``). Pages whose token
        chunk already has a canonical node keep that node (duplicate
        content under a different page is simply not registered — the
        K/V bytes are equivalent, so mixed chains stay exact). Returns
        the number of nodes added."""
        if not self.prefix_cache_enabled or seq_id not in self._tables:
            return 0
        ps = self.page_size
        n_full = min(int(upto), len(prompt)) // ps
        table = self._tables[seq_id]
        node = self._prefix_root
        self._clock += 1
        added = 0
        for i in range(n_full):
            key = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                page = table[i]
                if page in self._cached:  # pragma: no cover - invariant
                    raise AssertionError(
                        f"page {page} already registered in the tree")
                child = _RadixNode(key, page, node, self._clock)
                node.children[key] = child
                self._cached[page] = child
                added += 1
            child.last_used = self._clock
            node = child
        return added

    def clear_prefix(self):
        """Flush every reclaimable (rc==0) cached page back to the free
        list — the weight-reload path: cached K/V computed under OLD
        weights must never be served to post-reload requests. On an
        idle (drained) engine every cached page has rc==0, so this is a
        full tree flush. Returns the number of pages reclaimed.

        The attached KV tier (if any) is detached for the loop and
        INVALIDATED after it: reload-flushed pages hold K/V computed
        under the OLD weights, so spilling them — or keeping anything
        already spilled — would serve stale bytes to post-reload
        requests."""
        n = 0
        tier, self._tier = self._tier, None
        try:
            while self._evict_lru_leaf():
                n += 1
        finally:
            self._tier = tier
        if tier is not None:
            tier.invalidate()
        return n

    # -- page migration (disaggregated prefill/decode, round 14) -----------
    def geometry(self):
        """The shape contract a migration payload must satisfy."""
        return {"n_layers": self.n_layers, "n_kv_heads": self.n_kv_heads,
                "head_dim": self.head_dim, "page_size": self.page_size,
                "dtype": str(self.dtype), "tp_degree": self.tp_degree}

    def check_geometry(self, meta):
        mine = self.geometry()
        theirs = {k: meta.get(k) for k in mine}
        if mine != theirs:
            raise GeometryMismatch(
                f"page payload geometry {theirs} does not match this "
                f"cache ({mine})")

    def export_pages(self, seq_id, skip_pages=0):
        """Fetch a sequence's page chain — K/V bytes plus layout meta —
        for migration to another allocator (the disaggregated
        prefill→decode handoff).  ``skip_pages`` leading pages are
        omitted: the radix tree is the transfer index, and prefix pages
        the importer already holds resident are never re-transferred.

        Read-only (refcounts untouched): migration is copy-then-release,
        so a failed transfer leaves the source sequence intact.  Returns
        ``(meta, k_arrays, v_arrays)`` — per-layer numpy arrays of shape
        ``[n_pages, page_size, n_kv_heads, head_dim]``.  Quantized
        (int8) caches append the per-layer float32 scale arrays
        (``[n_pages, page_size, n_kv_heads]``) AFTER the code arrays in
        each list — the wire format records every array's own shape and
        dtype, so the scale geometry rides the same payload.

        Tensor-parallel caches (``tp_degree=t > 1``) split every array
        into t per-shard chunks along the kv-head axis, layer-major /
        shard-minor (``[L0S0, L0S1, ..., L1S0, ...]``; int8 scale
        arrays after ALL code arrays, split the same way — scales ride
        every shard, the round-15 rule).  ``tp_degree`` is part of
        :meth:`geometry`, so a degree-skewed import bounces on
        :class:`GeometryMismatch` up front — the router/disagg
        re-prefill fallback covers it.
        """
        if seq_id not in self._tables:
            raise KeyError(f"export_pages: unknown sequence {seq_id!r}")
        table = self._tables[seq_id]
        skip_pages = int(skip_pages)
        if not 0 <= skip_pages <= len(table):
            raise ValueError(
                f"export_pages: skip_pages={skip_pages} outside "
                f"[0, {len(table)}]")
        pages = table[skip_pages:]
        meta = dict(self.geometry(), seq_len=self._lens[seq_id],
                    skip_pages=skip_pages, n_pages=len(pages))
        if not pages:
            empty = self._empty_payload()
            return meta, empty, [a.copy() for a in empty]
        k, v = self._fetch_pages(pages)
        return meta, self._split_shards(k), self._split_shards(v)

    def import_pages(self, seq_id, meta, k_arrays, v_arrays,
                     prompt=None, hist_len=None):
        """Splice an exported page chain into THIS allocator as a new
        sequence: acquire the locally-cached shared prefix (the pages
        the exporter skipped), allocate fresh pages for the transferred
        suffix, scatter the K/V bytes into the device buffers, and —
        with the prefix cache on — register the now-resident full
        prompt pages back into the radix tree.

        Raises :class:`GeometryMismatch` when the payload's cache shape
        differs, :class:`PrefixDrift` when the local radix match no
        longer equals ``meta["skip_pages"]`` (pages committed or
        evicted since the exporter probed — the caller re-exports with
        the carried ``cached_pages`` and retries), :class:`OutOfPages`
        when free + reclaimable pages cannot host the suffix.  All
        failures roll back fully (no sequence state left behind).
        """
        self.check_geometry(meta)
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        skip = int(meta["skip_pages"])
        n_pages = int(meta["n_pages"])
        seq_len = int(meta["seq_len"])
        if self.pages_for(seq_len) != skip + n_pages:
            raise ValueError(
                f"import_pages: seq_len={seq_len} spans "
                f"{self.pages_for(seq_len)} page(s), payload covers "
                f"{skip}+{n_pages}")
        self._check_payload_shapes(n_pages, k_arrays, v_arrays)
        # pin the locally-resident prefix; must match what the exporter
        # skipped or the page/token alignment breaks (PrefixDrift)
        if self.prefix_cache_enabled and prompt is not None:
            matched = self.acquire_prefix(
                seq_id, prompt,
                len(prompt) + 1 if hist_len is None else hist_len)
        else:
            self._tables[seq_id] = []
            self._lens[seq_id] = 0
            matched = 0
        if matched != skip:
            self.free_seq(seq_id)
            raise PrefixDrift(skip, matched)
        try:
            if n_pages > self.available_pages:
                raise OutOfPages(n_pages, self.available_pages)
            while n_pages > len(self._free):
                if not self._evict_lru_leaf():  # pragma: no cover
                    raise OutOfPages(n_pages, self.available_pages)
        except OutOfPages:
            self.free_seq(seq_id)
            raise
        table = self._tables[seq_id]
        fresh = [self._free.popleft() for _ in range(n_pages)]
        for p in fresh:
            self._rc[p] = 1
        table.extend(fresh)
        self._lens[seq_id] = seq_len
        self._scatter_pages(fresh, self._merge_shards(k_arrays),
                            self._merge_shards(v_arrays))
        if self.prefix_cache_enabled and prompt is not None:
            # the imported prompt pages are canonical K/V: later
            # shared-prefix requests on THIS replica hit them.  Bounded
            # by seq_len: a sequence imported SHORTER than its prompt
            # (rolled back below it) holds fewer pages than the prompt
            # spans, and commit must never index past its table.
            self.commit_prefix(seq_id, prompt, min(len(prompt),
                                                   seq_len))
        return len(table)

    def _check_payload_shapes(self, n_pages, k_arrays, v_arrays):
        """Validate an incoming page payload's array count and shapes
        against this cache's geometry (codes + scales for int8).  The
        wire unit is the per-shard chunk: t = tp_degree chunks per
        layer, kv-head extent n_kv_heads // t each."""
        t = self.tp_degree
        kv = self.n_kv_heads // t
        shape = (n_pages, self.page_size, kv, self.head_dim)
        sshape = (n_pages, self.page_size, kv)
        n_codes = self.n_layers * t
        per_list = n_codes * (2 if self.quantized else 1)
        for arrs, what in ((k_arrays, "k"), (v_arrays, "v")):
            if len(arrs) != per_list:
                raise GeometryMismatch(
                    f"{what} payload has {len(arrs)} array(s), this "
                    f"cache expects {per_list} ({self.n_layers} "
                    f"layer(s) x {t} shard(s)"
                    + (" of codes + scales)" if self.quantized
                       else ")"))
            for a in arrs[:n_codes]:
                if tuple(a.shape) != shape:
                    raise GeometryMismatch(
                        f"{what} page array shape {tuple(a.shape)} != "
                        f"{shape}")
            for a in arrs[n_codes:]:
                if tuple(a.shape) != sshape:
                    raise GeometryMismatch(
                        f"{what} scale array shape {tuple(a.shape)} != "
                        f"{sshape}")

    def _empty_payload(self):
        """A zero-page export's array list — the SAME per-shard wire
        structure as a real payload so shape validation never branches
        on emptiness."""
        t = self.tp_degree
        kv = self.n_kv_heads // t
        empty = [np.empty((0, self.page_size, kv, self.head_dim),
                          self.dtype)
                 for _ in range(self.n_layers * t)]
        if self.quantized:
            empty += [np.empty((0, self.page_size, kv), np.float32)
                      for _ in range(self.n_layers * t)]
        return empty

    def _split_shards(self, arrays):
        """Per-layer fetched arrays -> the per-shard wire lists
        (layer-major / shard-minor; no-op at tp_degree=1).  Works for
        codes [n, PS, KV, D] and scales [n, PS, KV] alike — the
        kv-head axis is axis 2 in both."""
        if self.tp_degree == 1:
            return list(arrays)
        out = []
        for a in arrays:
            out.extend(np.split(np.asarray(a), self.tp_degree, axis=2))
        return out

    def _merge_shards(self, arrays):
        """Inverse of :meth:`_split_shards`: t consecutive per-shard
        chunks concatenate back into one per-layer array."""
        if self.tp_degree == 1:
            return list(arrays)
        t = self.tp_degree
        return [np.concatenate([np.asarray(x) for x in
                                arrays[i:i + t]], axis=2)
                for i in range(0, len(arrays), t)]

    def _all_pools(self):
        """Every device pool in canonical order (k, v[, k_scales,
        v_scales]) — the operand list of the fused transfer programs."""
        pools = list(self.k_pages) + list(self.v_pages)
        if self.quantized:
            pools += list(self.k_scales) + list(self.v_scales)
        return pools

    def _store_pools(self, pools):
        ln = self.n_layers
        self.k_pages = list(pools[:ln])
        self.v_pages = list(pools[ln:2 * ln])
        if self.quantized:
            self.k_scales = list(pools[2 * ln:3 * ln])
            self.v_scales = list(pools[3 * ln:])

    @staticmethod
    def _pad_pow2(pages):
        """Pow2-padded int32 index row; padding points at the scratch
        page (garbage by contract), bounding the transfer programs'
        trace cache."""
        pad = 1
        while pad < len(pages):
            pad <<= 1
        idx = np.full(pad, SCRATCH_PAGE, np.int32)
        idx[:len(pages)] = pages
        return idx

    def _fetch_pages(self, pages):
        """Fetch a page chain from every pool — ONE compiled gather +
        ONE host transfer (the per-layer dispatch overhead otherwise
        dominates a prefix ship).  Returns ``(k_arrays, v_arrays)`` in
        the export list shape (codes then scales)."""
        import jax
        import jax.numpy as jnp
        n = len(pages)
        idx = self._pad_pow2(pages)
        if self._gather_fn is None:
            self._gather_fn = jax.jit(
                lambda pools, i: [p[i] for p in pools])
        out = jax.device_get(
            self._gather_fn(self._all_pools(), jnp.asarray(idx)))
        out = [a[:n] for a in out]
        ln = self.n_layers
        k = out[:ln]
        v = out[ln:2 * ln]
        if self.quantized:
            k += out[2 * ln:3 * ln]
            v += out[3 * ln:]
        return k, v

    def _scatter_pages(self, dsts, k_arrays, v_arrays):
        """Write an imported payload's K/V (and scales) into freshly
        allocated device pages — ONE compiled scatter across every
        pool."""
        if not dsts:
            return
        import jax
        import jax.numpy as jnp
        n = len(dsts)
        idx = self._pad_pow2(dsts)
        ln = self.n_layers
        vals = list(k_arrays[:ln]) + list(v_arrays[:ln])
        if self.quantized:
            vals += list(k_arrays[ln:]) + list(v_arrays[ln:])
        if len(idx) != n:
            vals = [np.concatenate(
                [np.asarray(a),
                 np.zeros((len(idx) - n,) + tuple(a.shape[1:]),
                          np.asarray(a).dtype)]) for a in vals]
        if self._scatter_fn is None:
            self._scatter_fn = jax.jit(
                lambda pools, i, vs: [
                    p.at[i].set(v.astype(p.dtype))
                    for p, v in zip(pools, vs)])
        self._store_pools(self._scatter_fn(
            self._all_pools(), jnp.asarray(idx),
            [jnp.asarray(a) for a in vals]))

    # -- fleet prefix transfer (router-driven prefix ships, round 18) ------
    def export_prefix_pages(self, prompt, skip_pages=0):
        """Export the CACHED prefix of ``prompt`` — no live sequence
        involved: the radix tree itself is the source (the fleet prefix
        ship: a donor replica serves its cached pages to a replica the
        router is about to place a matching request on).  ``skip_pages``
        leading pages are omitted (the recipient already holds them).

        Read-only on refcounts; the exported chain's LRU clocks are
        refreshed (a donated prefix is demonstrably hot).  Raises
        :class:`PrefixDrift` when the local match is SHORTER than
        ``skip_pages`` (the tree shrank since the router probed —
        ``cached_pages`` carries the true count).  Returns
        ``(meta, k_arrays, v_arrays)`` with ``meta["kind"] ==
        "prefix"`` and ``meta["prompt"]`` holding the FULL matched
        token prefix (skipped pages included, so the importer can walk
        its own tree from the root)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        chain = self._walk(prompt, len(prompt) // self.page_size)
        matched = len(chain)
        skip_pages = int(skip_pages)
        if skip_pages > matched:
            raise PrefixDrift(skip_pages, matched)
        self._clock += 1
        for node in chain:
            node.last_used = self._clock
        pages = [n.page for n in chain[skip_pages:]]
        meta = dict(self.geometry(), kind="prefix",
                    skip_pages=skip_pages, n_pages=len(pages),
                    cached_pages=matched,
                    prompt=[int(t) for t in
                            prompt[:matched * self.page_size]])
        if not pages:
            empty = self._empty_payload()
            return meta, empty, [a.copy() for a in empty]
        k, v = self._fetch_pages(pages)
        return meta, self._split_shards(k), self._split_shards(v)

    def import_prefix_pages(self, meta, k_arrays, v_arrays):
        """Splice a shipped prefix payload into THIS allocator's radix
        tree: the imported pages enter as CACHED (rc==0, reclaimable)
        full prompt pages — exactly the state a locally-prefilled-and-
        freed prefix leaves behind, so every existing accounting rule
        (LRU eviction, uncached-only admission, conservation) applies
        unchanged.

        The local tree must match exactly ``meta["skip_pages"]`` pages
        of the payload's token prefix — :class:`PrefixDrift` otherwise
        (pages committed or evicted since the router probed; the
        carried ``cached_pages`` lets the driver re-export the right
        suffix).  :class:`GeometryMismatch` on any shape/dtype skew,
        :class:`OutOfPages` when the suffix cannot be hosted.  All
        failures roll back fully.  Returns the number of pages
        imported."""
        if not self.prefix_cache_enabled:
            raise GeometryMismatch(
                "prefix ship into a cache with prefix_cache disabled: "
                "imported pages could never be registered or reused")
        self.check_geometry(meta)
        prompt = np.asarray(meta["prompt"], np.int32).reshape(-1)
        skip = int(meta["skip_pages"])
        n_pages = int(meta["n_pages"])
        if prompt.size != (skip + n_pages) * self.page_size:
            raise ValueError(
                f"import_prefix_pages: prompt of {prompt.size} token(s)"
                f" does not span exactly {skip}+{n_pages} full page(s)")
        self._check_payload_shapes(n_pages, k_arrays, v_arrays)
        # pin the locally-resident lead (a temp sequence protects both
        # the matched chain and the fresh pages from the evict loop)
        sid = ("__prefix_import__", self._clock)
        matched = self.acquire_prefix(sid, prompt, prompt.size + 1)
        if matched != skip:
            self.free_seq(sid)
            raise PrefixDrift(skip, matched)
        try:
            if n_pages > self.available_pages:
                raise OutOfPages(n_pages, self.available_pages)
            while n_pages > len(self._free):
                if not self._evict_lru_leaf():  # pragma: no cover
                    raise OutOfPages(n_pages, self.available_pages)
        except OutOfPages:
            self.free_seq(sid)
            raise
        table = self._tables[sid]
        fresh = [self._free.popleft() for _ in range(n_pages)]
        for p in fresh:
            self._rc[p] = 1
        table.extend(fresh)
        self._lens[sid] = prompt.size
        self._scatter_pages(fresh, self._merge_shards(k_arrays),
                            self._merge_shards(v_arrays))
        self.commit_prefix(sid, prompt, prompt.size)
        # drop the pin: committed pages stay resident (CACHED, rc==0)
        self.free_seq(sid)
        return n_pages

    def drop_prefix(self, prompt):
        """Evict ``prompt``'s cached chain AND its whole unpinned
        subtree — the router's dedup lever for hot prefixes resident on
        more replicas than the fleet needs.  A hot system prompt's
        chain always has tail extensions committed under it, so the
        subtree must go leaf-first or nothing is ever droppable; a
        pinned page (rc>0: a live sequence maps it) survives and keeps
        its ancestors matchable.  Returns the number of pages reclaimed
        to the free list."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        chain = self._walk(prompt, len(prompt) // self.page_size)
        if not chain:
            return 0
        dropped = 0

        def evict(node):
            del node.parent.children[node.key]
            del self._cached[node.page]
            self._free.append(node.page)
            self.prefix_evictions += 1

        def prune(node):
            nonlocal dropped
            for child in list(node.children.values()):
                prune(child)
            if node.children or self._rc[node.page] != 0:
                return
            evict(node)
            dropped += 1

        prune(chain[-1])
        # ancestors can only go once the deep end is gone (matching
        # always walks from the root, so an interior hole would leak
        # unreachable-but-resident pages)
        for node in reversed(chain[:-1]):
            if node.children or self._rc[node.page] != 0:
                break
            evict(node)
            dropped += 1
        return dropped

    def _evict_lru_leaf(self):
        """Reclaim the least-recently-used cached LEAF page no sequence
        maps (rc==0). Leaf-first keeps every remaining chain matchable
        from the root. Returns False when nothing is evictable."""
        victim = None
        for page, node in self._cached.items():
            if self._rc[page] == 0 and not node.children:
                if victim is None or node.last_used < victim.last_used:
                    victim = node
        if victim is None:
            return False
        if self._tier is not None:
            # spill BEFORE unlinking: the tier walks the victim's
            # ancestors to rebuild the token chain, and the page bytes
            # must be captured before the page re-enters the free list.
            # Best-effort by contract — the eviction proceeds whatever
            # happens in there.
            self._tier.spill(self, victim)
        del victim.parent.children[victim.key]
        del self._cached[victim.page]
        self._free.append(victim.page)
        self.prefix_evictions += 1
        return True
