"""Thread-safe bridge between concurrent clients and the single-threaded
:class:`~paddle_tpu.serving.engine.ServingEngine` loop.

The engine is strictly single-threaded (host bookkeeping + a jit step);
the front-end owns it behind ONE lock and a dedicated loop thread:

- ``submit()`` (any thread) admits a request under the lock and returns
  a :class:`RequestStream` — a queue the loop thread feeds via the
  engine's ``on_event`` callback, so tokens stream out as they are
  sampled (no drain-then-return).
- **Load shedding** (the no-preemption envelope): a submission is
  REJECTED (:class:`Rejected` → HTTP 429) when the waiting queue is at
  ``max_queued`` or when reserving the request's WORST-CASE page need
  (full prompt+max_new_tokens, ×n for forks) on top of every already
  accepted request's outstanding reservation would dip into the
  scheduler watermark. Reservation admission is deliberately more
  conservative than the engine's own history+1 watermark check: every
  accepted request can grow to completion without the allocator ever
  raising OutOfPages, so an over-capacity burst is shed with 429s and
  NEVER evicts a running decode. (Direct engine users keep the
  preemption elasticity; the shed gate is a front-end policy.)
- ``cancel()`` (any thread) frees the request's pages and purges the
  scheduler queues synchronously under the lock.
- ``drain()`` stops admissions (:class:`Unavailable` → HTTP 503),
  finishes all in-flight work, then parks the loop thread.
- The loop SURVIVES injected step faults (engine.FaultInjected — the
  hook fires before any state mutation, so the step is retried); any
  other loop exception is fatal: live pages are released
  (``engine.release_live``), every open stream gets an error event, and
  the front-end reports ``"failed"``.

Capacity math and engine state are only ever read/written under the
lock, so a submission races neither the step loop nor other submitters.
The lock is held across a whole engine step — including the first-call
jit trace — so a submit may block for one step duration; that IS the
backpressure.
"""
from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time

import numpy as np

from .engine import FaultInjected

_log = logging.getLogger("paddle_tpu.serving")

__all__ = ["Rejected", "RequestStream", "ServingFrontend", "Unavailable"]


class Rejected(RuntimeError):
    """Load-shed admission (maps to HTTP 429: retry later)."""


class Unavailable(RuntimeError):
    """Front-end draining or failed (maps to HTTP 503)."""


class RequestStream:
    """Per-submission event stream. For ``n>1`` sampling the forked
    children's events arrive on the SAME stream, tagged with a stable
    ``index`` (0 = the submitted parent, 1.. = forks in creation order);
    the stream completes after ``n`` finish events."""

    def __init__(self, req_id, n=1):
        self.req_id = req_id
        self.n = int(n)
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._ids = {req_id: 0}
        self._finished = 0
        self.error = None

    # -- loop-thread side --------------------------------------------------
    def _index_for(self, rid):
        if rid not in self._ids:
            self._ids[rid] = len(self._ids)
        return self._ids[rid]

    def _push(self, ev):
        if ev["type"] == "finish":
            self._finished += 1
        self._q.put(ev)

    def _fail(self, exc):
        self.error = exc
        self._q.put({"type": "error", "message": str(exc)})

    @property
    def done(self):
        return self._finished >= self.n

    def all_ids(self):
        """Every req_id feeding this stream (parent + known forks)."""
        return list(self._ids)

    # -- client side -------------------------------------------------------
    def events(self, timeout=120.0, idle_s=None):
        """Yield event dicts ({"type": "token"|"finish", "index", ...})
        until all n samples finished. Raises TimeoutError when no event
        lands within ``timeout`` seconds, RuntimeError when the engine
        loop died. With ``idle_s`` set, a ``{"type": "idle"}`` event is
        yielded whenever no real event arrived for that long (the SSE
        keepalive hook: the server turns idles into ``: ping`` comment
        frames, which is ALSO how client disconnects are detected in
        bounded time while decode or prefill stalls)."""
        finishes = 0
        last = time.monotonic()
        while finishes < self.n:
            wait = timeout if idle_s is None else min(idle_s, timeout)
            try:
                ev = self._q.get(timeout=wait)
            except queue.Empty:
                if idle_s is not None \
                        and time.monotonic() - last < timeout:
                    yield {"type": "idle"}
                    continue
                raise TimeoutError(
                    f"request {self.req_id}: no event within "
                    f"{timeout}s") from None
            if ev["type"] == "error":
                raise RuntimeError(
                    f"engine loop failed: {ev['message']}")
            last = time.monotonic()
            yield ev
            if ev["type"] == "finish":
                finishes += 1

    def result(self, timeout=120.0):
        """Block until complete; returns a list of n dicts
        ({"tokens", "finish_reason"}) ordered by sample index."""
        out = [{"tokens": [], "finish_reason": None}
               for _ in range(self.n)]
        for ev in self.events(timeout=timeout):
            slot = out[ev["index"]]
            if ev["type"] == "token":
                slot["tokens"].append(ev["token"])
            else:
                slot["finish_reason"] = ev["reason"]
        return out


ROLES = ("mixed", "prefill", "decode")


class ServingFrontend:
    def __init__(self, engine, *, max_queued=64, poll_interval_s=0.001,
                 role=None):
        if engine.on_event is not None:
            raise ValueError("engine already has an on_event consumer")
        engine.on_event = self._on_event
        self.engine = engine
        role = role or os.environ.get("PADDLE_TPU_SERVING_ROLE") \
            or "mixed"
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; one of {ROLES}")
        # advertised in /healthz; a ROUTING intent, not a capability
        # limit — any engine can serve either phase, the disagg router
        # just routes prefill_only work to "prefill" replicas and page
        # adoptions to "decode" ones
        self.role = role
        self.max_queued = int(max_queued)
        self.poll_interval_s = float(poll_interval_s)
        # process identity (round 19, fleet control plane): /healthz
        # advertises pid + start time so a supervising backend (and a
        # recovering router's sweep) can tell a RESTARTED replica
        # process from the one that died — same host:port, new life
        self.started_unix = time.time()
        self.lock = threading.Lock()
        self.error = None
        self._streams: dict[int, RequestStream] = {}
        self._state = "ok"            # ok | draining | failed
        self._thread = None
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._fault_streak = 0  # consecutive FaultInjected (escalation)
        # loop naps route through the engine's chaos sleeper so fault
        # schedules stay deterministic under a fake clock (graftlint
        # serving-raw-sleep); engines always carry one since round 17
        chaos = getattr(engine, "chaos", None)
        self._sleep = chaos.sleep if chaos is not None else time.sleep

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None:
            raise RuntimeError("front-end already started")
        self._thread = threading.Thread(
            target=self._loop, name="serving-engine-loop", daemon=True)
        self._thread.start()
        return self

    @property
    def state(self):
        return self._state

    def drain(self, timeout=120.0):
        """Stop admissions, finish every in-flight request, stop the
        loop thread. Returns True when fully drained within timeout."""
        with self.lock:
            if self._state == "ok":
                self._state = "draining"
                self.engine.start_drain()
        ok = self._drained.wait(timeout)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return ok and self._state != "failed"

    def resume(self):
        """Rolling-drain re-admit: restart a DRAINED front-end (weight
        reloads happen in the drained window — weights are arguments of
        the compiled step, so the update flows through live). Raises
        unless the loop thread is parked and the state is recoverable."""
        if self._state == "failed":
            raise RuntimeError("cannot resume a failed front-end")
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("front-end not drained: loop still live")
        self._thread = None
        self._stop.clear()
        self._drained.clear()
        self.engine.resume_admissions()
        self._state = "ok"
        return self.start()

    def fail(self, exc):
        """External failure injection (the router's replica-kill hook
        and the fault-escalation path): release live pages, error every
        open stream, flip to "failed", park the loop."""
        with self.lock:
            self._fail_locked(exc)
        self._stop.set()

    def close(self, timeout=120.0):
        return self.drain(timeout)

    # -- client API --------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, **kw):
        """Admit a request; returns a RequestStream. Raises Rejected
        (429) under load shed, Unavailable (503) when draining/failed,
        ValueError for malformed requests."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = int(kw.get("n", 1))
        with self.lock:
            if self._state != "ok":
                raise Unavailable(f"front-end is {self._state}")
            self._check_capacity(prompt, int(max_new_tokens), n,
                                 prefill_only=bool(
                                     kw.get("prefill_only")))
            rid = self.engine.add_request(
                prompt, max_new_tokens=int(max_new_tokens), **kw)
            stream = RequestStream(rid, n)
            self._streams[rid] = stream
        return stream

    def cancel(self, req_id):
        """Cancel a submission (parent + any forks on its stream);
        pages return to the free list before this call returns. True
        if anything was actually cancelled."""
        with self.lock:
            stream = self._streams.get(req_id)
            ids = stream.all_ids() if stream is not None else [req_id]
            hit = False
            for rid in ids:
                hit = self.engine.cancel(rid) or hit
        return hit

    def cancel_stream(self, stream):
        """Identity-checked cancel (round 19): engine req_ids are
        PER-ENGINE sequential ints, so a caller holding a stale stream
        handle — e.g. a router teardown racing a cross-replica
        failover — can alias a DIFFERENT live request's rid on this
        engine.  Cancel only if this exact stream object still owns
        its rid here; the identity check and the cancel share the lock
        so no new owner can slip in between (the fleet harness's
        exactness gate caught the unchecked version cancelling an
        innocent stream)."""
        with self.lock:
            if self._streams.get(stream.req_id) is not stream:
                return False
            hit = False
            for rid in stream.all_ids():
                hit = self.engine.cancel(rid) or hit
        return hit

    def health(self):
        with self.lock:
            eng = self.engine
            tier_stats = eng.tier_stats()
            return {"status": self._state,
                    "role": self.role,
                    "pid": os.getpid(),
                    "started_unix": self.started_unix,
                    "waiting": eng.scheduler.queue_depth(),
                    "live": len(eng.scheduler.live_requests()),
                    "held": len(eng._held),
                    "free_pages": eng.cache.free_pages,
                    "reserved_pages": self._reserved_pages(),
                    "speculative_k": getattr(eng, "spec_k", 0),
                    # quantized serving (round 15): the cache dtype is
                    # part of the migration geometry contract, so a
                    # disagg router can see dtype skew before a page
                    # transfer bounces on GeometryMismatch
                    "cache_dtype": getattr(eng, "cache_dtype",
                                           str(eng.cache.dtype)),
                    "weight_quant": getattr(eng, "weight_quant", None),
                    # tensor-parallel serving (round 23): the shard
                    # degree is part of the pagewire geometry contract
                    # (per-shard payload lists), so a router can bounce
                    # tp-skewed transfers up front — same shape as the
                    # dtype-skew guard
                    "tp_degree": getattr(eng, "tp_degree", 1),
                    "tp_mesh": getattr(eng, "tp_mesh_shape", None),
                    # fleet prefix cache (round 18): how much reusable
                    # prefix this replica holds — the router's transfer
                    # index consults these before scheduling a ship
                    "cached_pages": eng.cache.cached_pages,
                    "reclaimable_pages": eng.cache.reclaimable_pages,
                    "prefix_tree_depth": eng.cache.prefix_tree_depth,
                    # hierarchical KV tier (round 20): host-tier
                    # occupancy — a router can prefer a warm replica
                    # (kvtier is None without a tier; the flat page
                    # count rides top-level for cheap router reads)
                    "host_pool_pages": (tier_stats or
                                        {}).get("host_pool_pages", 0),
                    "kvtier": tier_stats,
                    # versioned live deployment (round 21): the weight
                    # version each set is serving.  MUTABLE mid-life —
                    # consumers must read it fresh every time (never
                    # the cache_dtype cached-once pattern); the
                    # router's version-pin guard depends on that
                    "weight_version": dict(
                        getattr(eng, "weight_version", None) or
                        {"target": 0, "draft": 0}),
                    "requests_finished":
                        eng.metrics.requests_finished.value}

    def load(self):
        """Routing load signal: outstanding worst-case page
        reservations (the same math the shed gate charges admissions
        against). 0 = idle; the router's least-loaded policy sorts on
        this, and /healthz exposes it as ``reserved_pages`` so HTTP
        replicas report the identical number."""
        with self.lock:
            return self._reserved_pages()

    def prometheus(self):
        """Refresh the point-in-time gauges and render the exposition."""
        with self.lock:
            eng = self.engine
            m = eng.metrics
            m.queue_depth_gauge.set(eng.scheduler.queue_depth())
            m.page_occupancy_gauge.set(eng.cache.occupancy())
            m.running_gauge.set(len(eng.scheduler.running))
            return m.to_prometheus()

    # -- observability (round 16): /debug/trace + /debug/flight ------------
    def debug_trace(self, request_id=None, req_id=None):
        """Serialized span timelines for one request (by X-Request-Id
        string or engine req_id) or, with neither, every retained
        timeline.  Reads under the engine lock — a scrape never races
        the step loop's appends."""
        with self.lock:
            return {"timelines": self.engine.trace.timelines(
                request_id=request_id, req_id=req_id)}

    def debug_flight(self):
        """The engine flight ring, oldest-first, plus counters."""
        with self.lock:
            flight = self.engine.trace.flight
            return {"events": flight.dump(),
                    "recorded": flight.recorded,
                    "cap": flight.cap}

    # -- KV page migration (disaggregated serving, round 14) ---------------
    # Export/import touch the cache's device buffers and host
    # bookkeeping, so every path below holds the SAME lock as the step
    # loop — a page import racing a step would scatter into buffers the
    # in-flight program is about to replace (enforced by graftlint
    # `page-migration-lock`).
    def probe_prefix(self, prompt, hist_len=None):
        """Radix-tree transfer index: how many leading prompt pages are
        already resident HERE (the exporter skips exactly these)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if hist_len is None:
            hist_len = prompt.size + 1
        with self.lock:
            return self.engine.cache.probe_prefix(prompt, hist_len)

    def export_request(self, req_id, skip_pages=0):
        """Export a held request's page chain (meta, k, v)."""
        with self.lock:
            return self.engine.export_request(req_id, skip_pages)

    def release_request(self, req_id):
        """Drop a held request's pages once the migration committed."""
        with self.lock:
            return self.engine.release_request(req_id)

    def adopt(self, meta, k_arrays, v_arrays, *, max_new_tokens, **kw):
        """Import a migrated page chain and continue decoding it here;
        returns a RequestStream that emits only NEW tokens (the prefill
        replica's tokens ride in ``meta["out_tokens"]``).  Sheds with
        Rejected when the imported chain plus its remaining decode
        growth cannot be reserved — the router then tries another
        decode replica."""
        with self.lock:
            if self._state != "ok":
                raise Unavailable(f"front-end is {self._state}")
            eng = self.engine
            cache = eng.cache
            prompt = np.asarray(meta["prompt"], np.int32).reshape(-1)
            need = cache.pages_for(prompt.size + int(max_new_tokens))
            need -= int(meta.get("skip_pages", 0))
            promised = self._reserved_pages()
            if need + promised + eng.scheduler.watermark_pages \
                    > cache.available_pages:
                eng.metrics.rejections.inc()
                raise Rejected(
                    f"over capacity: adoption needs {need} page(s), "
                    f"{cache.available_pages} available - {promised} "
                    f"reserved - {eng.scheduler.watermark_pages} "
                    "watermark")
            rid = eng.adopt_request(meta, k_arrays, v_arrays,
                                    max_new_tokens=int(max_new_tokens),
                                    **kw)
            stream = RequestStream(rid, 1)
            self._streams[rid] = stream
        return stream

    # -- fleet prefix transfer (round 18) ----------------------------------
    # Same locking contract as migration: prefix export/import touch
    # the cache's device buffers and radix tree, so they hold the
    # engine lock (graftlint `page-migration-lock` polices the cache/
    # engine-level calls; these wrappers are the blessed call shape).
    def export_prefix(self, prompt, skip_pages=0):
        """Export this replica's cached prefix of ``prompt`` (minus
        ``skip_pages`` leading pages the recipient already holds)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self.lock:
            return self.engine.export_prefix(prompt, skip_pages)

    def import_prefix(self, meta, k_arrays, v_arrays):
        """Land a shipped prefix payload here.  Sheds with Rejected
        when hosting the pages would dip into outstanding reservations
        + watermark — a prefix ship is an optimization and must never
        evict capacity live traffic has been promised."""
        with self.lock:
            if self._state != "ok":
                raise Unavailable(f"front-end is {self._state}")
            eng = self.engine
            need = int(meta.get("n_pages", 0))
            promised = self._reserved_pages()
            if need + promised + eng.scheduler.watermark_pages \
                    > eng.cache.available_pages:
                raise Rejected(
                    f"over capacity: prefix ship needs {need} page(s), "
                    f"{eng.cache.available_pages} available - "
                    f"{promised} reserved - "
                    f"{eng.scheduler.watermark_pages} watermark")
            return eng.import_prefix(meta, k_arrays, v_arrays)

    def drop_prefix(self, prompt):
        """Evict the unpinned cached chain for ``prompt`` (router
        dedup).  Returns the number of pages freed."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self.lock:
            return self.engine.drop_prefix(prompt)

    # -- hierarchical KV tier (round 20) -----------------------------------
    def restore_prefix(self, prompt):
        """Best-effort host-tier restore of ``prompt``'s missing prefix
        pages (probe order: local device → local host tier → remote
        donor → recompute).  Restored pages land CACHED at rc==0, so
        the shed gate's probe_prefix-based accounting covers them with
        no new case.  Returns pages restored (0 without a tier)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self.lock:
            return self.engine.restore_prefix(prompt)

    def prewarm_prefix(self, max_chains=None):
        """Restore the hottest spilled chains (autoscaler pre-warm of
        a freshly grown replica).  Returns pages restored."""
        with self.lock:
            return self.engine.prewarm_prefix(max_chains)

    # -- versioned live weight deployment (round 21) -----------------------
    def swap_weights(self, which, arrays, version):
        """The deployer's quiesce-swap — the ONE blessed multi-threaded
        path to ``engine.set_weights`` (graftlint ``weight-swap-lock``).
        The lock below is held across every engine step, so acquiring
        it IS the one-step quiesce: no compiled program can be
        mid-flight while the argument pytree changes, whether the loop
        is live (a mid-traffic draft refresh) or parked (a drained
        target rollout).  All-or-nothing and raising on a torn payload
        — the OLD version keeps serving on any failure.  Returns the
        number of stale-weight prefix pages flushed."""
        t0 = time.perf_counter()
        with self.lock:
            if self._state == "failed":
                raise Unavailable("front-end is failed")
            flushed = self.engine.set_weights(which, arrays, version)
            self.engine.metrics.weight_swap_s.record(
                time.perf_counter() - t0)
        return flushed

    def weight_version(self, which="target"):
        """Fresh read of the serving weight version (never cached —
        versions are mutable mid-life, unlike cache_dtype)."""
        return self.engine.weight_version.get(which)

    # -- internals ---------------------------------------------------------
    def _check_capacity(self, prompt, max_new, n, prefill_only=False):
        """Reservation admission (no-preemption envelope): reject when
        the waiting queue is full or the worst-case page need cannot be
        covered on top of all outstanding reservations + watermark.

        Prefix-cache accounting: the need counts only UNCACHED pages
        (``probe_prefix`` lookup — the matched pages are pinned by
        ``add_request`` under this same lock, so they cannot be evicted
        between this check and admission), and every queued request's
        reservation is likewise net of the pages it already holds
        pinned. Cached-but-unpinned pages count as capacity
        (``available_pages``) because eviction turns them into free
        pages on demand."""
        eng = self.engine
        sched, cache = eng.scheduler, eng.cache
        prompt_len = int(prompt.size)
        if sched.queue_depth() >= self.max_queued:
            eng.metrics.rejections.inc()
            if eng.trace.enabled:
                eng.trace.flight.record("shed", cause="queue_full",
                                        waiting=sched.queue_depth())
            raise Rejected(
                f"intake queue full ({self.max_queued} waiting)")
        # a prefill-only request stops after its first sampled token:
        # its worst case is prompt+1, never prompt+max_new — the
        # reservation asymmetry that makes a dedicated prefill replica
        # admit deep bursts a mixed replica would shed
        worst_new = 1 if prefill_only else max_new
        need = cache.pages_for(prompt_len + worst_new) * n
        need -= cache.probe_prefix(prompt)  # shared across the n forks
        promised = self._reserved_pages()
        if need + promised + sched.watermark_pages \
                > cache.available_pages:
            eng.metrics.rejections.inc()
            if eng.trace.enabled:
                eng.trace.flight.record("shed", cause="over_capacity",
                                        need=need, reserved=promised)
            raise Rejected(
                f"over capacity: need {need} page(s), "
                f"{cache.available_pages} available - {promised} "
                f"reserved - {sched.watermark_pages} watermark")

    def _reserved_pages(self):
        """Sum of every accepted request's outstanding worst-case page
        reservation (full prompt+max_new ×n, net of pages already
        held). Call under the lock."""
        eng = self.engine
        cache, sched = eng.cache, eng.scheduler
        promised = 0
        for r in list(sched.live_requests()) + list(sched.waiting):
            worst_new = 1 if r.prefill_only else r.max_new_tokens
            promised += max(
                0, cache.pages_for(r.prompt.size + worst_new)
                * r.n - cache.pages_held(r.seq_id))
        return promised

    def _on_event(self, ev):
        # runs in whichever thread holds the lock and drives the engine
        # (the loop thread via step(), a handler thread via cancel())
        rid = ev["req_id"]
        stream = self._streams.get(rid)
        if stream is None:
            req = self.engine.request(rid)
            pid = getattr(req, "parent_id", None)
            if pid is None or pid not in self._streams:
                return  # not a front-end submission
            stream = self._streams[pid]
            self._streams[rid] = stream
        stream._push(dict(ev, index=stream._index_for(rid)))
        if ev["type"] == "finish" and stream.done:
            for r in stream.all_ids():
                self._streams.pop(r, None)

    def _loop(self):
        eng = self.engine
        try:
            while not self._stop.is_set():
                with self.lock:
                    if self._state == "failed":
                        return  # externally killed (fail()); stop cold
                    idle = eng.scheduler.all_done()
                    if not idle:
                        try:
                            eng.step()
                            self._fault_streak = 0
                        except FaultInjected as exc:
                            # counted; boundary fault — retry next. But
                            # a fault STREAK means the replica is sick,
                            # not unlucky: escalate to a loop failure
                            # (streams error out, the router fails the
                            # requests over to a healthy replica). The
                            # threshold rides ChaosConfig (the legacy
                            # FAULT_ESCALATE_N env knob aliases in)
                            self._fault_streak += 1
                            esc = self._escalate_n()
                            if esc and self._fault_streak >= esc:
                                self._fail_locked(RuntimeError(
                                    f"fault escalation after "
                                    f"{self._fault_streak} consecutive "
                                    f"faults: {exc}"))
                                return
                        except Exception as exc:  # fatal: clean + report
                            self._fail_locked(exc)
                            return
                    elif self._state == "draining":
                        # quiesce: a live chaos alloc-pressure spike
                        # must not outlive the drained loop
                        eng._release_chaos_spike()
                        return
                    else:
                        # idle upkeep: held-deadline sweep + chaos
                        # alloc-spike countdown — a pure prefill
                        # replica idles between handoffs, and its held
                        # pages must still expire on deadline
                        eng.chaos_idle_tick()
                # idle: nap off-lock; busy: yield so submitters can
                # grab the lock between steps
                self._sleep(self.poll_interval_s if idle else 0)
        finally:
            self._drained.set()

    def _escalate_n(self):
        chaos = getattr(self.engine, "chaos", None)
        if chaos is None:
            return int(os.environ.get(
                "PADDLE_TPU_SERVING_FAULT_ESCALATE_N", "0") or 0)
        return int(chaos.cfg.escalate_n)

    def _fail_locked(self, exc):
        self._state = "failed"
        self.error = exc
        trace = self.engine.trace
        if trace.enabled:
            # the flight-recorder dump: the ring holds the failing
            # step's batch composition (step_begin precedes the device
            # work), so the round-9/11 loop-failure classes are
            # post-mortem-able from the structured log alone
            trace.flight.record("loop_error", error=repr(exc))
            _log.error(json.dumps({
                "event": "flight_recorder_dump",
                "error": repr(exc),
                "recorded": trace.flight.recorded,
                "events": trace.flight.dump()}))
        try:
            self.engine.release_live()
        except Exception:
            pass
        for stream in set(self._streams.values()):
            stream._fail(exc)
        self._streams.clear()
