"""paddle_tpu.serving — continuous-batching inference engine with a
block-paged KV cache (reference capability: Paddle's serving stack —
paddle.inference at scale / FastDeploy — and the vLLM/TPU
ragged-paged-attention design, PAPERS.md).

Layers:
- :mod:`kv_cache`   — paged K/V pool: free-list allocator, per-sequence
  page tables, refcounted copy-on-fork (n>1 sampling), budget sizing;
  round 10: radix-tree prefix cache (full-prompt-page reuse, LRU leaf
  eviction, uncached-only accounting) behind ``prefix_cache=True``.
- :mod:`sampling`   — fused on-device sampler (round 10): greedy/
  temperature/top-k/top-p with per-lane counter RNG inside the compiled
  step; the per-step host fetch is [B] ids + [B] logprobs, not [B, V]
  logits (host numpy oracle behind PADDLE_TPU_SERVING_HOST_SAMPLE=1).
- :mod:`attention`  — paged attention: jax gather reference path
  (oracle-parity with the contiguous static cache) + ONE unified
  ragged Pallas kernel gated behind ``PADDLE_TPU_PAGED_KERNEL``
  (interpret-mode only; round 22 folded the decode-only stub into it —
  ``ragged_paged_attention`` is the token-packed mixed-batch entry).
- :mod:`scheduler`  — continuous batching: watermark admission, chunked
  prefill, decode-priority iteration, deadlines, LIFO preemption.
- :mod:`engine`     — bucketed fixed-shape compiled step (weights as
  arguments) + :mod:`metrics` (TTFT / inter-token / occupancy JSON +
  Prometheus exposition). Round 9: per-token ``on_event`` streaming,
  ``cancel()`` (pages freed, queues purged), ``drain()`` mode,
  env-gated fault injection at the step boundary, failure-path page
  release. Round 12: batched speculative decoding
  (``draft_model=``/``speculative_k=`` — fused k+1-step draft-propose
  scan + ONE [B, k+1] verify step with deterministic-sample
  acceptance: greedy AND seeded-sampled streams token-exact vs the
  plain engine; accounting-only rollback via
  ``PagedKVCache.free_tail``; admission reserves the verify burst).
- :mod:`frontend`   — thread-safe request bridge: lock-serialized
  engine loop thread, per-request token streams, reservation-based
  load shedding (429) and graceful drain (503).
- :mod:`server`     — stdlib OpenAI-compatible HTTP front-end:
  /v1/completions + /v1/chat/completions (SSE streaming), /healthz,
  /metrics; disconnect-driven cancellation; round 11: SSE keepalive
  pings (bounded disconnect detection) + X-Request-Id propagation.
- :mod:`replica` / :mod:`router` — the multi-replica tier (round 11):
  ``ServingRouter`` fronts N replicas (in-process frontends or remote
  HTTP servers) behind the same front-end surface, with round-robin /
  least-loaded / prefix-cache-aware routing, token-exact mid-stream
  failover (determinism-backed stream splicing), aggregated 429
  admission, rolling drain + weight-reload re-admit, and a merged
  ``replica``-labelled /metrics.

- :mod:`disagg` / :mod:`pagewire` / :mod:`autoscale` — the
  disaggregated tier (round 14): ``DisaggRouter`` routes admissions to
  prefill-role replicas (``prefill_only`` requests hold their pages at
  the first token), migrates the KV page chain to a decode-role
  replica (radix tree as transfer index — only the uncached suffix
  moves; in-process array handoff or the ``/v1/_pages`` wire format),
  and splices the streams token-exactly; ``FleetAutoscaler`` grows the
  fleet from a replica factory and shrinks it through the rolling
  drain, driven by reserved-page load + TTFT histogram windows.

- :mod:`trace` — serving-wide observability (round 16): an always-on
  capped span timeline per request (queued/prefill/decode/spec/
  preempt/recompute/prefix-hit/migration/failover-splice/held, emitted
  under the existing locks) + a per-engine flight recorder ring
  (step composition/wall, admissions, sheds, preemptions, faults,
  drain, loop errors — dumped to the structured log on loop failure);
  ``/debug/trace?request_id=`` and ``/debug/flight`` expose both as
  JSON, router-merged across replicas like /metrics; completed
  timelines export as chrome://tracing JSON in the
  ``paddle_tpu.profiler`` event format (``bench_serving.py
  --trace-out``).

- Fleet-wide prefix cache (round 18): the router's affinity radix
  tree doubles as a KV-page TRANSFER INDEX (``prefix_fleet=True`` /
  ``PADDLE_TPU_SERVING_PREFIX_FLEET=1``) — on a prefix miss at the
  routed replica but a hit anywhere in the fleet, the cached prefix
  pages ship over the pagewire path (in-process array handoff or
  ``/v1/_pages/prefix``) instead of being recomputed; the target
  chunk-prefills only the uncovered suffix.  Donor liveness and
  eviction races resolve through the PrefixDrift/GeometryMismatch
  bounce into a recompute fallback (never a failed request), the
  router consults the ``/healthz``-advertised ``cache_dtype`` so
  dtype-skewed fleets skip doomed ships up front, and
  ``prefix_max_owners`` dedups hot prefixes across replicas
  (router-driven ``drop_prefix`` eviction pressure).

- :mod:`chaos` — the robustness layer (round 17): ONE seeded
  deterministic fault schedule (``ChaosConfig`` — the legacy FAULT_*
  knobs alias in) over 15 registered fault points (engine step
  fault/latency, allocator-pressure spikes, migration export/import/
  transfer failures, HTTP connect/EOF/slow-read, replica crash during
  drain/readmit/shrink, prefix-ship donor-gone/eviction-race/
  torn-payload), the injected sleeper every serving sleep
  routes through (graftlint ``serving-raw-sleep``), bounded
  exponential-backoff retries (migration + idempotent HTTP hops),
  per-replica circuit breakers (``/healthz``-advertised, /metrics
  counted, flight-dumped on open), held-page release on deadline
  expiry, and the global recovery invariants the chaos fuzz
  (``tools/chaos_fuzz.py``) asserts after every convulsion.

- :mod:`fleet` / :mod:`fleet_worker` — the crash-survivable fleet
  control plane (round 19): ``ProcessReplicaBackend`` provisions REAL
  replica server processes for the autoscaler (ephemeral ports,
  bounded ``/healthz`` readiness, liveness supervision with
  restart-backoff under a per-replica budget, every process reaped on
  every exit path incl. a parent-death self-reap watchdog in the
  worker); ``RouterJournal`` (CRC-framed append-only JSONL, torn
  records skipped on replay, bounded rotation) + one ``/healthz``
  sweep make EVERY piece of routing state rebuildable — a cold router
  (``ServingRouter.recover``) converges to a never-crashed router's
  decisions within one sweep; ``RouterSupervisor`` runs primary +
  warm standby with idempotent takeover — accepted streams survive
  the router's own death token-exactly via the client-side splice,
  and the autoscaler's pressure signal now also reads breaker state
  and shed/failover deltas (browning-out fleets grow BEFORE the SLOs
  blow; flapping replicas rotate out via drain-by-health).  Proof at
  scale: ``tools/fleet_harness.py`` (bursty/diurnal traffic + seeded
  concurrent chaos, SLO-gated, ``BENCH_serving_fleet.json``).

- :mod:`kvtier` — hierarchical KV-cache tiers (round 20): a
  byte-budgeted LRU ``HostPagePool`` (``PADDLE_TPU_SERVING_HOST_POOL_
  MB``) with an optional file-backed ``DiskPagePool`` under it, bound
  behind ``PagedKVCache`` via ``attach_tier``.  rc-0 cached pages
  evicted by allocation pressure spill their pagewire payload (int8
  codes+scales ride intact) to the host tier at step boundaries; a
  prefix probe that misses device pages but hits the tier restores
  them through the same fused gather/scatter import path as a remote
  ship (pages re-enter CACHED at rc==0, so the shed gate's
  probe-based accounting covers them with no new case).  Probe order:
  local device → local host tier → remote donor → recompute.
  Strictly best-effort: spill/restore failures, dtype/geometry skew,
  CRC-caught bit-rot (the pagewire payload checksum), and capacity
  sheds all degrade to the recompute the engine would have done
  anyway.  The autoscaler pre-warms freshly grown replicas from the
  hottest spilled chains (``prewarm_prefix``).

- :mod:`deploy` / :mod:`distill` — versioned live weight deployment +
  online draft distillation (round 21): a ``WeightRegistry`` (monotonic
  version ids across named weight sets — "target"/"draft" — in-memory
  handles with atomic npz spill-to-disk) and a ``RollingDeployer`` that
  hot-swaps one replica at a time: router-level drain (in-flight
  streams FINISH on the version they started on), a one-step quiesce
  under the engine lock (weights are ARGUMENTS of the compiled step —
  the swap is a pytree write, zero recompile), stale-weight K/V flush
  (``clear_prefix`` detaches + invalidates the spilled tiers too),
  ``/healthz``-advertised ``weight_version``, re-admit.  Routers PIN
  every stream to the version it started on (failover re-placement
  skips version-skewed replicas; prefix ships skip version-skewed
  donors) so no stream ever splices tokens from two versions.  The
  swap itself (``engine.set_weights`` — the graftlint
  ``weight-swap-lock`` blessed mutation site) validates the payload
  all-or-nothing, so a torn push degrades to serving the old version.
  ``DraftDistiller`` closes the training↔serving loop: the speculative
  verify step logs (history, target-token) pairs for free, a
  background trainer distills the draft on them, and refreshed draft
  weights roll out through the same deployer fully live (the draft
  only PROPOSES — the target's verify decides every emitted token, so
  a mid-stream draft refresh moves acceptance rate, never output).
  Proof: ``tools/deploy_harness.py`` (rolling deploy under SLO-gated
  traffic + chaos, ``BENCH_serving_deploy.json``).

- :mod:`tp` — tensor-parallel SPMD serving (round 23):
  ``ServingEngine(mesh=...)`` / ``tp_degree=k`` runs the whole
  decode/prefill/ragged step as ONE GSPMD program over a device mesh —
  weights committed to mesh shardings (last-output-dim splits composed
  on top of fleet dist_specs via ``_add_sharding``, never returned
  verbatim), KV page pools sharded on the head axis (one allocator,
  replicated page tables), paged attention pinned to the jnp gather
  path (``pallas_call`` has no GSPMD rule — the kernel knob demotes
  loudly: log + ``tp_kernel_fallbacks``), and fused sampling still
  in-program with the partial (vocab-column-sliced) logits
  all-gathered only at the sampled lane.  Because only non-contracting
  dims shard, every matmul keeps its full contraction local — a TP=k
  replica streams token-exact vs TP=1 (greedy AND seeded, across
  preemption/recompute).  ``/healthz`` advertises
  ``tp_degree``/``tp_mesh``, pagewire payloads grow per-shard lists
  (scales ride every shard), and tp-skewed transfers bounce to the
  re-prefill fallback exactly like dtype skew.

Drivers: ``bench_serving.py`` (repo root) replays a Poisson trace —
offline through the engine, or over real sockets with ``--server`` —
and emits the BENCH_serving artifacts. Docs: ``docs/SERVING.md``.
"""
from .attention import (paged_attention, paged_attention_ref,  # noqa: F401
                        ragged_paged_attention)
from .autoscale import FleetAutoscaler  # noqa: F401
from .chaos import (FAULT_POINTS, Backoff, ChaosConfig,  # noqa: F401
                    ChaosInjector, CircuitBreaker)
from .deploy import (DeployError, RollingDeployer,  # noqa: F401
                     WeightRegistry, snapshot_weights)
from .disagg import DisaggRouter, DisaggStream  # noqa: F401
from .distill import (DistillBuffer, DraftDistiller,  # noqa: F401
                      distill_buffer_from_env)
from .engine import (EngineDraining, FaultInjected,  # noqa: F401
                     ServingEngine)
from .fleet import (ProcessReplica, ProcessReplicaBackend,  # noqa: F401
                    ReplicaSpec, RouterCrashed, RouterJournal,
                    RouterSupervisor, SubprocessLauncher,
                    ThreadLauncher)
from .frontend import (Rejected, RequestStream,  # noqa: F401
                       ServingFrontend, Unavailable)
from .kv_cache import (SCRATCH_PAGE, GeometryMismatch,  # noqa: F401
                       OutOfPages, PagedKVCache, PrefixDrift)
from .kvtier import (DiskPagePool, HostPagePool,  # noqa: F401
                     KVTier, chain_key, host_pool_from_env)
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      LabeledCounter, ServingMetrics)
from .pagewire import (WireFormatError, deserialize_pages,  # noqa: F401
                       serialize_pages)
from .replica import (HTTPReplica, InProcessReplica,  # noqa: F401
                      ReplicaFailed)
from .router import RouterStream, ServingRouter  # noqa: F401
from .sampling import fused_sample  # noqa: F401
from .scheduler import (Request, RequestState, Scheduler,  # noqa: F401
                        SchedulerOutput)
from .server import ServingServer  # noqa: F401
from .tp import TP_AXIS, TPContext, resolve_tp  # noqa: F401
from .trace import (FlightRecorder, RequestTrace,  # noqa: F401
                    ServingTrace, chrome_trace_events,
                    export_chrome_trace)

__all__ = [
    "PagedKVCache", "OutOfPages", "SCRATCH_PAGE",
    "paged_attention", "paged_attention_ref", "ragged_paged_attention",
    "fused_sample",
    "Scheduler", "SchedulerOutput", "Request", "RequestState",
    "ServingEngine", "EngineDraining", "FaultInjected",
    "ServingMetrics", "Counter", "Gauge", "Histogram", "LabeledCounter",
    "ServingFrontend", "RequestStream", "Rejected", "Unavailable",
    "ServingServer",
    "ServingRouter", "RouterStream", "InProcessReplica", "HTTPReplica",
    "ReplicaFailed",
    "DisaggRouter", "DisaggStream", "FleetAutoscaler",
    "GeometryMismatch", "PrefixDrift", "WireFormatError",
    "serialize_pages", "deserialize_pages",
    "ServingTrace", "RequestTrace", "FlightRecorder",
    "chrome_trace_events", "export_chrome_trace",
    "ChaosConfig", "ChaosInjector", "Backoff", "CircuitBreaker",
    "FAULT_POINTS",
    "ProcessReplica", "ProcessReplicaBackend", "ReplicaSpec",
    "RouterCrashed", "RouterJournal", "RouterSupervisor",
    "SubprocessLauncher", "ThreadLauncher",
    "DiskPagePool", "HostPagePool", "KVTier", "chain_key",
    "host_pool_from_env",
    "DeployError", "RollingDeployer", "WeightRegistry",
    "snapshot_weights",
    "DistillBuffer", "DraftDistiller", "distill_buffer_from_env",
    "TPContext", "resolve_tp", "TP_AXIS",
]
