"""paddle_tpu.serving — continuous-batching inference engine with a
block-paged KV cache (reference capability: Paddle's serving stack —
paddle.inference at scale / FastDeploy — and the vLLM/TPU
ragged-paged-attention design, PAPERS.md).

Layers:
- :mod:`kv_cache`   — paged K/V pool: free-list allocator, per-sequence
  page tables, refcounted copy-on-fork (n>1 sampling), budget sizing.
- :mod:`attention`  — paged attention: jax gather reference path
  (oracle-parity with the contiguous static cache) + a Pallas stub
  gated behind ``PADDLE_TPU_PAGED_KERNEL`` (interpret-mode only).
- :mod:`scheduler`  — continuous batching: watermark admission, chunked
  prefill, decode-priority iteration, deadlines, LIFO preemption.
- :mod:`engine`     — bucketed fixed-shape compiled step (weights as
  arguments) + :mod:`metrics` (TTFT / inter-token / occupancy JSON).

Driver: ``bench_serving.py`` (repo root) replays a Poisson trace and
emits the BENCH_serving artifact. Docs: ``docs/SERVING.md``.
"""
from .attention import paged_attention, paged_attention_ref  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .kv_cache import SCRATCH_PAGE, OutOfPages, PagedKVCache  # noqa: F401
from .metrics import Counter, Histogram, ServingMetrics  # noqa: F401
from .scheduler import (Request, RequestState, Scheduler,  # noqa: F401
                        SchedulerOutput)

__all__ = [
    "PagedKVCache", "OutOfPages", "SCRATCH_PAGE",
    "paged_attention", "paged_attention_ref",
    "Scheduler", "SchedulerOutput", "Request", "RequestState",
    "ServingEngine", "ServingMetrics", "Counter", "Histogram",
]
