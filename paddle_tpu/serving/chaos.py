"""Fleet-wide chaos harness + retry/backoff/circuit-breaker layer.

The serving stack accumulated *piecemeal* fault machinery one PR at a
time — env-gated step faults in the engine, the front-end's
``_fault_streak`` escalation, the router's ``ROUTER_KILL`` drill, the
failover splice, the health prober, the flight recorder.  Each point
was hand-tested once in its own PR; nothing drove *combinations* of
faults against the whole fleet or checked the global recovery
invariants.  This module is that missing layer (reference capability:
Paddle Fleet's elastic fault tolerance — replicas die, requests
survive, capacity degrades gracefully; the Gemma-on-TPU serving paper
evaluates exactly this replica-churn regime):

- :class:`ChaosConfig` — ONE seeded, deterministic fault schedule that
  unifies the legacy knobs (``PADDLE_TPU_SERVING_FAULT_LATENCY_S``,
  ``_FAULT_ERROR_RATE``, ``_FAULT_SEED``, ``_FAULT_ESCALATE_N``,
  ``_ROUTER_KILL`` — all still honored as aliases) with the new fault
  points: pagewire migration failures (export fail / import bounce /
  mid-transfer kill), HTTPReplica network faults (connect refused,
  mid-stream EOF, slow reads), allocator pressure spikes, and replica
  crashes during drain/readmit/autoscaler shrink.  Injected via
  constructor (``chaos=``) or env (``PADDLE_TPU_SERVING_CHAOS``).
- :class:`ChaosInjector` — the per-component firing engine: one
  persistent RNG stream per fault point (schedules are reproducible
  per seed regardless of which OTHER points are enabled), per-point
  fired counters (the fuzz harness's coverage report), recording into
  the component's flight ring, and the **injected sleeper** every
  retry/backoff/latency sleep in the serving tier must route through
  (graftlint ``serving-raw-sleep``) so chaos schedules stay
  deterministic and tests can collapse time.
- :class:`Backoff` — bounded exponential backoff with deterministic
  jitter for page-migration and HTTP replica hops.  Retrying those is
  safe by the existing idempotency contracts: a bounced import leaves
  no state behind (GeometryMismatch/PrefixDrift re-export), and an
  exhausted retry budget falls back to the re-prefill path.
- :class:`CircuitBreaker` — per-replica closed → open → half-open →
  closed state machine with an injectable clock; the router excludes
  open replicas from routing, feeds the health prober with the
  cooldown gate, advertises the state in ``/healthz`` and counts
  opens/retries in ``/metrics``.
- Invariant checks (:func:`verify_page_conservation`,
  :func:`verify_engine_quiescent`, :func:`fleet_invariants`) — the
  global recovery conditions the chaos fuzz asserts after every
  convulsion: two-allocator page conservation, zero leaked
  reservations/held pages, allocator-clean idle engines.

Nothing here imports jax and nothing touches a device: the whole layer
is host bookkeeping, CPU-mesh-verifiable by construction.
"""
from __future__ import annotations

import json
import logging
import os
import time
import zlib
from collections import Counter as _Tally

import numpy as np

__all__ = ["Backoff", "ChaosConfig", "ChaosInjector", "CircuitBreaker",
           "FAULT_POINTS", "fleet_invariants",
           "verify_engine_quiescent", "verify_page_conservation",
           "verify_tier_conservation"]

_log = logging.getLogger("paddle_tpu.serving")

# The registered fault points.  The fuzz harness (tools/chaos_fuzz.py)
# reports per-point fired counts over a run and FAILS on a never-fired
# point, so a new fault hook must be added here in the same commit.
FAULT_POINTS = (
    "step_fault",            # engine: FaultInjected at the step boundary
    "step_latency",          # engine: added per-step latency
    "alloc_pressure",        # engine: chaos seq grabs free pages N steps
    "migrate_export_fail",   # disagg: source export dies (partial export)
    "migrate_import_bounce",  # disagg: destination bounces the import
    "migrate_transfer_kill",  # disagg: destination dies mid-transfer
    "http_connect",          # HTTPReplica: connection refused
    "http_midstream_eof",    # HTTPReplica: SSE stream EOF mid-decode
    "http_slow_read",        # HTTPReplica: slow response read
    "crash_drain",           # router: replica crash during drain
    "crash_readmit",         # router: replica crash during readmit
    "crash_shrink",          # router: replica crash during autoscaler
    #                                  shrink (retire_replica)
    # fleet prefix transfer (round 18): faults on the router-driven
    # prefix ship path — every one must degrade to recompute, never to
    # a failed request
    "prefix_export_gone",    # router: donor lost the pages mid-export
    "prefix_import_drift",   # router: recipient tree changed (eviction
    #                                  race) -> PrefixDrift bounce
    "prefix_wire_truncate",  # HTTPReplica: torn prefix payload
    # fleet control plane (round 19): the crash-survivable tier — every
    # one must converge back to a correct fleet view, never lose an
    # accepted stream
    "router_crash",          # supervisor: primary router dies mid-
    #                          stream (clients retry on the standby)
    "standby_takeover_race",  # supervisor: a concurrent promotion races
    #                           the takeover (must be idempotent)
    "replica_proc_kill",     # backend: replica server process SIGKILLed
    #                          (supervision restarts within budget)
    "journal_torn_write",    # journal: a record is torn mid-write
    #                          (replay must skip it, not die)
    # hierarchical KV tiers (round 20): faults on the host/disk spill
    # and restore paths — strictly best-effort, every one must degrade
    # to the eviction/recompute the engine would have done anyway
    "tier_spill_fail",       # kvtier: a deferred spill is dropped
    #                          (page evicts uncached, as before tiers)
    "tier_restore_fail",     # kvtier: a restore probe dies -> miss
    "tier_slow_io",          # kvtier: spill/restore I/O latency
    "tier_corrupt_payload",  # kvtier: at-rest bit-rot — the pagewire
    #                          CRC must catch it, entry dropped
    # versioned live deployment (round 21): faults on the rolling
    # weight-swap and draft-distillation push paths — every one must
    # degrade to serving the OLD version, never to a failed request
    "deploy_swap_fail",      # deployer: swap dies pre-apply (replica
    #                          keeps serving the old version)
    "deploy_stale_version",  # deployer: post-swap /healthz scrape is
    #                          stale -> one fresh re-read converges
    "distill_push_torn",     # distiller: torn weight payload -> the
    #                          all-or-nothing swap validation bounces
    # tensor-parallel serving (round 23): a tp-skewed page transfer —
    # adopt/import raises GeometryMismatch, which must bounce to the
    # existing re-prefill/recompute fallback, never fail the request
    "shard_geometry_mismatch",  # engine: per-shard payload geometry
    #                             (tp_degree) skew on adopt/import
)

# legacy aliases (round 9/11 knobs) folded into the unified config
_ENV_LATENCY = "PADDLE_TPU_SERVING_FAULT_LATENCY_S"
_ENV_RATE = "PADDLE_TPU_SERVING_FAULT_ERROR_RATE"
_ENV_SEED = "PADDLE_TPU_SERVING_FAULT_SEED"
_ENV_ESCALATE = "PADDLE_TPU_SERVING_FAULT_ESCALATE_N"
_ENV_ROUTER_KILL = "PADDLE_TPU_SERVING_ROUTER_KILL"
# the unified schedule knobs
_ENV_CHAOS = "PADDLE_TPU_SERVING_CHAOS"
_ENV_CHAOS_SEED = "PADDLE_TPU_SERVING_CHAOS_SEED"
_ENV_SLOW_READ = "PADDLE_TPU_SERVING_CHAOS_SLOW_READ_S"
# retry/backoff + circuit-breaker production knobs
_ENV_RETRY_MAX = "PADDLE_TPU_SERVING_RETRY_MAX"
_ENV_RETRY_BASE = "PADDLE_TPU_SERVING_RETRY_BASE_S"
_ENV_RETRY_CAP = "PADDLE_TPU_SERVING_RETRY_MAX_S"
_ENV_BREAKER_N = "PADDLE_TPU_SERVING_BREAKER_N"
_ENV_BREAKER_COOLDOWN = "PADDLE_TPU_SERVING_BREAKER_COOLDOWN_S"


def _env_float(name, default):
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else float(default)
    except ValueError:
        return float(default)


def _env_int(name, default):
    v = os.environ.get(name)
    try:
        return int(v) if v not in (None, "") else int(default)
    except ValueError:
        return int(default)


def parse_rates(spec):
    """``"step_fault:0.05,http_midstream_eof:0.2"`` → rate dict.
    Unknown point names raise — a typo'd schedule must not silently
    disable the fault it meant to enable."""
    rates = {}
    if not spec:
        return rates
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        point, _, rate = part.partition(":")
        point = point.strip()
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown chaos fault point {point!r}; one of "
                f"{FAULT_POINTS}")
        rates[point] = float(rate or 1.0)
    return rates


class ChaosConfig:
    """One deterministic fault schedule for a serving component.

    ``rates`` maps fault-point name → per-evaluation firing
    probability; latency-shaped points also carry a duration
    (``step_latency_s``, ``slow_read_s``).  ``from_env()`` folds the
    legacy scattered knobs in as aliases, so every pre-existing fault
    drill keeps working unchanged while new code configures ONE
    object."""

    def __init__(self, *, seed=0, rates=None, step_latency_s=0.0,
                 slow_read_s=0.0, tier_slow_io_s=0.0, escalate_n=0,
                 router_kill=None,
                 alloc_pressure_frac=0.5, alloc_pressure_steps=4,
                 retry_max=3, retry_base_s=0.05, retry_max_s=2.0,
                 breaker_n=3, breaker_cooldown_s=5.0):
        self.seed = int(seed)
        self.rates = dict(rates or {})
        for point in self.rates:
            if point not in FAULT_POINTS:
                raise ValueError(
                    f"unknown chaos fault point {point!r}; one of "
                    f"{FAULT_POINTS}")
        self.step_latency_s = float(step_latency_s)
        self.slow_read_s = float(slow_read_s)
        # duration the tier_slow_io point sleeps when it fires (the
        # spill/restore analogue of slow_read_s)
        self.tier_slow_io_s = float(tier_slow_io_s)
        self.escalate_n = int(escalate_n)
        self.router_kill = router_kill  # (replica_idx, after_tokens)
        self.alloc_pressure_frac = float(alloc_pressure_frac)
        self.alloc_pressure_steps = int(alloc_pressure_steps)
        self.retry_max = int(retry_max)
        self.retry_base_s = float(retry_base_s)
        self.retry_max_s = float(retry_max_s)
        self.breaker_n = int(breaker_n)
        self.breaker_cooldown_s = float(breaker_cooldown_s)

    @classmethod
    def from_env(cls):
        """Resolve the unified schedule from the environment.  Legacy
        knobs are ALIASES: ``FAULT_ERROR_RATE`` feeds the
        ``step_fault`` rate, ``FAULT_LATENCY_S`` enables
        ``step_latency`` at rate 1 with that duration, ``FAULT_SEED``
        seeds the injector (``CHAOS_SEED`` wins when both are set),
        ``FAULT_ESCALATE_N`` is the front-end escalation streak and
        ``ROUTER_KILL`` the router availability drill."""
        rates = parse_rates(os.environ.get(_ENV_CHAOS))
        rate = os.environ.get(_ENV_RATE)
        if rate:
            rates.setdefault("step_fault", float(rate))
        latency = _env_float(_ENV_LATENCY, 0.0)
        if latency > 0:
            rates.setdefault("step_latency", 1.0)
        kill = os.environ.get(_ENV_ROUTER_KILL)
        router_kill = None
        if kill:
            idx, after = kill.split(":")
            router_kill = (int(idx), int(after))
        seed = _env_int(_ENV_CHAOS_SEED, _env_int(_ENV_SEED, 0))
        return cls(
            seed=seed, rates=rates, step_latency_s=latency,
            slow_read_s=_env_float(_ENV_SLOW_READ, 0.0),
            escalate_n=_env_int(_ENV_ESCALATE, 0),
            router_kill=router_kill,
            retry_max=_env_int(_ENV_RETRY_MAX, 3),
            retry_base_s=_env_float(_ENV_RETRY_BASE, 0.05),
            retry_max_s=_env_float(_ENV_RETRY_CAP, 2.0),
            breaker_n=_env_int(_ENV_BREAKER_N, 3),
            breaker_cooldown_s=_env_float(_ENV_BREAKER_COOLDOWN, 5.0))

    def rate(self, point):
        return float(self.rates.get(point, 0.0))

    @property
    def any_enabled(self):
        return bool(self.rates) or self.step_latency_s > 0


class ChaosInjector:
    """Deterministic fault firing for one serving component.

    ``config=None`` (the default) runs in ENV MODE: the schedule is
    re-resolved from the environment at every evaluation, which is
    what keeps the legacy monkeypatch-mid-test workflow working (tests
    flip ``PADDLE_TPU_SERVING_FAULT_ERROR_RATE`` on a live engine).
    An explicit :class:`ChaosConfig` freezes the schedule.

    Each fault point draws from its OWN persistent RNG stream (seeded
    from ``seed`` + the point name), so enabling one point never
    perturbs another point's schedule — the property that makes a
    multi-seed fuzz shrinkable to a single failing point.

    ``sleep`` is the injected sleeper: every latency/backoff sleep in
    the serving tier routes through here (graftlint
    ``serving-raw-sleep``), so a fake sleeper collapses chaos time in
    tests and the fuzz harness.
    """

    def __init__(self, config=None, *, name="engine", sleep=None,
                 trace=None):
        self._config = config
        self.name = name
        self._sleep = sleep if sleep is not None else time.sleep
        self._trace = trace      # ServingTrace; bound late by owners
        self.counts = _Tally()   # fault point -> times fired
        self.evaluated = _Tally()
        self._rngs = {}
        self._seed = (config.seed if config is not None
                      else _env_int(_ENV_CHAOS_SEED,
                                    _env_int(_ENV_SEED, 0)))

    # -- configuration -----------------------------------------------------
    @property
    def cfg(self):
        return (self._config if self._config is not None
                else ChaosConfig.from_env())

    def bind(self, trace):
        """Late-bind the owning component's trace store (the engine
        builds its trace after its injector)."""
        self._trace = trace
        return self

    def _rng(self, point):
        rng = self._rngs.get(point)
        if rng is None:
            rng = self._rngs[point] = np.random.default_rng(
                (self._seed & 0xFFFFFFFF) ^ zlib.crc32(point.encode()))
        return rng

    # -- firing ------------------------------------------------------------
    def fire(self, point, cfg=None, **info):
        """Evaluate one fault point; True when it fires (counted and
        recorded to the flight ring).  The RNG draw happens on every
        evaluation with a nonzero rate, so a given seed produces the
        same fire/no-fire sequence per point regardless of outcome
        handling.  ``cfg`` reuses an already-resolved config (the
        engine's per-step hot path resolves once for three points);
        ``info`` must stay JSON-serializable — it lands in the flight
        ring verbatim."""
        rate = (cfg if cfg is not None else self.cfg).rate(point)
        if rate <= 0.0:
            return False
        self.evaluated[point] += 1
        if self._rng(point).random() >= rate:
            return False
        self.counts[point] += 1
        if self._trace is not None and self._trace.enabled:
            self._trace.flight.record("chaos", point=point,
                                      injector=self.name, **info)
        _log.debug(json.dumps({"event": "chaos_injected", "point": point,
                               "injector": self.name}))
        return True

    def sleep(self, seconds):
        """The blessed sleeper for serving loop paths (see
        graftlint ``serving-raw-sleep``)."""
        if seconds > 0:
            self._sleep(seconds)
        else:
            self._sleep(0)

    def backoff(self):
        """A fresh deterministic Backoff from the config's retry knobs
        (one per retried operation, so jitter streams don't couple)."""
        cfg = self.cfg
        return Backoff(base_s=cfg.retry_base_s, max_s=cfg.retry_max_s,
                       retries=cfg.retry_max,
                       seed=self._seed ^ 0x5EED)


class Backoff:
    """Bounded exponential backoff with deterministic jitter.

    ``delay(attempt)`` for attempt 0, 1, 2… is
    ``min(base * 2**attempt, max) * (1 + jitter)`` with jitter drawn
    uniformly from ``[-jitter_frac, +jitter_frac]`` by a seeded RNG —
    the schedule is a pure function of the seed, pinned by unit test.
    ``retries`` bounds the attempt count (``delays()`` lists the whole
    schedule)."""

    def __init__(self, *, base_s=0.05, factor=2.0, max_s=2.0,
                 jitter_frac=0.1, retries=3, seed=0):
        self.base_s = float(base_s)
        self.factor = float(factor)
        self.max_s = float(max_s)
        self.jitter_frac = float(jitter_frac)
        self.retries = int(retries)
        self._rng = np.random.default_rng(int(seed))

    def delay(self, attempt):
        d = min(self.base_s * self.factor ** int(attempt), self.max_s)
        if self.jitter_frac > 0:
            d *= 1.0 + float(self._rng.uniform(-self.jitter_frac,
                                               self.jitter_frac))
        return max(0.0, d)

    def delays(self):
        return [self.delay(i) for i in range(self.retries)]


class CircuitBreaker:
    """Per-replica failure breaker: closed → open after ``threshold``
    consecutive failures, half-open after ``cooldown_s``, closed again
    after a success (a half-open failure re-opens and restarts the
    cooldown).  ``clock=`` injects the time source so the
    open→half-open→close transitions are pinned deterministically.
    ``threshold=0`` disables the breaker (always closed)."""

    def __init__(self, threshold=3, cooldown_s=5.0, clock=None):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock if clock is not None else time.monotonic
        self.failures = 0
        self.opens = 0
        self._opened_at = None
        self._half_open = False

    @property
    def state(self):
        if self._opened_at is None:
            return "closed"
        if self._half_open or self.cooldown_elapsed():
            return "half_open"
        return "open"

    def cooldown_elapsed(self):
        return (self._opened_at is not None
                and self.clock() - self._opened_at >= self.cooldown_s)

    def allow(self):
        """May traffic be routed here?  Open blocks until the cooldown
        elapses, then half-open admits trial traffic."""
        if self._opened_at is None:
            return True
        if self.cooldown_elapsed():
            self._half_open = True
            return True
        return False

    def record_failure(self):
        """Count a failure; returns True on the closed→open (or
        half-open→open) transition."""
        if self.threshold <= 0:
            return False
        self.failures += 1
        if self._opened_at is not None:
            if self._half_open:
                # the half-open trial failed: re-open, fresh cooldown
                self._half_open = False
                self._opened_at = self.clock()
                self.opens += 1
                return True
            return False
        if self.failures >= self.threshold:
            self._opened_at = self.clock()
            self._half_open = False
            self.opens += 1
            return True
        return False

    def record_success(self):
        self.failures = 0
        self._opened_at = None
        self._half_open = False

    def force_open(self):
        """Restore an OPEN state directly (journal replay on router
        recovery): the cooldown restarts NOW — the recovered router has
        no memory of how long the original breaker had been open, so it
        re-earns the half-open trial instead of guessing."""
        if self.threshold <= 0:
            return
        self.failures = max(self.failures, self.threshold)
        self._opened_at = self.clock()
        self._half_open = False
        self.opens += 1


# ---------------------------------------------------------------------------
# Global recovery invariants (the chaos fuzz checks these after every
# convulsion; they are also importable by tests directly)


def verify_page_conservation(cache, what="cache"):
    """Free + (distinct mapped or cached) pages == allocatable; every
    refcount equals the number of sequences mapping the page; the free
    list never overlaps live/cached pages.  Raises AssertionError with
    a labelled message on any violation."""
    mapped = set()
    rc = _Tally()
    for sid in cache.live_seqs():
        mapped.update(cache._tables[sid])
        rc.update(cache._tables[sid])
    resident = mapped | set(cache._cached)
    assert cache.free_pages + len(resident) == cache.allocatable_pages, (
        f"{what}: page leak — free={cache.free_pages} "
        f"resident={len(resident)} allocatable={cache.allocatable_pages}")
    free = set(cache._free)
    assert not (free & resident), (
        f"{what}: free list overlaps resident pages "
        f"{sorted(free & resident)[:8]}")
    for p in range(1, cache.num_pages):
        assert cache.refcount(p) == rc.get(p, 0), (
            f"{what}: page {p} refcount {cache.refcount(p)} != "
            f"{rc.get(p, 0)} mapping sequences")
    tier = getattr(cache, "_tier", None)
    if tier is not None:
        verify_tier_conservation(tier, what=f"{what}.tier")


def verify_tier_conservation(tier, what="tier"):
    """Host/disk tier invariants (round 20): the RAM pool's byte
    accounting matches its entries and stays under budget, disk files
    exist on disk at exactly their recorded sizes, and no chain key is
    double-resident (RAM and disk at once — a restore would be
    ambiguous and the bytes double-counted).  Spilled pages are COPIES
    of device pages, so device-side conservation is untouched by the
    tier; this check covers the tier's own ledger.  Works off the
    pool's :meth:`snapshot` view so it never reaches into pool
    internals (graftlint ``kvtier-blessed-access``)."""
    snap = tier.pool.snapshot()
    ram_keys = {k for k, _ in snap["entries"]}
    ram_bytes = sum(n for _, n in snap["entries"])
    assert ram_bytes == snap["bytes_used"], (
        f"{what}: host pool bytes_used={snap['bytes_used']} but "
        f"entries sum to {ram_bytes}")
    assert snap["bytes_used"] <= snap["budget_bytes"], (
        f"{what}: host pool over budget — "
        f"{snap['bytes_used']} > {snap['budget_bytes']}")
    disk = snap["disk"]
    if disk is not None:
        disk_keys = {k for k, _, _ in disk["entries"]}
        assert not (ram_keys & disk_keys), (
            f"{what}: {len(ram_keys & disk_keys)} chain(s) resident in "
            "BOTH the RAM and disk tiers")
        disk_bytes = 0
        for _, path, nbytes in disk["entries"]:
            assert os.path.isfile(path), (
                f"{what}: disk tier entry file missing: {path}")
            actual = os.path.getsize(path)
            assert actual == nbytes, (
                f"{what}: disk entry {path} is {actual} byte(s), "
                f"ledger says {nbytes}")
            disk_bytes += nbytes
        assert disk_bytes == disk["bytes_used"], (
            f"{what}: disk pool bytes_used={disk['bytes_used']} but "
            f"entries sum to {disk_bytes}")
        assert disk["bytes_used"] <= disk["budget_bytes"], (
            f"{what}: disk pool over budget — "
            f"{disk['bytes_used']} > {disk['budget_bytes']}")


def verify_engine_quiescent(engine, what="engine",
                            require_drained=True):
    """An idle engine holds NOTHING: no live scheduler work, no held
    (prefilled) requests, no chaos alloc-pressure residue, and every
    page back on the free list (cached prefix pages are reclaimable
    capacity and count as available).  ``require_drained=False``
    relaxes the empty-queue check for a CRASHED replica — its failure
    path requeues live requests as waiting (recompute semantics, pages
    freed), which is correct state, not a leak."""
    if require_drained:
        assert engine.scheduler.all_done(), (
            f"{what}: scheduler not drained — "
            f"waiting={engine.scheduler.queue_depth()} "
            f"live={len(engine.scheduler.live_requests())}")
    assert not engine.scheduler.live_requests(), (
        f"{what}: {len(engine.scheduler.live_requests())} request(s) "
        "still live")
    assert not engine._held, (
        f"{what}: {len(engine._held)} held request(s) leaked pages "
        f"(ids {sorted(engine._held)[:8]})")
    verify_page_conservation(engine.cache, what=what)
    if engine._draft_cache is not None:
        verify_page_conservation(engine._draft_cache, f"{what}.draft")
    assert engine.cache.available_pages == engine.cache.allocatable_pages, (
        f"{what}: {engine.cache.allocatable_pages - engine.cache.available_pages}"
        " page(s) neither free nor reclaimable after drain")


def fleet_invariants(router):
    """Run the quiescence + conservation checks over every in-process
    replica of a drained fleet (down/retired replicas included — a
    crashed replica must still have released its pages) and the router
    bookkeeping: no leaked router streams.  Returns the number of
    engines checked."""
    checked = 0
    for i, rep in enumerate(router.replicas):
        engine = getattr(rep, "engine", None)
        if engine is None:  # HTTPReplica: remote state, not inspectable
            continue
        failed = getattr(rep, "state", "ok") == "failed"
        verify_engine_quiescent(engine, what=f"replica[{i}]",
                                require_drained=not failed)
        checked += 1
    assert not router._streams, (
        f"router leaked {len(router._streams)} open stream(s)")
    return checked
