"""Crash-survivable fleet control plane (round 19).

Everything below the router already survives faults — failover splices
streams, migrations bounce and retry, breakers shed flaky replicas —
but until this module the CONTROL PLANE was a lab stub: replicas were
factory callbacks in the router's own process, and the one router
object was a single point of failure whose affinity/breaker/ownership
state died with it.  This module is the production tier (reference
capability: Paddle Fleet elastic training's control plane, and the
replica-lifecycle/SLO operability the Gemma-on-TPU serving paper
frames as what separates a demo engine from a deployment):

- :class:`RouterJournal` — a small append-only JSONL journal with
  per-record CRC framing and bounded rotation.  The router appends its
  journaled state transitions (placements, ownership drops, breaker
  opens, stream begin/end, down/up) as it serves; replay skips torn
  records (the ``journal_torn_write`` chaos point tears them on
  purpose) instead of dying — the file is a recovery accelerant, never
  a dependency.
- :class:`ProcessReplicaBackend` — real provisioning for
  :class:`~paddle_tpu.serving.autoscale.FleetAutoscaler`: spawns
  actual replica *server processes* (``python -m
  paddle_tpu.serving.fleet_worker``) with ephemeral-port allocation, a
  readiness poll against ``/healthz`` under a bounded startup
  deadline, and liveness supervision that restarts a dead process with
  backoff under a per-replica restart budget.  Spawned processes are
  tracked and reaped on EVERY exit path (close, atexit, and the worker
  self-reaps when its parent dies) — no stale-pytest-style orphans.
  :class:`ThreadLauncher` swaps the subprocess for an in-process
  ``ServingServer`` so the chaos fuzz and unit tests exercise the
  identical supervision machinery without process spawn costs; the
  graftlint ``fleet-process-spawn`` rule keeps every OTHER replica
  spawn in the tree routed through this backend.
- :class:`RouterSupervisor` — primary + warm standby with takeover:
  the primary router journals as it serves; when it crashes
  (``kill_active`` or the ``router_crash`` chaos point), the dead
  router's client connections are torn down exactly as a dead
  process's would be (in-process streams erred, HTTP sockets closed —
  the remote's disconnect-cancel fires), and the FIRST client to
  notice promotes the standby: journal replay rebuilds
  affinity/ownership/breaker state, ONE ``/healthz`` sweep rebuilds
  liveness and load, orphaned requests are cancelled best-effort (held
  pages otherwise fall to the deadline-expiry sweep).  Promotion is
  idempotent under the supervisor lock — the ``standby_takeover_race``
  chaos point drives a concurrent promotion attempt through the guard.
  :class:`SupervisorStream` retries a crashed router's streams on the
  new active with a client-side splice, so accepted streams survive
  the death of the router itself token-exactly.

What is journaled vs swept (the recovery contract, docs/FLEET.md):
liveness, loads and reservations are LIVE state owned by the replicas
— one sweep rebuilds them; affinity/ownership order, breaker opens and
stream begin/end are ROUTER state — the journal rebuilds them.  A cold
router = constructor + ``adopt_journal`` + ``sweep_health`` +
``release_orphans`` (:meth:`ServingRouter.recover`), and converges to
a never-crashed router's routing decisions within that one sweep.

Env knobs (docs/ENV_KNOBS.md): ``PADDLE_TPU_SERVING_FLEET_STARTUP_S``,
``PADDLE_TPU_SERVING_FLEET_RESTARTS``,
``PADDLE_TPU_SERVING_FLEET_SUPERVISE_S``,
``PADDLE_TPU_SERVING_FLEET_JOURNAL_MB``.

Nothing here imports jax: the control plane is host bookkeeping (the
worker process imports jax in ITS interpreter).  Subprocess workers
force ``jax_platforms=cpu`` by default — SIGKILLing one can never
wedge a chip grant (CLAUDE.md chip hygiene); pass ``platform=None`` in
the spec to let a real deployment keep its accelerator.
"""
from __future__ import annotations

import atexit
import http.client
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
import weakref
import zlib

import numpy as np

from .chaos import ChaosConfig, ChaosInjector
from .frontend import Rejected, Unavailable
from .replica import HTTPReplica
from .router import ServingRouter

__all__ = ["ProcessReplica", "ProcessReplicaBackend", "ReplicaSpec",
           "RouterCrashed", "RouterJournal", "RouterSupervisor",
           "SubprocessLauncher", "SupervisorStream", "ThreadLauncher"]

_log = logging.getLogger("paddle_tpu.serving")

_ENV_STARTUP = "PADDLE_TPU_SERVING_FLEET_STARTUP_S"
_ENV_RESTARTS = "PADDLE_TPU_SERVING_FLEET_RESTARTS"
_ENV_SUPERVISE = "PADDLE_TPU_SERVING_FLEET_SUPERVISE_S"
_ENV_JOURNAL_MB = "PADDLE_TPU_SERVING_FLEET_JOURNAL_MB"


def _env_float(name, default):
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else float(default)
    except ValueError:
        return float(default)


class RouterCrashed(RuntimeError):
    """The router serving this stream died — retry against the
    standby (the supervisor does this transparently)."""


# ---------------------------------------------------------------------------
# The routing journal


class RouterJournal:
    """Append-only JSONL journal with per-record CRC framing.

    Line format: ``<crc32 hex8> <compact json>\\n`` — the CRC covers the
    JSON bytes, so a record torn mid-write (process death, full disk,
    the ``journal_torn_write`` chaos point) fails the check and replay
    SKIPS it (counted in ``torn_skipped``) instead of dying.  Appends
    are flushed per record: the file is current at the instant of a
    crash, which is the whole point.

    Rotation keeps the journal small: past ``max_bytes`` (default
    ``PADDLE_TPU_SERVING_FLEET_JOURNAL_MB``, 16 MB) the live file
    rotates to ``<path>.1`` (replacing the previous rotation) and
    replay reads ``.1`` then the live file — affinity state is
    recency-weighted, so dropping the oldest half of history degrades
    recovered cache-hit rates, never correctness."""

    def __init__(self, path, *, max_bytes=None, chaos=None):
        self.path = str(path)
        if max_bytes is None:
            max_bytes = int(_env_float(_ENV_JOURNAL_MB, 16.0)
                            * 1024 * 1024)
        self.max_bytes = int(max_bytes)
        if isinstance(chaos, ChaosInjector):
            self.chaos = chaos
        else:
            assert chaos is None or isinstance(chaos, ChaosConfig)
            self.chaos = ChaosInjector(chaos, name="journal")
        self._lock = threading.Lock()
        self._file = None
        try:
            self._bytes = os.path.getsize(self.path)
        except OSError:
            self._bytes = 0
        self.appended = 0       # records written (incl. torn ones)
        self.torn_writes = 0    # records the chaos point tore
        self.torn_skipped = 0   # bad records skipped by the last replay

    def append(self, rec):
        line = json.dumps(rec, separators=(",", ":"))
        data = line.encode()
        framed = f"{zlib.crc32(data):08x} {line}\n".encode()
        if self.chaos.fire("journal_torn_write"):
            # a torn write: the frame stops mid-JSON.  The newline is
            # kept so the NEXT record stays parseable — replay handles
            # an un-terminated final line (real crash tail) separately.
            framed = framed[: max(10, len(framed) // 2)] + b"\n"
            self.torn_writes += 1
        with self._lock:
            if self._bytes + len(framed) > self.max_bytes:
                self._rotate_locked()
            if self._file is None:
                self._file = open(self.path, "ab")
            self._file.write(framed)
            self._file.flush()
            self._bytes += len(framed)
            self.appended += 1

    def _rotate_locked(self):
        if self._file is not None:
            self._file.close()
            self._file = None
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass
        self._bytes = 0

    def replay(self):
        """Yield journaled records oldest-first (rotated file, then the
        live one), skipping torn/corrupt lines."""
        self.torn_skipped = 0
        for path in (self.path + ".1", self.path):
            try:
                f = open(path, "rb")
            except OSError:
                continue
            with f:
                for raw in f:
                    rec = self._parse(raw)
                    if rec is None:
                        self.torn_skipped += 1
                        continue
                    yield rec

    @staticmethod
    def _parse(raw):
        raw = raw.rstrip(b"\n")
        if not raw:
            return None
        crc, _, body = raw.partition(b" ")
        if len(crc) != 8 or not body:
            return None
        try:
            if int(crc, 16) != zlib.crc32(body):
                return None
            rec = json.loads(body)
        except ValueError:
            return None
        return rec if isinstance(rec, dict) else None

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def stats(self):
        return {"path": self.path, "appended": self.appended,
                "torn_writes": self.torn_writes,
                "torn_skipped": self.torn_skipped,
                "bytes": self._bytes}


# ---------------------------------------------------------------------------
# Replica server processes: spec, launchers, backend


class ReplicaSpec:
    """How one replica server process is built.  ``model`` /
    ``engine`` are kwargs for the worker's default tiny-Llama builder;
    ``builder`` (``"module:function"``, called with the spec dict,
    returning a ``ServingEngine``) overrides it for real models.
    ``platform`` defaults to ``"cpu"`` — the axon sitecustomize bakes
    the device platform at interpreter start, and a worker must never
    touch a dead tunnel; set ``platform=None`` only for a deployment
    that owns its accelerator."""

    def __init__(self, *, model=None, engine=None, role="mixed",
                 builder=None, max_queued=64, platform="cpu",
                 drain_s=10.0):
        self.model = dict(model or {})
        self.engine = dict(engine or {})
        self.role = role
        self.builder = builder
        self.max_queued = int(max_queued)
        self.platform = platform
        self.drain_s = float(drain_s)

    def to_dict(self):
        return {"model": self.model, "engine": self.engine,
                "role": self.role, "builder": self.builder,
                "max_queued": self.max_queued,
                "platform": self.platform, "drain_s": self.drain_s}

    @classmethod
    def from_dict(cls, d):
        return cls(**{k: d.get(k) for k in
                      ("model", "engine", "role", "builder",
                       "max_queued", "platform", "drain_s")
                      if d.get(k) is not None})


class WorkerHandle:
    """One spawned replica server: either a real subprocess (``proc``)
    or an in-process ServingServer (``server``/``engine``)."""

    def __init__(self, *, proc=None, server=None, engine=None,
                 ready_file=None, log_path=None, pid=None, port=None):
        self.proc = proc
        self.server = server
        self.engine = engine
        self.ready_file = ready_file
        self.log_path = log_path
        self.pid = pid
        self.port = port
        self._killed = False

    def alive(self):
        if self.proc is not None:
            return self.proc.poll() is None
        return self.server is not None and not self._killed


class SubprocessLauncher:
    """Spawns real replica server processes.  The ONE blessed home of
    ``subprocess.Popen`` for serving processes (graftlint
    ``fleet-process-spawn``): every spawn here is tracked, deadline-
    polled for readiness, and reaped on every exit path."""

    def __init__(self, *, python=None, log_dir=None, extra_env=None):
        self.python = python or sys.executable
        self.log_dir = log_dir or tempfile.mkdtemp(
            prefix="pdtpu_fleet_")
        self.extra_env = dict(extra_env or {})
        self._seq = 0

    def spawn(self, spec, name):
        self._seq += 1
        base = os.path.join(self.log_dir, f"{name}_{self._seq}")
        spec_path = base + ".spec.json"
        ready_path = base + ".ready.json"
        log_path = base + ".log"
        with open(spec_path, "w") as f:
            json.dump(spec.to_dict(), f)
        cmd = [self.python, "-m", "paddle_tpu.serving.fleet_worker",
               "--spec", spec_path, "--ready-file", ready_path,
               "--parent-pid", str(os.getpid())]
        env = dict(os.environ, **self.extra_env)
        log = open(log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, stdout=log,
                                    stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()
        return WorkerHandle(proc=proc, ready_file=ready_path,
                            log_path=log_path, pid=proc.pid)

    def poll_ready(self, handle):
        """One non-blocking readiness check: the worker writes its
        bound port to the ready file atomically once serving."""
        if handle.port is not None:
            return handle.port
        try:
            with open(handle.ready_file) as f:
                info = json.load(f)
        except (OSError, ValueError):
            return None
        handle.port = int(info["port"])
        handle.pid = int(info.get("pid", handle.pid or 0)) or handle.pid
        return handle.port

    def kill(self, handle):
        """SIGKILL — the kill -9 drill.  Workers are CPU-forced by
        default, so this can never wedge a chip grant."""
        handle._killed = True
        if handle.proc is not None and handle.proc.poll() is None:
            handle.proc.kill()
            handle.proc.wait(timeout=10)

    def terminate(self, handle, grace=10.0):
        """SIGTERM with grace (the worker drains), then SIGKILL."""
        handle._killed = True
        proc = handle.proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.communicate(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


class ThreadLauncher:
    """In-process stand-in for :class:`SubprocessLauncher` — the chaos
    fuzz and unit tests drive the IDENTICAL supervision machinery
    (spawn / readiness / kill / restart budget) without paying a
    process spawn per replica.  ``kill`` is the closest in-process
    analog of SIGKILL the invariants allow: the front-end fails hard
    (pages released — a real SIGKILL releases them by erasing the
    process) and the listener stops, so clients see reset connections
    and an unreachable ``/healthz``."""

    def __init__(self, engine_factory=None):
        # engine_factory(spec) -> ServingEngine; defaults to the
        # worker's own spec builder (single source of truth)
        self.engine_factory = engine_factory
        self._seq = 0

    def _build_engine(self, spec):
        if self.engine_factory is not None:
            return self.engine_factory(spec)
        from .fleet_worker import build_engine_from_spec
        return build_engine_from_spec(spec.to_dict())

    def spawn(self, spec, name):
        from .server import ServingServer
        self._seq += 1
        engine = self._build_engine(spec)
        srv = ServingServer(engine, port=0, role=spec.role,
                            max_queued=spec.max_queued)
        _, port = srv.start()
        return WorkerHandle(server=srv, engine=engine, port=port,
                            pid=-self._seq)  # synthetic, never a real pid

    def poll_ready(self, handle):
        return handle.port

    def kill(self, handle):
        handle._killed = True
        handle.server.abort(RouterCrashed("fleet: process killed"))

    def terminate(self, handle, grace=10.0):
        handle._killed = True
        handle.server.close(timeout=grace)


class _BackendEntry:
    __slots__ = ("replica", "spec", "handle", "name", "restarts",
                 "stopped", "failed")

    def __init__(self, replica, spec, handle, name):
        self.replica = replica
        self.spec = spec
        self.handle = handle
        self.name = name
        self.restarts = 0
        self.stopped = False
        self.failed = False


class ProcessReplica(HTTPReplica):
    """An :class:`HTTPReplica` bound to a supervised server process.
    ``close()`` routes through the backend (terminate + reap); a
    supervised restart re-points ``port`` at the new process — the
    router's health prober then readmits the slot on its own."""

    kind = "proc"

    def __init__(self, backend, host, port, **kw):
        super().__init__(host, port, **kw)
        self._backend = weakref.ref(backend)
        self.failed_permanently = False

    @property
    def pid(self):
        entry = self.backend_entry
        return entry.handle.pid if entry is not None else None

    @property
    def restarts(self):
        entry = self.backend_entry
        return entry.restarts if entry is not None else 0

    @property
    def backend_entry(self):
        backend = self._backend()
        if backend is None:
            return None
        return backend._entry_for(self)

    def close(self, timeout=10.0):
        backend = self._backend()
        if backend is not None:
            backend.stop_replica(self, grace=timeout)
        return True


# every live backend gets reaped at interpreter exit — belt-and-braces
# on top of close(); the worker's parent-pid watchdog is the third net
_LIVE_BACKENDS: "weakref.WeakSet" = weakref.WeakSet()


def _reap_all_backends():  # pragma: no cover - exit-path safety net
    for backend in list(_LIVE_BACKENDS):
        try:
            backend.close(grace=2.0)
        except Exception:
            pass


atexit.register(_reap_all_backends)


class ProcessReplicaBackend:
    """Real provisioning for the autoscaler: ``provision(role)``
    spawns a replica server process, waits for ``/healthz`` readiness
    under the startup deadline, and returns a routable
    :class:`ProcessReplica`.  A supervision thread restarts dead
    processes with backoff under the per-replica restart budget
    (``PADDLE_TPU_SERVING_FLEET_RESTARTS``); budget exhaustion marks
    the replica permanently failed — the router's breaker keeps
    traffic away, and drain-by-health rotation replaces it.

    ``spec_for_role`` is a :class:`ReplicaSpec`, a ``{role: spec}``
    dict, or a callable ``role -> spec``.  ``launcher`` defaults to
    :class:`SubprocessLauncher`; :class:`ThreadLauncher` runs the same
    machinery in-process for tests and the chaos fuzz (whose
    ``replica_proc_kill`` point fires in the supervision loop)."""

    def __init__(self, spec_for_role, *, launcher=None, startup_s=None,
                 restart_budget=None, supervise_interval_s=None,
                 chaos=None):
        self._spec_for_role = spec_for_role
        self.launcher = launcher or SubprocessLauncher()
        self.startup_s = (_env_float(_ENV_STARTUP, 45.0)
                          if startup_s is None else float(startup_s))
        self.restart_budget = (int(_env_float(_ENV_RESTARTS, 3))
                               if restart_budget is None
                               else int(restart_budget))
        self.supervise_interval_s = (
            _env_float(_ENV_SUPERVISE, 0.5)
            if supervise_interval_s is None
            else float(supervise_interval_s))
        if isinstance(chaos, ChaosInjector):
            self.chaos = chaos
        else:
            assert chaos is None or isinstance(chaos, ChaosConfig)
            self.chaos = ChaosInjector(chaos, name="fleet-backend")
        self._entries: list[_BackendEntry] = []
        self._lock = threading.Lock()
        # supervision passes are mutually exclusive: a manual
        # supervise_once() racing the daemon pass must not double-
        # restart one dead process (and leak the loser's spawn)
        self._sup_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._seq = 0
        self.spawns = 0
        self.restarts = 0
        self.kills = 0          # chaos replica_proc_kill firings
        self.perm_failures = 0  # restart budgets exhausted
        self._closed = False
        _LIVE_BACKENDS.add(self)

    # -- provisioning ------------------------------------------------------
    def _resolve_spec(self, role):
        s = self._spec_for_role
        if callable(s):
            s = s(role)
        elif isinstance(s, dict) and not isinstance(s, ReplicaSpec):
            s = s.get(role) or s.get("__default__")
        if not isinstance(s, ReplicaSpec):
            raise ValueError(f"no ReplicaSpec for role {role!r}")
        if s.role != role:
            s = ReplicaSpec(**dict(s.to_dict(), role=role))
        return s

    def provision(self, role="mixed"):
        """The autoscaler factory: spawn → ready → routable replica."""
        spec = self._resolve_spec(role)
        self._seq += 1
        name = f"replica_{role}_{self._seq}"
        handle = self._spawn_ready(spec, name)
        rep = ProcessReplica(self, "127.0.0.1", handle.port, role=role)
        with self._lock:
            self._entries.append(_BackendEntry(rep, spec, handle, name))
        self.start_supervision()
        _log.info(json.dumps({"event": "fleet_provisioned",
                              "name": name, "role": role,
                              "pid": handle.pid, "port": handle.port}))
        return rep

    def _spawn_ready(self, spec, name):
        """Spawn + bounded readiness: the ready file yields the port,
        then ``/healthz`` must answer ``ok`` — all under the startup
        deadline.  Failure reaps the half-started process."""
        handle = self.launcher.spawn(spec, name)
        self.spawns += 1
        deadline = time.monotonic() + self.startup_s
        port = None
        try:
            while time.monotonic() < deadline:
                if not handle.alive():
                    raise RuntimeError(
                        f"fleet replica {name} died during startup "
                        f"(log: {handle.log_path})")
                port = self.launcher.poll_ready(handle)
                if port is not None and self._healthz_ok(port):
                    return handle
                self.chaos.sleep(0.05)
            raise RuntimeError(
                f"fleet replica {name} not ready within "
                f"{self.startup_s}s (port={port}, "
                f"log: {handle.log_path})")
        except Exception:
            self.launcher.terminate(handle, grace=2.0)
            raise

    @staticmethod
    def _healthz_ok(port, timeout=2.0):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=timeout)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                data = resp.read()
            finally:
                conn.close()
            return (resp.status == 200
                    and json.loads(data).get("status") == "ok")
        except (OSError, ValueError):
            return False

    # -- supervision -------------------------------------------------------
    def start_supervision(self):
        if self._thread is None and not self._closed:
            self._thread = threading.Thread(
                target=self._supervise_loop, name="fleet-supervisor",
                daemon=True)
            self._thread.start()
        return self

    def _supervise_loop(self):
        # wait-first: a freshly provisioned replica is known healthy,
        # and tests driving manual supervise_once() passes must not
        # race an immediate daemon pass
        while not self._stop.wait(self.supervise_interval_s):
            try:
                self.supervise_once()
            except Exception:  # pragma: no cover - loop must not die
                _log.exception("fleet supervision pass failed")

    def supervise_once(self):
        """One supervision pass (tests call this synchronously): fire
        the ``replica_proc_kill`` chaos point, then restart any dead
        process with backoff under the restart budget."""
        with self._sup_lock:
            self._supervise_pass()

    def _supervise_pass(self):
        with self._lock:
            entries = list(self._entries)
        for entry in entries:
            if entry.stopped or entry.failed:
                continue
            if entry.handle.alive() \
                    and self.chaos.fire("replica_proc_kill",
                                        replica=entry.name):
                self.kills += 1
                self.launcher.kill(entry.handle)
                _log.warning(json.dumps({
                    "event": "fleet_chaos_proc_kill",
                    "name": entry.name, "pid": entry.handle.pid}))
            if entry.handle.alive():
                continue
            self._restart(entry)

    def _restart(self, entry):
        if entry.restarts >= self.restart_budget:
            entry.failed = True
            entry.replica.failed_permanently = True
            self.perm_failures += 1
            _log.error(json.dumps({
                "event": "fleet_replica_failed_permanently",
                "name": entry.name, "restarts": entry.restarts}))
            return
        delay = self.chaos.backoff().delay(entry.restarts)
        self.chaos.sleep(delay)
        entry.restarts += 1
        try:
            handle = self._spawn_ready(
                entry.spec, f"{entry.name}_r{entry.restarts}")
        except Exception as e:
            # counted against the budget; next pass retries or fails
            _log.warning(json.dumps({
                "event": "fleet_restart_failed", "name": entry.name,
                "attempt": entry.restarts, "cause": repr(e)}))
            return
        entry.handle = handle
        entry.replica.port = handle.port
        self.restarts += 1
        _log.info(json.dumps({
            "event": "fleet_replica_restarted", "name": entry.name,
            "attempt": entry.restarts, "pid": handle.pid,
            "port": handle.port}))

    # -- drills / teardown -------------------------------------------------
    def _entry_for(self, replica):
        with self._lock:
            for entry in self._entries:
                if entry.replica is replica:
                    return entry
        return None

    def kill_replica_process(self, replica):
        """The harness's kill -9 drill: SIGKILL the replica's server
        process NOW (supervision will restart it within budget)."""
        entry = self._entry_for(replica)
        if entry is None or not entry.handle.alive():
            return False
        self.launcher.kill(entry.handle)
        _log.warning(json.dumps({"event": "fleet_proc_kill_drill",
                                 "name": entry.name,
                                 "pid": entry.handle.pid}))
        return True

    def stop_replica(self, replica, grace=10.0):
        entry = self._entry_for(replica)
        if entry is None or entry.stopped:
            return False
        entry.stopped = True
        self.launcher.terminate(entry.handle, grace=grace)
        return True

    def live_pids(self):
        """Pids of processes still alive — the harness's zero-orphan
        gate asserts this is empty after close()."""
        with self._lock:
            return [e.handle.pid for e in self._entries
                    if e.handle.alive()]

    def close(self, grace=10.0):
        """Reap EVERYTHING: stop supervision, terminate every process
        (SIGTERM with grace, then SIGKILL), verify nothing survived."""
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, grace))
            self._thread = None
        with self._lock:
            entries = list(self._entries)
        for entry in entries:
            entry.stopped = True
            try:
                self.launcher.terminate(entry.handle, grace=grace)
            except Exception:  # pragma: no cover - reap best-effort
                pass
        leftovers = self.live_pids()
        if leftovers:  # pragma: no cover - the reap above is bounded
            _log.error(json.dumps({"event": "fleet_orphan_processes",
                                   "pids": leftovers}))
        return not leftovers

    def stats(self):
        with self._lock:
            return {"replicas": len(self._entries),
                    "spawns": self.spawns, "restarts": self.restarts,
                    "chaos_kills": self.kills,
                    "perm_failures": self.perm_failures,
                    "live": len([e for e in self._entries
                                 if e.handle.alive()])}


# ---------------------------------------------------------------------------
# Router supervisor: primary + warm standby with takeover


class SupervisorStream:
    """One client stream that survives ROUTER death: consumes the
    active router's :class:`RouterStream` and, when that router
    crashes mid-stream, resubmits on the promoted standby with a
    client-side splice (skip the tokens already delivered) — the
    determinism contract (token t pure in weights/history/seed/t)
    makes the retried stream byte-identical."""

    def __init__(self, sup, req_id, prompt, kwargs, n):
        self.sup = sup
        self.req_id = req_id
        self.request_id = kwargs.get("request_id")
        self.prompt = prompt
        self.kwargs = kwargs
        self.n = int(n)
        self._delivered = [0] * self.n
        self._finished = [False] * self.n
        self._router = None
        self._rs = None
        self.takeovers_seen = 0

    @property
    def done(self):
        return all(self._finished)

    def _attach(self, router):
        """(Re)submit on ``router``, arming the cross-router splice."""
        rs = router.submit(self.prompt, **self.kwargs)
        rs._skip = [d if not f else 0
                    for d, f in zip(self._delivered, self._finished)]
        self._router, self._rs = router, rs
        return rs

    def events(self, timeout=120.0, idle_s=None):
        sup = self.sup
        deadline = time.monotonic() + timeout
        while not self.done:
            router = sup._ensure_active()
            if self._router is not router:
                try:
                    self._attach(router)
                    self.takeovers_seen = sup.takeovers
                except Unavailable:
                    if sup.active is not router or router._crashed:
                        continue  # crashed between ensure and submit
                    raise
                except Rejected:
                    raise
            try:
                for ev in self._rs.events(timeout=timeout,
                                          idle_s=idle_s):
                    if self._router._crashed:
                        # the router died under us: events pulled past
                        # this point may be orphan-release artifacts
                        # (a `cancelled` finish for a request the NEW
                        # router's recovery reaped) — never treat them
                        # as completion; resubmit with the splice.
                        # `_crashed` is set before the takeover that
                        # runs orphan release, so the check is ordered
                        # ahead of any such artifact.
                        raise RouterCrashed("router crashed mid-stream")
                    if ev["type"] == "idle":
                        yield ev
                        continue
                    idx = ev.get("index", 0)
                    if self._finished[idx]:
                        continue  # replayed sample on a resubmission
                    if ev["type"] == "token":
                        self._delivered[idx] += 1
                        if sup.chaos.fire("router_crash"):
                            sup.kill_active(cause="chaos:router_crash")
                        yield ev
                    elif ev["type"] == "finish":
                        self._finished[idx] = True
                        yield ev
                if not self.done and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"supervisor stream {self.req_id} incomplete "
                        f"after {timeout}s")
            except TimeoutError:
                raise
            except RuntimeError:
                # the router serving us died (RouterCrashed via the
                # inner stream, or its failover path found the router
                # halted) -> retry on the promoted standby.  A router
                # that is alive and still active re-raises: that is a
                # terminal stream failure, not a takeover.
                if self._router is not None and (
                        self._router._crashed
                        or sup.active is not self._router):
                    self._router = None
                    continue
                raise
        sup._stream_done(self)

    def result(self, timeout=120.0):
        out = [{"tokens": [], "finish_reason": None}
               for _ in range(self.n)]
        for ev in self.events(timeout=timeout):
            if ev["type"] == "token":
                out[ev["index"]]["tokens"].append(ev["token"])
            elif ev["type"] == "finish":
                out[ev["index"]]["finish_reason"] = ev["reason"]
        return out


class RouterSupervisor:
    """Primary + warm standby for the routing tier itself.

    The PRIMARY router serves and journals; the WARM STANDBY is a
    constructed (unstarted, state-cold) router over the same fleet.
    On primary death (:meth:`kill_active`, or the ``router_crash``
    chaos point firing inside a stream), the dead router's client
    connections are torn down the way a dead process's would be, and
    the first caller to need a router promotes the standby:
    ``adopt_journal`` (affinity/ownership/breakers/orphans) +
    ``sweep_health`` (liveness/loads) + ``release_orphans``.
    Promotion is idempotent under the supervisor lock; the
    ``standby_takeover_race`` point drives a concurrent attempt
    through the guard.  Presents the front-end surface
    (``submit``/``cancel``/``health``/``prometheus``/``drain``), so a
    ``ServingServer`` can front a supervised fleet unchanged."""

    def __init__(self, replicas, *, journal_path, router_cls=None,
                 chaos=None, seed=None, **router_kw):
        self.router_cls = router_cls or ServingRouter
        self.router_kw = dict(router_kw)
        if isinstance(chaos, ChaosInjector):
            self.chaos = chaos
        else:
            assert chaos is None or isinstance(chaos, ChaosConfig)
            self.chaos = ChaosInjector(chaos, name="supervisor")
        self.journal = RouterJournal(
            journal_path,
            chaos=ChaosInjector(self.chaos._config, name="journal"))
        self.active = self.router_cls(replicas, journal=self.journal,
                                      **self.router_kw)
        self._standby = self._make_standby()
        self._lock = threading.Lock()
        self._ids = iter(range(1 << 60))
        self._streams: dict[int, SupervisorStream] = {}
        self._seed_rng = np.random.default_rng(seed)
        self.epoch = 0
        self.takeovers = 0
        self.takeover_s = None      # duration of the last promotion
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if not self._started:
            self.active.start()
            self._started = True
        return self

    def drain(self, timeout=120.0):
        return self.active.drain(timeout)

    def close(self, timeout=120.0):
        ok = self.active.close(timeout)
        self.journal.close()
        return ok

    # -- the crash drill ---------------------------------------------------
    def kill_active(self, cause="kill_active"):
        """Crash the active router: halt it (prober stopped, submits
        refused), bump the epoch, and tear down its client connections
        — in-process inner streams get an error event (their consumers
        wake with ``RouterCrashed``), HTTP inner sockets close (the
        remote's disconnect-cancel frees the pages), and in-process
        replica-side requests are cancelled (the disconnect-cancel
        analog).  Held pages a teardown cannot reach fall to the
        deadline-expiry sweep.  Promotion itself is LAZY — the next
        caller that needs a router performs it — which is exactly the
        cold-standby shape: the standby does nothing until traffic
        arrives."""
        with self._lock:
            dead = self.active
            if dead is None or dead._crashed:
                return False
            dead.halt()
            self.epoch += 1
        _log.warning(json.dumps({"event": "router_crashed",
                                 "epoch": self.epoch, "cause": cause}))
        for stream in list(dead._streams.values()):
            inner = stream._inner
            if inner is None:
                continue
            try:
                if hasattr(inner, "_fail"):
                    inner._fail(RouterCrashed(
                        f"router crashed ({cause})"))
                else:
                    inner.close()
            except Exception:
                pass
            idx = stream.replica_idx
            try:
                if idx is not None and hasattr(dead.replicas[idx],
                                               "frontend"):
                    dead.replicas[idx].cancel_stream(inner)
            except Exception:
                pass
        return True

    def _ensure_active(self):
        """The takeover: promote the warm standby if the active router
        crashed.  Idempotent — concurrent callers serialize on the
        lock and late ones see the promotion already done (the
        ``standby_takeover_race`` chaos point drives a second attempt
        through that guard for real)."""
        race = False
        with self._lock:
            act = self.active
            if not act._crashed:
                return act
            t0 = time.perf_counter()
            standby = self._standby
            if standby is None \
                    or len(standby.replicas) != len(act.replicas):
                # the fleet grew/shrank under the old primary: the
                # pre-built standby is stale — rebuild from the dead
                # router's (authoritative) replica list
                standby = self._make_standby(act)
            race = self.chaos.fire("standby_takeover_race")
            standby.adopt_journal(self.journal)
            standby.sweep_health()
            standby.start()
            orphans = standby.release_orphans()
            self.active = standby
            self._standby = None
            self.takeovers += 1
            self.takeover_s = time.perf_counter() - t0
            _log.warning(json.dumps({
                "event": "router_takeover", "epoch": self.epoch,
                "takeover_s": round(self.takeover_s, 4),
                "orphans": orphans,
                "journal": self.journal.stats()}))
        if race:
            # a concurrent promotion attempt MUST no-op: it serializes
            # on the lock and finds the new active healthy
            t = threading.Thread(target=self._ensure_active)
            t.start()
            t.join()
        with self._lock:
            if self._standby is None:
                self._standby = self._make_standby()
        return self.active

    def _make_standby(self, source=None):
        src = source or self.active
        kw = dict(self.router_kw)
        # the standby shares the fleet (replica objects) but none of
        # the routing state: that arrives via journal replay + sweep
        return self.router_cls(list(src.replicas), **kw)

    # -- front-end surface -------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, **kw):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if kw.get("do_sample") and kw.get("seed") is None:
            # the seed must OUTLIVE any one router: a takeover
            # resubmission is token-exact only if it rides along
            kw["seed"] = int(self._seed_rng.integers(1, 2 ** 31 - 1))
        kw["max_new_tokens"] = int(max_new_tokens)
        stream = SupervisorStream(self, next(self._ids), prompt, kw,
                                  n=int(kw.get("n", 1)))
        with self._lock:
            self._streams[stream.req_id] = stream
        return stream

    def cancel(self, req_id):
        with self._lock:
            stream = self._streams.pop(req_id, None)
        if stream is None or stream._rs is None or stream._router is None:
            return False
        return bool(stream._router.cancel(stream._rs.req_id))

    def _stream_done(self, stream):
        with self._lock:
            self._streams.pop(stream.req_id, None)

    def health(self):
        h = self.active.health()
        h.update(epoch=self.epoch, takeovers=self.takeovers,
                 takeover_s=self.takeover_s,
                 journal=self.journal.stats())
        return h

    def prometheus(self):
        text = self.active.prometheus()
        pre = "paddle_tpu_serving_supervisor"
        lines = [f"# TYPE {pre}_takeovers_total counter",
                 f"{pre}_takeovers_total {self.takeovers}",
                 f"# TYPE {pre}_epoch gauge",
                 f"{pre}_epoch {self.epoch}",
                 f"# TYPE {pre}_journal_torn_skipped_total counter",
                 f"{pre}_journal_torn_skipped_total "
                 f"{self.journal.torn_skipped}"]
        return text + "\n".join(lines) + "\n"

    def debug_trace(self, request_id=None, req_id=None):
        return self.active.debug_trace(request_id=request_id,
                                       req_id=req_id)

    def debug_flight(self):
        return self.active.debug_flight()

    @property
    def state(self):
        return self.active.state
