"""Real static-graph mode: Program recording + Executor replay.

Reference parity: paddle.static Program/program_guard/data/Executor.run
(upstream python/paddle/static/, paddle/fluid/framework ProgramDesc +
executor — unverified; SURVEY.md §2.1 "Legacy framework", §2.2 "Static
API").

TPU-native design: the reference's ProgramDesc is an op-list IR executed
op-by-op; here the IR is the framework's own op stream. Every
differentiable op already flows through `core.autograd.apply` — under
`program_guard` that chokepoint appends (fn, input-keys, output-keys) to
the active Program while ops still execute eagerly on placeholder zeros
(shape inference for free, any Python control flow already resolved,
exactly like tracing). `Executor.run(program, feed, fetch_list)` replays
the recorded op list as a PURE function of the feeds — parameters and
recorded constants enter as leaf inputs, read at run time so a trained
weight updates the program's behavior — and compiles the whole replay
with `jax.jit` (cached per feed signature). That makes Executor.run one
XLA computation per signature: the reference's
ProgramDesc→executor→kernel-loop pipeline collapsed into trace + XLA.

Static TRAINING (reference: paddle.static append_backward + optimizer
op rewriting, upstream python/paddle/base/backward.py — unverified):
`append_backward(loss)` appends ONE gradient record that replays the
forward sub-program under `jax.grad` w.r.t. the parameter leaves (XLA
CSEs the recomputed forward against the fetched one inside the same
jitted replay), and `optimizer.minimize(loss)` inside `program_guard`
appends the optimizer's own fused update rule as a record whose outputs
are WRITTEN BACK to the parameter / optimizer-state leaves after every
`Executor.run` — the reference's in-scope variable mutation, expressed
as a pure program + host-side assign list.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd as _ag
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = ["Program", "program_guard", "data", "Executor", "global_scope",
           "scope_guard", "append_backward"]


class _Record:
    __slots__ = ("fn", "in_keys", "out_keys", "name", "kind")

    def __init__(self, fn, in_keys, out_keys, name, kind="op"):
        self.fn = fn
        self.in_keys = in_keys
        self.out_keys = out_keys
        self.name = name
        self.kind = kind  # "op" | "backward" | "opt"


class Program:
    """A recorded op DAG (the TPU-native ProgramDesc)."""

    def __init__(self):
        self._records: list[_Record] = []
        self._feeds: dict[str, int] = {}       # data name -> key
        self._leaves: dict[int, object] = {}   # key -> Tensor
        self._produced: set[int] = set()
        self._jit_cache: dict = {}
        # static-training writebacks: (src value key, setter). After every
        # run the fetched src value is handed to the setter — a Tensor
        # (in-place update) or a callable — mutating the parameter /
        # optimizer-state leaves exactly like the reference executor
        # mutates scope variables.
        self._assigns: list[tuple[int, object]] = []
        # callables invoked before each run (e.g. refresh the lr leaf
        # from an LRScheduler)
        self._prerun_hooks: list = []
        # Strong refs to EVERY tensor whose id() appears in the record —
        # id() keys are only unique while the object lives; without the
        # pin, a freed intermediate's id could be reused by a later
        # tensor and silently corrupt the DAG.
        self._pins: list = []

    # -- recording (called from autograd.apply) -----------------------------
    def record(self, fn, in_tensors, out_tensors, name="", kind="op"):
        in_keys = []
        for t in in_tensors:
            k = id(t)
            if k not in self._produced and k not in self._leaves:
                # leaf: a parameter (replayed from its live value) or a
                # constant created outside/inside the guard
                self._leaves[k] = t
            in_keys.append(k)
        out_keys = [id(t) for t in out_tensors]
        self._produced.update(out_keys)
        self._pins.extend(in_tensors)
        self._pins.extend(out_tensors)
        self._records.append(_Record(fn, tuple(in_keys), tuple(out_keys),
                                     name, kind))

    def _register_feed(self, name, tensor):
        self._feeds[name] = id(tensor)
        self._produced.add(id(tensor))  # fed, not a leaf constant
        self._pins.append(tensor)

    # -- reference API surface ----------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        """for_test=True: the reference strips backward + optimizer ops
        so Executor.run on the clone evaluates without training. Here
        that is a view Program sharing this one's forward records and
        leaves (live parameters included — a trained weight evaluates
        with its current value) but carrying no training records,
        writebacks, or pre-run hooks."""
        if not for_test:
            return self
        p = Program()
        p._records = [r for r in self._records if r.kind == "op"]
        # shallow copies: the clone sees the same LIVE Tensor objects
        # (a trained weight evaluates with its current value) but
        # recording into the clone must not mutate this Program's maps
        p._feeds = dict(self._feeds)
        p._leaves = dict(self._leaves)
        p._produced = set(self._produced)
        p._pins = list(self._pins)
        return p

    def all_parameters(self):
        from ..core.tensor import Parameter
        return [t for t in self._leaves.values()
                if isinstance(t, Parameter)]

    @property
    def num_ops(self):
        return len(self._records)

    # -- replay --------------------------------------------------------------
    def run(self, feed, fetch_list):
        for hook in self._prerun_hooks:
            hook()
        feed = feed or {}
        fetch_keys = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                fetch_keys.append(id(f))
            elif isinstance(f, str) and f in self._feeds:
                fetch_keys.append(self._feeds[f])
            else:
                raise TypeError(f"fetch_list entries must be Tensors "
                                f"(got {f!r})")
        # dead-record elimination: replay only ops whose outputs reach a
        # fetch or writeback (the reference prunes the same way for
        # test-clone programs — an eval fetch must not demand the label
        # feed that only the loss op consumes)
        need = set(fetch_keys)
        need.update(k for k, _ in self._assigns)
        active = []
        for rec in reversed(self._records):
            if any(k in need for k in rec.out_keys):
                active.append(rec)
                need.update(rec.in_keys)
        active.reverse()
        names = sorted(n for n in self._feeds
                       if self._feeds[n] in need)
        missing = [n for n in names if n not in feed]
        if missing:
            raise ValueError(f"missing feeds: {missing}")
        feed_arrays = [jnp.asarray(feed[n]._data if isinstance(feed[n],
                                                               Tensor)
                                   else feed[n]) for n in names]
        # key order must match _replay's zip over the ordered feed names
        ordered_keys = [self._feeds[n] for n in names]
        leaf_arrays = [t._data for t in self._leaves.values()]

        # num_ops/num_assigns are in the key: the jitted replay closes
        # over the record list at trace time, so a Program extended after
        # compilation must not replay the stale op list for already-seen
        # feed signatures.
        sig = (tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               tuple(fetch_keys), len(self._records), len(self._assigns))
        fn = self._jit_cache.get(sig)
        if fn is None:
            assign_keys = [k for k, _ in self._assigns]

            def pure(feed_arrays, leaf_arrays):
                env = dict(zip(ordered_keys, feed_arrays))
                env.update(zip(self._leaves.keys(), leaf_arrays))  # graftlint: disable=jit-constant-capture (keys only — the leaf ARRAYS arrive as the leaf_arrays jit argument)
                for rec in active:
                    try:
                        args = [env[k] for k in rec.in_keys]
                    except KeyError as e:
                        raise RuntimeError(
                            f"static Program replay: op "
                            f"{rec.name or rec.fn} consumes a value not "
                            f"reachable from feeds/leaves ({e}); was it "
                            f"created under a different Program?")
                    out = rec.fn(*args)
                    outs = out if isinstance(out, (tuple, list)) else (out,)
                    env.update(zip(rec.out_keys, outs))
                return ([env[k] for k in fetch_keys],
                        [env[k] for k in assign_keys])

            fn = jax.jit(pure)
            self._jit_cache[sig] = fn
        # replaying a record must never re-record (an op replayed while a
        # guard is active would append itself to the active Program)
        prev = _ag._set_static_recorder(None)
        try:
            outs, assign_vals = fn(feed_arrays, leaf_arrays)
        finally:
            _ag._set_static_recorder(prev)
        for (_, target), val in zip(self._assigns, assign_vals):
            if callable(target):
                target(val)
            else:
                target._inplace_update(val)
        return [np.asarray(o) for o in outs]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    program=None):
    """Append the gradient computation for `loss` to the Program
    (reference: paddle.static.append_backward, upstream
    python/paddle/base/backward.py — unverified; SURVEY.md §2.2).

    TPU-native design: instead of emitting one grad op per forward op,
    ONE record is appended whose fn replays the forward sub-program (the
    records present when append_backward was called) under `jax.grad`
    w.r.t. the parameter leaves. Inside the jitted replay XLA CSEs this
    recomputed forward against the fetched one, so the cost matches an
    op-by-op backward. Returns [(param, grad_tensor)] — grad tensors are
    ordinary program values (fetchable, consumable by later records).
    """
    prog = program if program is not None else default_main_program()
    if parameter_list is None:
        params = prog.all_parameters()
    else:
        params = [p for p in parameter_list]
    skip_ids = {id(s) for s in (no_grad_set or ())}
    params = [p for p in params
              if not p.stop_gradient and id(p) not in skip_ids]
    if not params:
        raise ValueError("append_backward: no trainable parameters reach "
                         "the loss (all stop_gradient or filtered)")
    loss_key = id(loss)
    if loss_key not in prog._produced:
        raise ValueError(
            "append_backward: loss was not produced by this Program "
            "(build it under program_guard on the same Program)")
    fwd_records = list(prog._records)
    param_keys = [id(p) for p in params]
    param_dtypes = [p._data.dtype for p in params]
    for p in params:
        if id(p) not in prog._leaves and id(p) not in prog._produced:
            prog._leaves[id(p)] = p
            prog._pins.append(p)
    feed_keys = tuple(prog._feeds[n] for n in sorted(prog._feeds))
    leaf_keys = tuple(prog._leaves.keys())
    in_keys = feed_keys + leaf_keys

    def _grads_fn(*args):
        env = dict(zip(in_keys, args))

        def loss_of(pvals):
            e = dict(env)
            e.update(zip(param_keys, pvals))
            for rec in fwd_records:
                a = [e[k] for k in rec.in_keys]
                out = rec.fn(*a)
                outs = out if isinstance(out, (tuple, list)) else (out,)
                e.update(zip(rec.out_keys, outs))
            return jnp.sum(e[loss_key].astype(jnp.float32))

        g = jax.grad(loss_of)([env[k] for k in param_keys])
        return tuple(gi.astype(dt) for gi, dt in zip(g, param_dtypes))

    grad_tensors = [Tensor(jnp.zeros_like(p._data)) for p in params]
    for p, g in zip(params, grad_tensors):
        g.name = (getattr(p, "name", None) or "param") + "@GRAD"
    prog._produced.update(id(g) for g in grad_tensors)
    prog._pins.extend(grad_tensors)
    prog._records.append(_Record(
        _grads_fn, in_keys, tuple(id(g) for g in grad_tensors),
        "append_backward", kind="backward"))
    return list(zip(params, grad_tensors))


_default_main = Program()
_default_startup = Program()
_active: Program | None = None


def default_main_program():
    return _active if _active is not None else _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    """Activate a Program: ops executed in the block are recorded."""
    global _active
    prog = main_program if isinstance(main_program, Program) else Program()
    prev_active = _active
    _active = prog
    prev = _ag._set_static_recorder(prog)
    try:
        yield prog
    finally:
        _ag._set_static_recorder(prev)
        _active = prev_active


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder: a zero Tensor with the declared shape.

    Dynamic dims (-1/None) become 1 for the RECORDING pass; Executor.run
    then re-traces the replay per concrete feed signature. This covers
    shape-polymorphic programs (elementwise/matmul/reduce chains — jax
    tracing re-specializes them at run). A program whose PYTHON code
    reads `x.shape` at build time (e.g. reshape computed from the
    placeholder dim) bakes the stand-in 1 into the recorded op — declare
    concrete shapes for such programs, as with any trace-specialized
    system."""
    prog = _active
    shp = tuple(1 if (s is None or s == -1) else int(s) for s in shape)
    t = Tensor(jnp.zeros(shp, convert_dtype(dtype)))
    t.name = name
    if prog is not None:
        prog._register_feed(name, t)
    return t


class _Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)


_scope = _Scope()


def global_scope():
    return _scope


@contextlib.contextmanager
def scope_guard(scope):
    global _scope
    prev = _scope
    _scope = scope
    try:
        yield
    finally:
        _scope = prev


class Executor:
    """paddle.static.Executor over the replay engine (place-agnostic:
    XLA owns placement)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, **kwargs):
        if program is None:
            program = default_main_program()
        elif not isinstance(program, Program):
            raise TypeError(
                f"Executor.run expects a paddle_tpu.static.Program, got "
                f"{type(program).__name__}")
        if not program._records and not fetch_list:
            return []  # startup program: parameters are already live
        if fetch_list is None:
            if not program._assigns:
                return []
            fetch_list = []  # training program: run for the writebacks
        return program.run(feed, fetch_list)

    def close(self):
        pass


def gradients(targets, inputs, target_gradients=None, no_grad_set=None,
              program=None):
    """paddle.static.gradients: append records computing d(sum targets)
    / d(inputs) for ARBITRARY program values (feeds, parameters, or
    intermediates — reference python/paddle/base/backward.py gradients,
    unverified). Same TPU-native design as append_backward: ONE record
    replaying the forward sub-program under jax.grad; an intermediate
    input is treated as an independent variable by substituting it
    right after the record that produced it (standard cut-the-graph
    semantics), and `no_grad_set` values are routed through
    lax.stop_gradient at their production site. Returns one gradient
    Tensor per input (fetchable program values)."""
    prog = program if program is not None else default_main_program()
    targets = list(targets) if isinstance(targets, (list, tuple)) \
        else [targets]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
        else [inputs]
    tg = target_gradients
    if tg is not None:
        tg = list(tg) if isinstance(tg, (list, tuple)) else [tg]
        if len(tg) != len(targets):
            raise ValueError("target_gradients must match targets")
        for t in tg:
            if t is not None and id(t) not in prog._produced \
                    and id(t) not in prog._leaves:
                prog._leaves[id(t)] = t
                prog._pins.append(t)
    for t in targets:
        if id(t) not in prog._produced and id(t) not in prog._leaves:
            raise ValueError("gradients: target was not produced by this "
                             "Program")
    known = set(prog._produced) | set(prog._leaves) \
        | set(prog._feeds.values())
    for x in inputs:
        if id(x) not in known:
            raise ValueError("gradients: input is not a value of this "
                             "Program (feed, parameter, or op output)")
    stop_keys = {id(s) for s in (no_grad_set or ())}
    fwd_records = list(prog._records)
    input_keys = [id(x) for x in inputs]
    input_dtypes = [x._data.dtype for x in inputs]
    target_keys = [id(t) for t in targets]
    tg_keys = [None if tg is None or tg[i] is None else id(tg[i])
               for i in range(len(targets))]
    feed_keys = tuple(prog._feeds[n] for n in sorted(prog._feeds))
    leaf_keys = tuple(prog._leaves.keys())
    in_keys = feed_keys + leaf_keys

    def _replay(e, sub):
        """Run fwd_records over env e; `sub` maps value-key -> override
        array (the independent variables). Overrides apply to seed
        values immediately and to produced values at their production
        site; no_grad_set values get stop_gradient at production."""
        e = dict(e)
        for k, v in sub.items():
            if k in e:
                e[k] = v
        for rec in fwd_records:
            args = [e[k] for k in rec.in_keys]
            out = rec.fn(*args)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            e.update(zip(rec.out_keys, outs))
            for k in rec.out_keys:
                if k in sub:
                    e[k] = sub[k]
                elif k in stop_keys:
                    e[k] = jax.lax.stop_gradient(e[k])
        return e

    def _grads_fn(*args):
        env0 = dict(zip(in_keys, args))
        base = _replay(env0, {})

        def total(xval, key):
            # each input differentiated INDEPENDENTLY (reference
            # semantics): only this input's value is cut from the
            # graph, so another requested input does not sever a path
            # the current one flows through
            e = _replay(env0, {key: xval})
            s = jnp.float32(0.0)
            for i, tk in enumerate(target_keys):
                ct = (e[tg_keys[i]] if tg_keys[i] is not None
                      else jnp.ones_like(e[tk]))
                s = s + jnp.sum(e[tk].astype(jnp.float32)
                                * ct.astype(jnp.float32))
            return s

        # one grad per input; XLA CSEs the shared replays inside the jit
        return tuple(
            jax.grad(total)(base[k], k).astype(dt)
            for k, dt in zip(input_keys, input_dtypes))

    grad_tensors = [Tensor(jnp.zeros_like(x._data)) for x in inputs]
    for x, g in zip(inputs, grad_tensors):
        g.name = (getattr(x, "name", None) or "var") + "@GRAD"
    prog._produced.update(id(g) for g in grad_tensors)
    prog._pins.extend(grad_tensors)
    prog._records.append(_Record(
        _grads_fn, in_keys, tuple(id(g) for g in grad_tensors),
        "gradients", kind="backward"))
    return grad_tensors


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """paddle.static.py_func: embed a host Python callable as an op of
    the static program (reference python/paddle/static/nn/common.py —
    unverified). TPU-native realization: the record's fn wraps `func`
    in `jax.pure_callback` (XLA host callback), so the compiled replay
    calls back into Python with concrete arrays; `backward_func` (if
    given) becomes the custom VJP, also as a host callback. `out` is
    the pre-created placeholder Tensor(s) fixing shape/dtype — the
    reference contract.

    The callable must be PURE per XLA semantics (it may run 0+ times,
    and never under dead-code paths)."""
    prog = default_main_program()
    if prog is None:
        raise RuntimeError("py_func requires an active program_guard")
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    single = not isinstance(out, (list, tuple))
    out_specs = [jax.ShapeDtypeStruct(tuple(t._data.shape),
                                      t._data.dtype) for t in outs]

    def host_fwd(*arrays):
        import numpy as _np
        res = func(*[Tensor(jnp.asarray(a)) for a in arrays])
        rs = res if isinstance(res, (list, tuple)) else [res]
        return tuple(_np.asarray(r._data if isinstance(r, Tensor) else r,
                                 dtype=s.dtype)
                     for r, s in zip(rs, out_specs))

    if backward_func is None:
        def fn(*arrays):
            # no backward_func: gradient stops here (zero), like the
            # cpp_extension op default — a bare pure_callback would
            # raise an opaque JAX error from inside the replay instead
            r = jax.pure_callback(host_fwd, tuple(out_specs),
                                  *[jax.lax.stop_gradient(a)
                                    for a in arrays])
            return tuple(r)
    else:
        # reference contract: backward_func receives the forward INPUTS,
        # then the forward OUTPUTS, then the output grads — minus any
        # variable listed in skip_vars_in_backward_input (which may name
        # inputs OR outputs, e.g. tanh's backward wants (y, dy) with x
        # skipped) — and returns grads for the inputs x, in order.
        skip = {id(s) for s in (skip_vars_in_backward_input or ())}
        keep_x = [i for i, t in enumerate(xs) if id(t) not in skip]
        keep_o = [j for j, t in enumerate(outs) if id(t) not in skip]

        @jax.custom_vjp
        def core(*arrays):
            return tuple(jax.pure_callback(host_fwd, tuple(out_specs),
                                           *arrays))

        def core_fwd(*arrays):
            res = core(*arrays)
            return res, (arrays, res)

        def core_bwd(saved, cts):
            arrays, fwd_outs = saved
            # integer primals take float0 cotangents (custom_vjp
            # contract); only float inputs get host-computed grads
            float_ix = [i for i, a in enumerate(arrays)
                        if jnp.issubdtype(a.dtype, jnp.inexact)]
            in_specs = [jax.ShapeDtypeStruct(arrays[i].shape,
                                             arrays[i].dtype)
                        for i in float_ix]
            n_x, n_o = len(keep_x), len(keep_o)

            def host_bwd(*packed):
                import numpy as _np
                vals = [Tensor(jnp.asarray(a))
                        for a in packed[:n_x + n_o]]
                gouts = [Tensor(jnp.asarray(g))
                         for g in packed[n_x + n_o:]]
                gin = backward_func(*vals, *gouts)
                gs = gin if isinstance(gin, (list, tuple)) else [gin]
                if len(gs) == len(arrays) and len(gs) != len(float_ix):
                    gs = [gs[i] for i in float_ix]  # grads for all x
                return tuple(
                    _np.zeros(s.shape, s.dtype) if g is None
                    else _np.asarray(g._data if isinstance(g, Tensor)
                                     else g, dtype=s.dtype)
                    for g, s in zip(gs, in_specs))

            picked = ([arrays[i] for i in keep_x]
                      + [fwd_outs[j] for j in keep_o])
            fgs = jax.pure_callback(host_bwd, tuple(in_specs),
                                    *picked, *cts)
            fgs = list(fgs)
            out_gs = []
            for i, a in enumerate(arrays):
                if i in float_ix:
                    out_gs.append(fgs.pop(0))
                else:
                    import numpy as _np
                    out_gs.append(_np.zeros(a.shape,
                                            jax.dtypes.float0))
            return tuple(out_gs)

        core.defvjp(core_fwd, core_bwd)

        def fn(*arrays):
            return core(*arrays)

    prog.record(fn, xs, outs, name="py_func")
    return outs[0] if single else outs
