"""paddle_tpu.static — static-graph mode.

Reference parity: paddle.static.* (upstream python/paddle/static/ —
unverified, see SURVEY.md §2.2). Two tiers:

- **Real Program/Executor** (static/program.py): `program_guard` records
  the op DAG through the autograd chokepoint while ops run eagerly on
  placeholder zeros; `Executor.run(prog, feed, fetch_list)` replays it as
  ONE jitted XLA computation per feed signature. Inference-style programs
  (data → layers/ops → fetch) work end-to-end; parameters created inside
  the guard stay live Tensors, so their trained values flow into later
  runs.
- Deployment save/load maps onto jit.save/load (StableHLO artifacts).
- **Static TRAINING**: `append_backward(loss)` + `optimizer.minimize`
  inside `program_guard` append gradient/update records whose outputs
  are written back to parameter and optimizer-state leaves after every
  `Executor.run` (see static/program.py). The dynamic path (`to_static`,
  fleet Engine) remains the recommended compiled-training story.
"""
from __future__ import annotations

import contextlib

from ..jit.save_load import InputSpec, TranslatedLayer  # noqa: F401
from ..jit.save_load import load as _jit_load
from ..jit.save_load import save as _jit_save
from . import nn  # noqa: F401
from .program import (Executor, Program, append_backward, data,  # noqa: F401
                      default_main_program, default_startup_program,
                      global_scope, program_guard, scope_guard)

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "Program", "program_guard", "data", "Executor",
           "append_backward", "default_main_program",
           "default_startup_program", "global_scope", "scope_guard",
           "name_scope", "device_guard"]


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


from ..core.device import device_guard  # noqa: E402,F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    layer = kwargs.get("layer")
    if layer is None:
        raise ValueError(
            "TPU-native save_inference_model exports a Layer: pass "
            "layer=<nn.Layer> (the reference Program path does not exist "
            "here); or use paddle_tpu.jit.save directly.")
    specs = feed_vars if feed_vars else None
    _jit_save(layer, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit_load(path_prefix)
