"""paddle_tpu.static — static-graph mode.

Reference parity: paddle.static.* (upstream python/paddle/static/ —
unverified, see SURVEY.md §2.2). Two tiers:

- **Real Program/Executor** (static/program.py): `program_guard` records
  the op DAG through the autograd chokepoint while ops run eagerly on
  placeholder zeros; `Executor.run(prog, feed, fetch_list)` replays it as
  ONE jitted XLA computation per feed signature. Inference-style programs
  (data → layers/ops → fetch) work end-to-end; parameters created inside
  the guard stay live Tensors, so their trained values flow into later
  runs.
- Deployment save/load maps onto jit.save/load (StableHLO artifacts).
- **Static TRAINING**: `append_backward(loss)` + `optimizer.minimize`
  inside `program_guard` append gradient/update records whose outputs
  are written back to parameter and optimizer-state leaves after every
  `Executor.run` (see static/program.py). The dynamic path (`to_static`,
  fleet Engine) remains the recommended compiled-training story.
"""
from __future__ import annotations

import contextlib

from ..jit.save_load import InputSpec, TranslatedLayer  # noqa: F401
from . import amp  # noqa: F401
from ..jit.save_load import load as _jit_load
from ..jit.save_load import save as _jit_save
from . import nn  # noqa: F401
from .program import (Executor, Program, append_backward, data,  # noqa: F401
                      default_main_program, default_startup_program,
                      global_scope, program_guard, scope_guard)

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "Program", "program_guard", "data", "Executor",
           "append_backward", "default_main_program",
           "default_startup_program", "global_scope", "scope_guard",
           "name_scope", "device_guard"]


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


from ..core.device import device_guard  # noqa: E402,F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Export an inference artifact loadable by `load_inference_model` /
    `jit.load` / the C++ `pd_infer` runtime.

    Two paths (reference: paddle.static.save_inference_model):
    - `layer=<nn.Layer>`: delegates to jit.save (trace-based export);
    - a recorded PROGRAM (default main or `program=`): the op records
      reaching `fetch_vars` are pruned (training records excluded) and
      exported as StableHLO with the leaf constants/parameters saved by
      name — the reference's Program→inference-model path."""
    layer = kwargs.get("layer")
    if layer is not None:
        specs = feed_vars if feed_vars else None
        _jit_save(layer, path_prefix, input_spec=specs)
        return
    import json
    import os

    import numpy as np

    import jax
    import jax.numpy as jnp

    from .program import Program, default_main_program
    prog = program if isinstance(program, Program) \
        else default_main_program()
    if not prog._records:
        raise ValueError(
            "save_inference_model: the Program has no recorded ops; "
            "build it under program_guard (or pass layer=<nn.Layer>)")
    feed_vars = list(feed_vars or [])
    fetch_vars = list(fetch_vars or [])
    if not feed_vars or not fetch_vars:
        raise ValueError("save_inference_model needs feed_vars and "
                         "fetch_vars from the recorded Program")
    fetch_keys = [id(t) for t in fetch_vars]
    feed_keys = [id(t) for t in feed_vars]
    # prune to forward records reaching the fetches (no training records,
    # no writebacks — an inference snapshot)
    need = set(fetch_keys)
    active = []
    for rec in reversed([r for r in prog._records if r.kind == "op"]):
        if any(k in need for k in rec.out_keys):
            active.append(rec)
            need.update(rec.in_keys)
    active.reverse()
    leaf_keys = [k for k in prog._leaves if k in need]
    leaf_arrays = [prog._leaves[k]._data for k in leaf_keys]
    names = [f"leaf_{i}" for i in range(len(leaf_keys))]

    def pure(params, buffers, *feeds):
        env = dict(zip(leaf_keys, params))
        env.update(zip(feed_keys, feeds))
        for rec in active:
            args = [env[k] for k in rec.in_keys]
            out = rec.fn(*args)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            env.update(zip(rec.out_keys, outs))
        return tuple(env[k] for k in fetch_keys)

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    np.savez(path_prefix + ".pdiparams.npz",
             **{n: np.asarray(a) for n, a in zip(names, leaf_arrays)})
    meta = {"type": "program", "params": names, "buffers": [],
            "fetches": len(fetch_keys)}
    specs = [jax.ShapeDtypeStruct(tuple(t._data.shape),
                                  jnp.dtype(t._data.dtype))
             for t in feed_vars]
    try:
        exported = jax.export.export(jax.jit(pure))(
            [jax.ShapeDtypeStruct(a.shape, a.dtype)
             for a in leaf_arrays], [], *specs)
        with open(path_prefix + ".stablehlo", "wb") as f:
            f.write(exported.serialize())
        meta["stablehlo"] = True
    except Exception as e:
        meta["stablehlo"] = False
        meta["export_error"] = str(e)[:500]
    with open(path_prefix + ".pdmodel.json", "w") as f:
        json.dump(meta, f)


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit_load(path_prefix)


def cpu_places(device_count=None):
    """paddle.static.cpu_places (reference python/paddle/base/framework
    — unverified): CPU places; count defaults to 1 (the reference reads
    CPU_NUM)."""
    import os

    from ..core.device import Place
    n = device_count or int(os.environ.get("CPU_NUM", "1"))
    return [Place("cpu", i) for i in range(n)]


def cuda_places(device_ids=None):
    """paddle.static.cuda_places, TPU-natively: places of the visible
    ACCELERATOR devices (tpu under axon/PJRT — the role 'cuda_places'
    plays in reference code is "give me the accelerators"). Falls back
    to CPU places when no accelerator is attached."""
    import jax

    from ..core.device import Place
    kinds = {"tpu": "tpu", "axon": "tpu", "gpu": "gpu", "cuda": "gpu"}
    devs = [d for d in jax.local_devices()
            if d.platform in kinds]
    if not devs:
        return cpu_places(len(device_ids) if device_ids else None)
    if device_ids is None:
        device_ids = range(len(devs))
    return [Place(kinds[devs[i].platform], i) for i in device_ids]


def save(program, path_prefix, protocol=4):
    """paddle.static.save: persist the program's parameters
    (``.pdparams``) and the remaining float leaf state, e.g. optimizer
    moments pinned by minimize (``.pdopt``). Positional format — the
    reference keys by variable name; record-time ids are not stable
    across processes, so entries are (name, array) pairs restored by
    position into the SAME program structure."""
    import pickle

    import numpy as np

    from ..core.tensor import Parameter
    params, state = [], []
    for t in program._leaves.values():
        entry = (getattr(t, "name", None), np.asarray(t._data))
        (params if isinstance(t, Parameter) else state).append(entry)
    with open(path_prefix + ".pdparams", "wb") as f:
        pickle.dump(params, f, protocol=protocol)
    if state:
        with open(path_prefix + ".pdopt", "wb") as f:
            pickle.dump(state, f, protocol=protocol)


def load(program, path_prefix, executor=None, var_list=None):
    """paddle.static.load: restore what `save` wrote, by position."""
    import os
    import pickle

    import jax.numpy as jnp

    from ..core.tensor import Parameter
    with open(path_prefix + ".pdparams", "rb") as f:
        params = pickle.load(f)
    state = []
    if os.path.exists(path_prefix + ".pdopt"):
        with open(path_prefix + ".pdopt", "rb") as f:
            state = pickle.load(f)
    targets_p = [t for t in program._leaves.values()
                 if isinstance(t, Parameter)]
    targets_s = [t for t in program._leaves.values()
                 if not isinstance(t, Parameter)]
    if len(params) != len(targets_p):
        raise ValueError(
            f"checkpoint has {len(params)} parameters, program has "
            f"{len(targets_p)} — was it saved from this program?")
    if state and len(state) != len(targets_s):
        raise ValueError(
            f"checkpoint has {len(state)} aux-state entries, program "
            f"has {len(targets_s)} — rebuild the program to the same "
            "point (e.g. run minimize before load) or delete the "
            ".pdopt file for a params-only restore")
    for t, (_, arr) in zip(targets_p, params):
        t._inplace_update(jnp.asarray(arr).astype(t._data.dtype))
    for t, (_, arr) in zip(targets_s, state):
        t._inplace_update(jnp.asarray(arr).astype(t._data.dtype))


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """paddle.static.normalize_program: prune a trained program down to
    the inference graph for the given feeds/fetches. The record-replay
    design makes this the test-mode clone (dead-record elimination at
    run time keeps exactly the ops reaching the fetches)."""
    return program.clone(for_test=True)


from .program import gradients, py_func  # noqa: E402,F401

__all__ += ["cpu_places", "cuda_places", "save", "load",
            "normalize_program", "gradients", "py_func"]
