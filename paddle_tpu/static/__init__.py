"""paddle_tpu.static — static-graph compatibility shims.

Reference parity: paddle.static.* (upstream python/paddle/static/ —
unverified, see SURVEY.md §2.2). This framework is eager-first with
jax.jit compilation (SURVEY.md §7 design stance: PIR/program machinery
collapses into tracing); the static API surface maps onto the jit/export
path so reference scripts keep working:

- InputSpec → shape/dtype specs for to_static/jit.save
- save/load_inference_model → jit.save/load (StableHLO artifact)
- program_guard/default_main_program → no-op context shims
"""
from __future__ import annotations

import contextlib

from ..jit.save_load import InputSpec, TranslatedLayer  # noqa: F401
from ..jit.save_load import load as _jit_load
from ..jit.save_load import save as _jit_save

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "Program", "program_guard", "default_main_program",
           "default_startup_program", "name_scope", "device_guard"]


class Program:
    """Placeholder Program: compiled programs are jaxprs managed by jit."""

    def __init__(self):
        self._is_shim = True

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


@contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    yield


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


from ..core.device import device_guard  # noqa: E402,F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    layer = kwargs.get("layer")
    if layer is None:
        raise ValueError(
            "TPU-native save_inference_model exports a Layer: pass "
            "layer=<nn.Layer> (the reference Program path does not exist "
            "here); or use paddle_tpu.jit.save directly.")
    specs = feed_vars if feed_vars else None
    _jit_save(layer, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit_load(path_prefix)
