"""paddle.static.amp — static-graph AMP namespace (reference: upstream
python/paddle/static/amp/ — unverified, SURVEY.md blocker notice).

The dynamic amp module's auto_cast/decorate/GradScaler compose with the
static recorder (tested in tests/test_static_training.py's AMP case), so
the static namespace is the same machinery re-exported — the reference's
separate static rewrite pass collapses under trace-and-compile.
"""
from ..amp import (GradScaler, auto_cast, decorate)  # noqa: F401

amp_guard = auto_cast          # legacy alias (fluid.dygraph.amp_guard)
amp_decorate = decorate        # legacy alias

__all__ = ["auto_cast", "decorate", "GradScaler", "amp_guard",
           "amp_decorate"]
