"""paddle.static.nn parity — layer-functions usable inside program_guard
(reference: python/paddle/static/nn/ fc/conv2d/batch_norm — unverified;
SURVEY.md §2.2 "Static API").

Each call creates the parameters eagerly (they become live leaf inputs
of the active Program) and runs the op through the recorded functional
path — so `Executor.run` replays with current weights.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn import functional as F
from ..nn import initializer as I
from ..core.tensor import Parameter
from ..ops._base import ensure_tensor

__all__ = ["fc", "conv2d", "batch_norm", "embedding"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    x = ensure_tensor(x)
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= d
    if len(x.shape) > num_flatten_dims + 1:
        # keep dim 0 symbolic (-1): the recorded reshape must not bake
        # the data() placeholder's stand-in batch size; dims
        # 1..num_flatten_dims-1 stay concrete (only the batch dim is
        # dynamic in the data() contract)
        x = x.reshape([-1] + list(x.shape[1:num_flatten_dims]) + [in_dim])
    w = Parameter(I.XavierNormal()((in_dim, size), jnp.float32))
    b = Parameter(jnp.zeros((size,), jnp.float32)) \
        if bias_attr is not False else None
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    input = ensure_tensor(input)
    cin = input.shape[1]
    ks = (filter_size if isinstance(filter_size, (tuple, list))
          else (filter_size, filter_size))
    w = Parameter(I.XavierNormal()(
        (num_filters, cin // groups) + tuple(ks), jnp.float32))
    b = Parameter(jnp.zeros((num_filters,), jnp.float32)) \
        if bias_attr is not False else None
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=True, name=None):
    """Inference-mode BN (static programs are inference programs here)."""
    from ..core.tensor import Tensor
    input = ensure_tensor(input)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    gamma = Parameter(jnp.ones((c,), jnp.float32))
    beta = Parameter(jnp.zeros((c,), jnp.float32))
    mean = Tensor(jnp.zeros((c,), jnp.float32))
    var = Tensor(jnp.ones((c,), jnp.float32))
    out = F.batch_norm(input, mean, var, gamma, beta, training=False,
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    from ..core.dtype import convert_dtype
    input = ensure_tensor(input)
    w = Parameter(I.XavierNormal()(tuple(size), convert_dtype(dtype)))
    return F.embedding(input, w, padding_idx=padding_idx)
