"""paddle.static.nn parity — layer-functions usable inside program_guard
(reference: python/paddle/static/nn/ fc/conv2d/batch_norm — unverified;
SURVEY.md §2.2 "Static API").

Each call creates the parameters eagerly (they become live leaf inputs
of the active Program) and runs the op through the recorded functional
path — so `Executor.run` replays with current weights.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn import functional as F
from ..nn import initializer as I
from ..core.tensor import Parameter
from ..ops._base import ensure_tensor

__all__ = ["fc", "conv2d", "batch_norm", "embedding",
           "cond", "while_loop", "switch_case"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    x = ensure_tensor(x)
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= d
    if len(x.shape) > num_flatten_dims + 1:
        # keep dim 0 symbolic (-1): the recorded reshape must not bake
        # the data() placeholder's stand-in batch size; dims
        # 1..num_flatten_dims-1 stay concrete (only the batch dim is
        # dynamic in the data() contract)
        x = x.reshape([-1] + list(x.shape[1:num_flatten_dims]) + [in_dim])
    w = Parameter(I.XavierNormal()((in_dim, size), jnp.float32))
    b = Parameter(jnp.zeros((size,), jnp.float32)) \
        if bias_attr is not False else None
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    input = ensure_tensor(input)
    cin = input.shape[1]
    ks = (filter_size if isinstance(filter_size, (tuple, list))
          else (filter_size, filter_size))
    w = Parameter(I.XavierNormal()(
        (num_filters, cin // groups) + tuple(ks), jnp.float32))
    b = Parameter(jnp.zeros((num_filters,), jnp.float32)) \
        if bias_attr is not False else None
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=True, name=None):
    """Inference-mode BN (static programs are inference programs here)."""
    from ..core.tensor import Tensor
    input = ensure_tensor(input)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    gamma = Parameter(jnp.ones((c,), jnp.float32))
    beta = Parameter(jnp.zeros((c,), jnp.float32))
    mean = Tensor(jnp.zeros((c,), jnp.float32))
    var = Tensor(jnp.ones((c,), jnp.float32))
    out = F.batch_norm(input, mean, var, gamma, beta, training=False,
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    from ..core.dtype import convert_dtype
    input = ensure_tensor(input)
    w = Parameter(I.XavierNormal()(tuple(size), convert_dtype(dtype)))
    return F.embedding(input, w, padding_idx=padding_idx)


# ---------------------------------------------------------------------------
# Control flow (reference: paddle.static.nn.cond/while_loop/switch_case).
# TPU-native design: each construct is ONE recorded op whose fn runs the
# matching lax primitive (cond/while_loop/switch). The user's branch/body
# callables are Tensor-level closures over earlier program values; their
# closed-over Tensors are collected as record INPUTS and substituted at
# replay, so the branches re-execute against the replay's live values —
# dynamic control flow survives into the jitted replay instead of being
# frozen at record time.


def _closure_tensors(fns):
    from ..core.tensor import Tensor
    seen = {}

    def visit(v):
        if isinstance(v, Tensor):
            seen.setdefault(id(v), v)
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)
        elif isinstance(v, dict):
            for x in v.values():
                visit(x)

    for f in fns:
        if f is None:
            continue
        for cell in (getattr(f, "__closure__", None) or ()):
            try:
                visit(cell.cell_contents)
            except ValueError:
                pass  # empty cell
    return list(seen.values())


def _flatten_out(out):
    from ..core.tensor import Tensor
    outs = out if isinstance(out, (tuple, list)) else (out,)
    return tuple(o._data if isinstance(o, Tensor) else jnp.asarray(o)
                 for o in outs)


def _record_or_apply(fn, in_tensors, name):
    """Recording path for control-flow constructs: capture shapes
    ABSTRACTLY (eval_shape — traces, executes nothing) with the recorder
    shielded, and append one record with placeholder outputs. Two
    reasons apply() cannot be used here: (a) a while_loop executed
    eagerly on the data() placeholders (zeros) can diverge; (b) lax
    control flow traces its branches even eagerly, so the branches'
    interior framework ops would be recorded as spurious program
    entries. Outside recording, apply() runs the construct for real
    (dygraph semantics, differentiable)."""
    from ..core import autograd as _ag
    from ..core.autograd import apply
    from ..core.tensor import Tensor as _T
    rec = _ag._STATIC_RECORDER
    if rec is None:
        return apply(fn, *in_tensors, name=name)
    import jax
    prev = _ag._set_static_recorder(None)
    try:
        outs_shape = jax.eval_shape(fn, *[t._data for t in in_tensors])
    finally:
        _ag._set_static_recorder(prev)
    single = not isinstance(outs_shape, tuple)
    outs = (outs_shape,) if single else outs_shape
    out_tensors = [_T(jnp.zeros(s.shape, s.dtype)) for s in outs]
    rec.record(fn, list(in_tensors), out_tensors, name=name)
    return out_tensors[0] if single else tuple(out_tensors)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """paddle.static.nn.cond: run `true_fn()` or `false_fn()` depending
    on a (possibly feed-dependent) boolean Tensor. Branches must be
    side-effect-free and return matching structures."""
    import jax

    pred = ensure_tensor(pred)
    closed = _closure_tensors([true_fn, false_fn])

    def fn(pred_a, *cls):
        saved = [(t, t._data) for t in closed]
        for t, a in zip(closed, cls):
            t._data = a
        try:
            def run(f):
                return lambda: _flatten_out(f() if f is not None else ())
            p = jnp.squeeze(pred_a).astype(bool)
            out = jax.lax.cond(p, run(true_fn), run(false_fn))
            return out if len(out) != 1 else out[0]
        finally:
            for t, a in saved:
                t._data = a

    return _record_or_apply(fn, [pred] + closed, "static.nn.cond")


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop: iterate `body_fn(*vars)` while
    `cond_fn(*vars)` holds — lowered to lax.while_loop, so the trip
    count is runtime-dynamic in the replayed program. Carried values
    must keep shapes/dtypes across iterations."""
    import jax

    from ..core.tensor import Tensor

    loop_vars = [ensure_tensor(v) for v in loop_vars]
    n = len(loop_vars)
    closed = _closure_tensors([cond_fn, body_fn])

    def fn(*args):
        carry0 = tuple(args[:n])
        cls = args[n:]
        saved = [(t, t._data) for t in closed]
        for t, a in zip(closed, cls):
            t._data = a
        try:
            def c(carry):
                r = cond_fn(*[Tensor(a) for a in carry])
                r = r._data if isinstance(r, Tensor) else jnp.asarray(r)
                return jnp.squeeze(r).astype(bool)

            def b(carry):
                out = body_fn(*[Tensor(a) for a in carry])
                flat = _flatten_out(out)
                if len(flat) != n:
                    raise ValueError(
                        f"while_loop body returned {len(flat)} values "
                        f"for {n} loop_vars")
                return flat

            out = jax.lax.while_loop(c, b, carry0)
            return out if len(out) != 1 else out[0]
        finally:
            for t, a in saved:
                t._data = a

    out = _record_or_apply(fn, list(loop_vars) + closed,
                           "static.nn.while_loop")
    return list(out) if isinstance(out, tuple) else [out]


def switch_case(branch_index, branch_fns, default=None, name=None):
    """paddle.static.nn.switch_case over lax.switch."""
    import jax

    branch_index = ensure_tensor(branch_index)
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
        keys = [k for k, _ in items]
        fns = [f for _, f in items]
    else:
        fns = [f if not isinstance(f, (tuple, list)) else f[1]
               for f in branch_fns]
        keys = [i if not isinstance(f, (tuple, list)) else f[0]
                for i, f in enumerate(branch_fns)]
    if keys != list(range(len(keys))):
        raise NotImplementedError(
            f"switch_case requires dense 0..n-1 branch keys (got {keys})")
    if default is not None:
        fns = fns + [default]
    closed = _closure_tensors(fns)

    def fn(idx_a, *cls):
        saved = [(t, t._data) for t in closed]
        for t, a in zip(closed, cls):
            t._data = a
        try:
            runs = [(lambda f=f: _flatten_out(f())) for f in fns]
            raw = jnp.squeeze(idx_a).astype(jnp.int32)
            if default is not None:
                # out-of-range indices route to the default branch
                # (appended last)
                n_cases = len(fns) - 1
                i = jnp.where((raw >= 0) & (raw < n_cases), raw, n_cases)
            else:
                i = jnp.clip(raw, 0, len(runs) - 1)
            out = jax.lax.switch(i, runs)
            return out if len(out) != 1 else out[0]
        finally:
            for t, a in saved:
                t._data = a

    return _record_or_apply(fn, [branch_index] + closed,
                            "static.nn.switch_case")
