// TCPStore — rendezvous key-value store.
//
// Reference parity: the TCPStore/MasterDaemon rendezvous KV used by
// init_parallel_env and the elastic manager (upstream
// paddle/fluid/distributed/store/tcp_store.cc — unverified, see SURVEY.md
// §2.1). Re-designed, not translated: a compact single-file C++17
// implementation with a blocking master daemon thread, length-prefixed
// binary protocol, and a C ABI consumed from Python via ctypes (this
// image has no pybind11).
//
// Protocol: [u8 op][u32 klen][key][u32 vlen][value] -> [u32 len][payload]
//   op: 1=SET 2=GET 3=DEL 4=ADD(i64 delta; returns new value) 5=KEYS
//       6=WAIT(key; blocks until set) 7=PING
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread tcp_store.cpp -o libpd_store.so

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t { SET = 1, GET = 2, DEL = 3, ADD = 4, KEYS = 5,
                    WAIT = 6, PING = 7 };

bool read_all(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_u32(int fd, uint32_t* v) {
  uint32_t net;
  if (!read_all(fd, &net, 4)) return false;
  *v = ntohl(net);
  return true;
}

bool write_u32(int fd, uint32_t v) {
  uint32_t net = htonl(v);
  return write_all(fd, &net, 4);
}

bool read_blob(int fd, std::string* out) {
  uint32_t len;
  if (!read_u32(fd, &len)) return false;
  out->resize(len);
  return len == 0 || read_all(fd, out->data(), len);
}

bool write_blob(int fd, const std::string& s) {
  return write_u32(fd, static_cast<uint32_t>(s.size())) &&
         (s.empty() || write_all(fd, s.data(), s.size()));
}

class MasterDaemon {
 public:
  explicit MasterDaemon(int port) : port_(port) {}

  bool start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      return false;
    if (::listen(listen_fd_, 64) != 0) return false;
    running_.store(true);
    accept_thread_ = std::thread([this] { accept_loop(); });
    return true;
  }

  void stop() {
    running_.store(false);
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    cv_.notify_all();
    {
      // unblock serve() threads parked in read() on live connections
      std::lock_guard<std::mutex> g(fds_mu_);
      for (int fd : client_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    std::lock_guard<std::mutex> g(workers_mu_);
    for (auto& t : workers_)
      if (t.joinable()) t.join();
  }

  ~MasterDaemon() { stop(); }

 private:
  void accept_loop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (!running_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> g(fds_mu_);
        client_fds_.push_back(fd);
      }
      std::lock_guard<std::mutex> g(workers_mu_);
      workers_.emplace_back([this, fd] { serve(fd); });
    }
  }

  void serve(int fd) {
    while (running_.load()) {
      uint8_t op;
      if (!read_all(fd, &op, 1)) break;
      std::string key, val;
      if (op != PING && !read_blob(fd, &key)) break;
      switch (op) {
        case SET: {
          if (!read_blob(fd, &val)) goto done;
          {
            std::lock_guard<std::mutex> g(mu_);
            kv_[key] = val;
          }
          cv_.notify_all();
          if (!write_blob(fd, "ok")) goto done;
          break;
        }
        case GET: {
          std::string out;
          bool found;
          {
            std::lock_guard<std::mutex> g(mu_);
            auto it = kv_.find(key);
            found = it != kv_.end();
            if (found) out = it->second;
          }
          if (!write_u32(fd, found ? 1 : 0)) goto done;
          if (!write_blob(fd, out)) goto done;
          break;
        }
        case DEL: {
          {
            std::lock_guard<std::mutex> g(mu_);
            kv_.erase(key);
          }
          if (!write_blob(fd, "ok")) goto done;
          break;
        }
        case ADD: {
          if (!read_blob(fd, &val)) goto done;
          int64_t delta = 0;
          std::memcpy(&delta, val.data(),
                      std::min(val.size(), sizeof(delta)));
          int64_t now;
          {
            std::lock_guard<std::mutex> g(mu_);
            int64_t cur = 0;
            auto it = kv_.find(key);
            if (it != kv_.end() && it->second.size() == sizeof(int64_t))
              std::memcpy(&cur, it->second.data(), sizeof(cur));
            now = cur + delta;
            std::string packed(sizeof(now), '\0');
            std::memcpy(packed.data(), &now, sizeof(now));
            kv_[key] = packed;
          }
          cv_.notify_all();
          std::string packed(sizeof(now), '\0');
          std::memcpy(packed.data(), &now, sizeof(now));
          if (!write_blob(fd, packed)) goto done;
          break;
        }
        case KEYS: {
          // `key` carries an optional PREFIX: only matching keys are
          // returned (empty = all). Server-side filtering keeps the
          // elastic heartbeat scan O(matching), not O(total store).
          std::string joined;
          {
            std::lock_guard<std::mutex> g(mu_);
            for (auto& [k, _] : kv_) {
              if (!key.empty() && k.rfind(key, 0) != 0) continue;
              joined += k;
              joined += '\n';
            }
          }
          if (!write_blob(fd, joined)) goto done;
          break;
        }
        case WAIT: {
          std::unique_lock<std::mutex> g(mu_);
          cv_.wait(g, [&] {
            return !running_.load() || kv_.count(key) > 0;
          });
          std::string out = kv_.count(key) ? kv_[key] : "";
          g.unlock();
          if (!write_blob(fd, out)) goto done;
          break;
        }
        case PING: {
          if (!write_blob(fd, "pong")) goto done;
          break;
        }
        default:
          goto done;
      }
    }
  done:
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      client_fds_.erase(
          std::remove(client_fds_.begin(), client_fds_.end(), fd),
          client_fds_.end());
    }
    ::close(fd);
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::mutex workers_mu_;
  std::vector<std::thread> workers_;
  std::mutex fds_mu_;
  std::vector<int> client_fds_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> kv_;
};

class Client {
 public:
  Client(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    ::inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr),
                  sizeof(addr)) != 0) {
      ::close(fd_);
      fd_ = -1;
    } else {
      int one = 1;
      ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
  }

  bool ok() const { return fd_ >= 0; }

  bool set(const std::string& k, const std::string& v) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = SET;
    if (!write_all(fd_, &op, 1) || !write_blob(fd_, k) ||
        !write_blob(fd_, v))
      return false;
    std::string ack;
    return read_blob(fd_, &ack);
  }

  bool get(const std::string& k, std::string* out, bool* found) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = GET;
    if (!write_all(fd_, &op, 1) || !write_blob(fd_, k)) return false;
    uint32_t f;
    if (!read_u32(fd_, &f)) return false;
    *found = f != 0;
    return read_blob(fd_, out);
  }

  bool del(const std::string& k) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = DEL;
    if (!write_all(fd_, &op, 1) || !write_blob(fd_, k)) return false;
    std::string ack;
    return read_blob(fd_, &ack);
  }

  bool add(const std::string& k, int64_t delta, int64_t* out) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = ADD;
    std::string packed(sizeof(delta), '\0');
    std::memcpy(packed.data(), &delta, sizeof(delta));
    if (!write_all(fd_, &op, 1) || !write_blob(fd_, k) ||
        !write_blob(fd_, packed))
      return false;
    std::string res;
    if (!read_blob(fd_, &res) || res.size() != sizeof(int64_t))
      return false;
    std::memcpy(out, res.data(), sizeof(int64_t));
    return true;
  }

  bool keys(const std::string& prefix, std::string* out) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = KEYS;
    if (!write_all(fd_, &op, 1) || !write_blob(fd_, prefix)) return false;
    return read_blob(fd_, out);
  }

  bool wait(const std::string& k, std::string* out) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = WAIT;
    if (!write_all(fd_, &op, 1) || !write_blob(fd_, k)) return false;
    return read_blob(fd_, out);
  }

  ~Client() {
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  int fd_ = -1;
  std::mutex mu_;
};

thread_local std::string g_last_result;

}  // namespace

extern "C" {

void* pd_store_server_start(int port) {
  auto* d = new MasterDaemon(port);
  if (!d->start()) {
    delete d;
    return nullptr;
  }
  return d;
}

void pd_store_server_stop(void* h) {
  delete static_cast<MasterDaemon*>(h);
}

void* pd_store_client_new(const char* host, int port) {
  auto* c = new Client(host, port);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

void pd_store_client_free(void* h) { delete static_cast<Client*>(h); }

int pd_store_set(void* h, const char* key, const char* data, int len) {
  return static_cast<Client*>(h)->set(key, std::string(data, len)) ? 0 : -1;
}

// returns length (>=0) and stashes payload; -1 = missing, -2 = error
int pd_store_get(void* h, const char* key) {
  bool found = false;
  if (!static_cast<Client*>(h)->get(key, &g_last_result, &found)) return -2;
  if (!found) return -1;
  return static_cast<int>(g_last_result.size());
}

int pd_store_wait(void* h, const char* key) {
  if (!static_cast<Client*>(h)->wait(key, &g_last_result)) return -2;
  return static_cast<int>(g_last_result.size());
}

int pd_store_keys(void* h) {
  if (!static_cast<Client*>(h)->keys("", &g_last_result)) return -2;
  return static_cast<int>(g_last_result.size());
}

// prefix-filtered key listing (server-side) — empty prefix = all keys
int pd_store_keys_prefix(void* h, const char* prefix) {
  if (!static_cast<Client*>(h)->keys(prefix, &g_last_result)) return -2;
  return static_cast<int>(g_last_result.size());
}

void pd_store_fetch(void* h, char* out, int len) {
  std::memcpy(out, g_last_result.data(),
              std::min<size_t>(len, g_last_result.size()));
}

int pd_store_delete(void* h, const char* key) {
  return static_cast<Client*>(h)->del(key) ? 0 : -1;
}

long long pd_store_add(void* h, const char* key, long long delta) {
  int64_t out = 0;
  if (!static_cast<Client*>(h)->add(key, delta, &out)) return -1;
  return out;
}

}  // extern "C"
