"""Native (C++) runtime components + ctypes bindings.

Reference parity: the native runtime around the compute path — TCPStore
rendezvous (paddle/fluid/distributed/store/) and DataLoader worker core
(SURVEY.md §2.1/§2.2) — re-designed in compact C++17, built on demand with
g++ (no pybind11 in this image; bindings are ctypes over a C ABI).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time as _time

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()


def _build(src: str, out: str) -> str:
    src_path = os.path.join(_DIR, src)
    out_path = os.path.join(_DIR, out)
    with _BUILD_LOCK:
        if (not os.path.exists(out_path) or
                os.path.getmtime(out_path) < os.path.getmtime(src_path)):
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-pthread", src_path, "-o", out_path]
            subprocess.run(cmd, check=True, capture_output=True)
    return out_path


def _load(src, out):
    return ctypes.CDLL(_build(src, out))


# --------------------------------------------------------------------------
# TCPStore


class TCPStore:
    """Reference parity: paddle.distributed's TCPStore rendezvous KV.

    is_master=True starts the in-process master daemon; every instance is
    also a client. Values are bytes; `add` is an atomic int64 counter —
    the primitive barrier/rendezvous building block.
    """

    _lib = None

    @classmethod
    def lib(cls):
        if cls._lib is None:
            lib = _load("tcp_store.cpp", "libpd_store.so")
            lib.pd_store_server_start.restype = ctypes.c_void_p
            lib.pd_store_server_start.argtypes = [ctypes.c_int]
            lib.pd_store_server_stop.argtypes = [ctypes.c_void_p]
            lib.pd_store_client_new.restype = ctypes.c_void_p
            lib.pd_store_client_new.argtypes = [ctypes.c_char_p,
                                                ctypes.c_int]
            lib.pd_store_client_free.argtypes = [ctypes.c_void_p]
            lib.pd_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_char_p, ctypes.c_int]
            lib.pd_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.pd_store_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.pd_store_keys.argtypes = [ctypes.c_void_p]
            lib.pd_store_fetch.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p, ctypes.c_int]
            lib.pd_store_delete.argtypes = [ctypes.c_void_p,
                                            ctypes.c_char_p]
            lib.pd_store_add.restype = ctypes.c_longlong
            lib.pd_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_longlong]
            cls._lib = lib
        return cls._lib

    def __init__(self, host="127.0.0.1", port=23457, is_master=False,
                 world_size=1, timeout=None):
        lib = self.lib()
        self._server = None
        if is_master:
            self._server = lib.pd_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore master failed to bind :{port}")
        # Non-master workers may race the master's bind: retry until the
        # timeout (reference TCPStore clients block on connect the same way).
        deadline = _time.monotonic() + (120.0 if timeout is None
                                        else timeout)
        self._client = lib.pd_store_client_new(host.encode(), port)
        while not self._client and _time.monotonic() < deadline:
            _time.sleep(0.1)
            self._client = lib.pd_store_client_new(host.encode(), port)
        if not self._client:
            if self._server:
                lib.pd_store_server_stop(self._server)
            raise RuntimeError(f"TCPStore cannot connect {host}:{port}")

    def set(self, key: str, value: bytes):
        if isinstance(value, str):
            value = value.encode()
        rc = self.lib().pd_store_set(self._client, key.encode(), value,
                                     len(value))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def _fetch(self, n: int) -> bytes:
        buf = ctypes.create_string_buffer(n)
        self.lib().pd_store_fetch(self._client, buf, n)
        return buf.raw[:n]

    def get(self, key: str) -> bytes:
        n = self.lib().pd_store_get(self._client, key.encode())
        if n == -1:
            raise KeyError(key)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return self._fetch(n)

    def wait(self, key: str) -> bytes:
        n = self.lib().pd_store_wait(self._client, key.encode())
        if n < 0:
            raise RuntimeError("TCPStore.wait failed")
        return self._fetch(n)

    def add(self, key: str, delta: int = 1) -> int:
        return int(self.lib().pd_store_add(self._client, key.encode(),
                                           delta))

    def delete(self, key: str):
        self.lib().pd_store_delete(self._client, key.encode())

    def keys(self):
        n = self.lib().pd_store_keys(self._client)
        if n < 0:
            raise RuntimeError("TCPStore.keys failed")
        raw = self._fetch(n).decode()
        return [k for k in raw.split("\n") if k]

    def close(self):
        lib = self.lib()
        if self._client:
            lib.pd_store_client_free(self._client)
            self._client = None
        if self._server:
            lib.pd_store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# Token loader


class TokenLoader:
    """C++ mmap+prefetch reader of flat token binaries → [B, S+1] int32
    batches (LLM pretraining input pipeline; see data_loader.cpp)."""

    _lib = None

    @classmethod
    def lib(cls):
        if cls._lib is None:
            lib = _load("data_loader.cpp", "libpd_loader.so")
            lib.pd_loader_new.restype = ctypes.c_void_p
            lib.pd_loader_new.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_int, ctypes.c_int, ctypes.c_ulonglong,
                ctypes.c_int]
            lib.pd_loader_num_windows.restype = ctypes.c_longlong
            lib.pd_loader_num_windows.argtypes = [ctypes.c_void_p]
            lib.pd_loader_next.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(dtype=np.int32, flags="C")]
            lib.pd_loader_free.argtypes = [ctypes.c_void_p]
            cls._lib = lib
        return cls._lib

    def __init__(self, path, seq_len, batch_size, num_workers=2,
                 prefetch=4, seed=0, dtype="uint16"):
        dtype_size = np.dtype(dtype).itemsize
        self.seq_len = int(seq_len)
        self.batch_size = int(batch_size)
        self._h = self.lib().pd_loader_new(
            str(path).encode(), seq_len, batch_size, num_workers, prefetch,
            seed, dtype_size)
        if not self._h:
            raise RuntimeError(f"TokenLoader cannot open {path}")

    @property
    def num_windows(self):
        return int(self.lib().pd_loader_num_windows(self._h))

    def next(self):
        out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        rc = self.lib().pd_loader_next(self._h, out)
        if rc != 0:
            raise StopIteration
        return out

    def __iter__(self):
        while True:
            yield self.next()

    def close(self):
        if self._h:
            self.lib().pd_loader_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
