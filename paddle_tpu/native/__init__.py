"""Native (C++) runtime components + ctypes bindings.

Reference parity: the native runtime around the compute path — TCPStore
rendezvous (paddle/fluid/distributed/store/) and DataLoader worker core
(SURVEY.md §2.1/§2.2) — re-designed in compact C++17, built on demand with
g++ (no pybind11 in this image; bindings are ctypes over a C ABI).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import time as _time

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()


def _build(src: str, out: str, *, shared=True, extra_flags=()) -> str:
    """Compile `src` to `out` on demand, keyed by source CONTENT hash.

    Binaries are machine/ABI-specific and never checked in (.gitignore);
    an mtime check would trust a stale artifact after a fresh checkout
    (git resets mtimes), so the rebuild key is a sha256 of the source +
    flags, stored in a sidecar `.stamp` file next to the binary.
    """
    import fcntl
    src_path = os.path.join(_DIR, src)
    out_path = os.path.join(_DIR, out)
    stamp_path = out_path + ".stamp"
    with open(src_path, "rb") as f:
        digest = hashlib.sha256(
            f.read() + repr(sorted(extra_flags)).encode()).hexdigest()
    # _BUILD_LOCK serializes threads; the fcntl lock serializes PROCESSES
    # (multi-controller workers all import native on startup and would
    # otherwise race g++ writing the same .so in place).
    with _BUILD_LOCK, open(out_path + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        stale = not os.path.exists(out_path)
        if not stale:
            try:
                with open(stamp_path) as f:
                    stale = f.read().strip() != digest
            except OSError:
                stale = True
        if stale:
            tmp_path = f"{out_path}.tmp.{os.getpid()}"
            cmd = (["g++", "-O2", "-std=c++17"] +
                   (["-shared"] if shared else []) +
                   ["-fPIC", "-pthread"] + list(extra_flags) +
                   [src_path, "-o", tmp_path])
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode != 0:
                raise RuntimeError(f"native build of {src} failed:\n"
                                   f"{r.stderr}")
            os.replace(tmp_path, out_path)  # atomic: no half-written dlopen
            with open(stamp_path, "w") as f:
                f.write(digest)
    return out_path


def _load(src, out):
    return ctypes.CDLL(_build(src, out))


# --------------------------------------------------------------------------
# TCPStore


class TCPStore:
    """Reference parity: paddle.distributed's TCPStore rendezvous KV.

    is_master=True starts the in-process master daemon; every instance is
    also a client. Values are bytes; `add` is an atomic int64 counter —
    the primitive barrier/rendezvous building block.
    """

    _lib = None

    @classmethod
    def lib(cls):
        if cls._lib is None:
            lib = _load("tcp_store.cpp", "libpd_store.so")
            lib.pd_store_server_start.restype = ctypes.c_void_p
            lib.pd_store_server_start.argtypes = [ctypes.c_int]
            lib.pd_store_server_stop.argtypes = [ctypes.c_void_p]
            lib.pd_store_client_new.restype = ctypes.c_void_p
            lib.pd_store_client_new.argtypes = [ctypes.c_char_p,
                                                ctypes.c_int]
            lib.pd_store_client_free.argtypes = [ctypes.c_void_p]
            lib.pd_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_char_p, ctypes.c_int]
            lib.pd_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.pd_store_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.pd_store_keys.argtypes = [ctypes.c_void_p]
            lib.pd_store_keys_prefix.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_char_p]
            lib.pd_store_fetch.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p, ctypes.c_int]
            lib.pd_store_delete.argtypes = [ctypes.c_void_p,
                                            ctypes.c_char_p]
            lib.pd_store_add.restype = ctypes.c_longlong
            lib.pd_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_longlong]
            cls._lib = lib
        return cls._lib

    def __init__(self, host="127.0.0.1", port=23457, is_master=False,
                 world_size=1, timeout=None):
        lib = self.lib()
        self._server = None
        if is_master:
            self._server = lib.pd_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore master failed to bind :{port}")
        # Non-master workers may race the master's bind: retry until the
        # timeout (reference TCPStore clients block on connect the same way).
        deadline = _time.monotonic() + (120.0 if timeout is None
                                        else timeout)
        self._client = lib.pd_store_client_new(host.encode(), port)
        while not self._client and _time.monotonic() < deadline:
            _time.sleep(0.1)
            self._client = lib.pd_store_client_new(host.encode(), port)
        if not self._client:
            if self._server:
                lib.pd_store_server_stop(self._server)
            raise RuntimeError(f"TCPStore cannot connect {host}:{port}")

    def set(self, key: str, value: bytes):
        if isinstance(value, str):
            value = value.encode()
        rc = self.lib().pd_store_set(self._client, key.encode(), value,
                                     len(value))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def _fetch(self, n: int) -> bytes:
        buf = ctypes.create_string_buffer(n)
        self.lib().pd_store_fetch(self._client, buf, n)
        return buf.raw[:n]

    def get(self, key: str) -> bytes:
        n = self.lib().pd_store_get(self._client, key.encode())
        if n == -1:
            raise KeyError(key)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return self._fetch(n)

    def wait(self, key: str) -> bytes:
        n = self.lib().pd_store_wait(self._client, key.encode())
        if n < 0:
            raise RuntimeError("TCPStore.wait failed")
        return self._fetch(n)

    def add(self, key: str, delta: int = 1) -> int:
        return int(self.lib().pd_store_add(self._client, key.encode(),
                                           delta))

    def delete(self, key: str):
        self.lib().pd_store_delete(self._client, key.encode())

    def keys(self, prefix: str = ""):
        """List keys; `prefix` filters SERVER-side (the elastic
        heartbeat scan stays O(matching keys), not O(total store))."""
        if prefix:
            n = self.lib().pd_store_keys_prefix(self._client,
                                                prefix.encode())
        else:
            n = self.lib().pd_store_keys(self._client)
        if n < 0:
            raise RuntimeError("TCPStore.keys failed")
        raw = self._fetch(n).decode()
        return [k for k in raw.split("\n") if k]

    def close(self):
        lib = self.lib()
        if self._client:
            lib.pd_store_client_free(self._client)
            self._client = None
        if self._server:
            lib.pd_store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# Token loader


class TokenLoader:
    """C++ mmap+prefetch reader of flat token binaries → [B, S+1] int32
    batches (LLM pretraining input pipeline; see data_loader.cpp)."""

    _lib = None

    @classmethod
    def lib(cls):
        if cls._lib is None:
            lib = _load("data_loader.cpp", "libpd_loader.so")
            lib.pd_loader_new.restype = ctypes.c_void_p
            lib.pd_loader_new.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_int, ctypes.c_int, ctypes.c_ulonglong,
                ctypes.c_int]
            lib.pd_loader_num_windows.restype = ctypes.c_longlong
            lib.pd_loader_num_windows.argtypes = [ctypes.c_void_p]
            lib.pd_loader_next.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(dtype=np.int32, flags="C")]
            lib.pd_loader_free.argtypes = [ctypes.c_void_p]
            cls._lib = lib
        return cls._lib

    def __init__(self, path, seq_len, batch_size, num_workers=2,
                 prefetch=4, seed=0, dtype="uint16"):
        dtype_size = np.dtype(dtype).itemsize
        self.seq_len = int(seq_len)
        self.batch_size = int(batch_size)
        self._h = self.lib().pd_loader_new(
            str(path).encode(), seq_len, batch_size, num_workers, prefetch,
            seed, dtype_size)
        if not self._h:
            raise RuntimeError(f"TokenLoader cannot open {path}")

    @property
    def num_windows(self):
        return int(self.lib().pd_loader_num_windows(self._h))

    def next(self):
        out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        rc = self.lib().pd_loader_next(self._h, out)
        if rc != 0:
            raise StopIteration
        return out

    def __iter__(self):
        while True:
            yield self.next()

    def close(self):
        if self._h:
            self.lib().pd_loader_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# PJRT C++ inference runtime (native/pjrt_loader.cpp)


def _pjrt_include_dir():
    """The PJRT C API header ships with the tensorflow wheel in this
    image; the loader only needs pjrt_c_api.h (self-contained C)."""
    import glob
    import sysconfig
    for pat in [
        os.path.join(sysconfig.get_paths()["purelib"],
                     "tensorflow", "include"),
        "/opt/venv/lib/python3.12/site-packages/tensorflow/include",
    ]:
        for d in glob.glob(pat):
            if os.path.exists(os.path.join(d, "xla", "pjrt", "c",
                                           "pjrt_c_api.h")):
                return d
    raise RuntimeError("pjrt_c_api.h not found (tensorflow include dir)")


def _build_pjrt(binary=False):
    flags = ["-I", _pjrt_include_dir(), "-ldl"]
    if binary:
        flags.append("-DPD_PJRT_MAIN")
    return _build("pjrt_loader.cpp",
                  "pd_infer" if binary else "libpd_pjrt.so",
                  shared=not binary, extra_flags=flags)


def pd_infer_binary():
    """Build (if needed) and return the path of the pd_infer CLI."""
    return _build_pjrt(binary=True)


# dtype → code shared by the manifest writer (jit/save_load.py), the
# ctypes runner below, and the C++ enum switch in pjrt_loader.cpp.
PJRT_DTYPE_CODES = {"float32": 0, "bfloat16": 1, "int32": 2, "float16": 3,
                    "float64": 4, "int64": 5, "bool": 6, "int8": 7,
                    "uint8": 8}


class PjrtRunner:
    """C++ PJRT inference session (reference parity: the C++ side of
    jit.save/load + AnalysisPredictor; SURVEY.md §2.1 "C++ JIT").

    Compiles StableHLO bytecode on a PJRT plugin and executes it without
    jax in the loop — the same native runtime the `pd_infer` CLI uses.
    """

    _lib = None

    @classmethod
    def lib(cls):
        if cls._lib is None:
            lib = ctypes.CDLL(_build_pjrt())
            lib.pd_pjrt_create.restype = ctypes.c_void_p
            lib.pd_pjrt_create.argtypes = [ctypes.c_char_p,
                                           ctypes.c_char_p]
            lib.pd_pjrt_destroy.argtypes = [ctypes.c_void_p]
            lib.pd_pjrt_last_error.restype = ctypes.c_char_p
            lib.pd_pjrt_last_error.argtypes = [ctypes.c_void_p]
            lib.pd_pjrt_compile.restype = ctypes.c_void_p
            lib.pd_pjrt_compile.argtypes = [ctypes.c_void_p,
                                            ctypes.c_char_p,
                                            ctypes.c_size_t]
            lib.pd_pjrt_num_outputs.restype = ctypes.c_size_t
            lib.pd_pjrt_num_outputs.argtypes = [ctypes.c_void_p]
            lib.pd_pjrt_execute.restype = ctypes.c_void_p
            lib.pd_pjrt_execute.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_void_p)]
            lib.pd_pjrt_output_size.restype = ctypes.c_int64
            lib.pd_pjrt_output_size.argtypes = [ctypes.c_void_p,
                                                ctypes.c_size_t]
            lib.pd_pjrt_output_copy.restype = ctypes.c_int
            lib.pd_pjrt_output_copy.argtypes = [ctypes.c_void_p,
                                                ctypes.c_size_t,
                                                ctypes.c_void_p,
                                                ctypes.c_size_t]
            lib.pd_pjrt_result_destroy.argtypes = [ctypes.c_void_p]
            lib.pd_pjrt_exec_destroy.argtypes = [ctypes.c_void_p]
            cls._lib = lib
        return cls._lib

    def __init__(self, plugin_path, options=None):
        """options: dict of plugin create options (ints or strings) —
        e.g. the axon TPU plugin needs remote_compile/topology/
        session_id (see default_axon_options())."""
        spec = None
        if options:
            spec = ";".join(f"{k}={v}" for k, v in options.items()).encode()
        self._ctx = self.lib().pd_pjrt_create(str(plugin_path).encode(),
                                              spec)
        if not self._ctx:
            raise RuntimeError(f"PJRT plugin init failed: {plugin_path}")
        self._exec = None

    @staticmethod
    def default_axon_options(topology="v5e:1x1x1"):
        import uuid
        return {"remote_compile": 1, "local_only": 0, "priority": 0,
                "topology": topology, "n_slices": 1,
                "session_id": str(uuid.uuid4())}

    def _err(self):
        return self.lib().pd_pjrt_last_error(self._ctx).decode()

    def compile(self, stablehlo_bytes: bytes):
        e = self.lib().pd_pjrt_compile(self._ctx, stablehlo_bytes,
                                       len(stablehlo_bytes))
        if not e:
            raise RuntimeError(f"PJRT compile failed: {self._err()}")
        self._exec = e
        return self

    def run(self, arrays):
        """Execute with host numpy arrays; returns list of raw byte
        buffers (one per output — caller reshapes/casts)."""
        assert self._exec, "compile() first"
        lib = self.lib()
        n = len(arrays)
        arrays = [np.ascontiguousarray(a) for a in arrays]
        dtypes = (ctypes.c_int * n)(*[
            PJRT_DTYPE_CODES[str(a.dtype)] for a in arrays])
        ranks = (ctypes.c_int * n)(*[a.ndim for a in arrays])
        dims_flat = []
        for a in arrays:
            dims_flat += list(a.shape)
        dims = (ctypes.c_int64 * len(dims_flat))(*dims_flat)
        ptrs = (ctypes.c_void_p * n)(*[
            a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
        res = lib.pd_pjrt_execute(self._exec, n, dtypes, ranks, dims, ptrs)
        if not res:
            raise RuntimeError(f"PJRT execute failed: {self._err()}")
        outs = []
        try:
            for i in range(lib.pd_pjrt_num_outputs(self._exec)):
                sz = lib.pd_pjrt_output_size(res, i)
                if sz < 0:
                    raise RuntimeError(self._err())
                buf = ctypes.create_string_buffer(int(sz))
                if lib.pd_pjrt_output_copy(res, i, buf, int(sz)) != 0:
                    raise RuntimeError(self._err())
                outs.append(bytes(buf.raw))
        finally:
            lib.pd_pjrt_result_destroy(res)
        return outs

    def close(self):
        if self._exec:
            self.lib().pd_pjrt_exec_destroy(self._exec)
            self._exec = None
        if self._ctx:
            self.lib().pd_pjrt_destroy(self._ctx)
            self._ctx = None
