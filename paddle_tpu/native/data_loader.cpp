// Prefetching token-dataset reader.
//
// Reference parity: the DataLoader's native worker/prefetch machinery
// (upstream C++ reader ops + multiprocess workers — see SURVEY.md §2.2
// "Data"). TPU-native redesign: LLM pretraining reads fixed-length token
// windows from a flat binary token file; this module mmaps the file and
// runs a worker-thread pipeline that materializes [batch, seq_len+1]
// int32 batches into a bounded ring buffer so the accelerator never waits
// on host IO.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread data_loader.cpp -o libpd_loader.so

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<int32_t> data;
};

class TokenLoader {
 public:
  TokenLoader(const char* path, int64_t seq_len, int64_t batch_size,
              int n_workers, int queue_cap, uint64_t seed, int dtype_size)
      : seq_len_(seq_len),
        batch_size_(batch_size),
        cap_(queue_cap),
        dtype_size_(dtype_size) {
    fd_ = ::open(path, O_RDONLY);
    if (fd_ < 0) return;
    struct stat st {};
    ::fstat(fd_, &st);
    bytes_ = static_cast<size_t>(st.st_size);
    base_ = static_cast<const uint8_t*>(
        ::mmap(nullptr, bytes_, PROT_READ, MAP_PRIVATE, fd_, 0));
    if (base_ == MAP_FAILED) {
      base_ = nullptr;
      return;
    }
    ::madvise(const_cast<uint8_t*>(base_), bytes_, MADV_SEQUENTIAL);
    n_tokens_ = static_cast<int64_t>(bytes_ / dtype_size_);
    n_windows_ = n_tokens_ / (seq_len_ + 1);
    running_.store(true);
    rng_.seed(seed);
    for (int i = 0; i < n_workers; ++i)
      workers_.emplace_back([this] { worker_loop(); });
  }

  bool ok() const { return base_ != nullptr && n_windows_ > 0; }
  int64_t num_windows() const { return n_windows_; }

  // Blocks until a batch is ready; copies into out[batch, seq_len+1] i32.
  bool next(int32_t* out) {
    std::unique_lock<std::mutex> g(mu_);
    cv_pop_.wait(g, [&] { return !queue_.empty() || !running_.load(); });
    if (queue_.empty()) return false;
    Batch b = std::move(queue_.front());
    queue_.pop_front();
    g.unlock();
    cv_push_.notify_one();
    std::memcpy(out, b.data.data(), b.data.size() * sizeof(int32_t));
    return true;
  }

  void stop() {
    running_.store(false);
    cv_push_.notify_all();
    cv_pop_.notify_all();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
  }

  ~TokenLoader() {
    stop();
    if (base_) ::munmap(const_cast<uint8_t*>(base_), bytes_);
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  int64_t draw_window() {
    std::lock_guard<std::mutex> g(rng_mu_);
    return static_cast<int64_t>(rng_() % static_cast<uint64_t>(n_windows_));
  }

  int32_t token_at(int64_t idx) const {
    const uint8_t* p = base_ + idx * dtype_size_;
    switch (dtype_size_) {
      case 2: {
        uint16_t v;
        std::memcpy(&v, p, 2);
        return static_cast<int32_t>(v);
      }
      case 4: {
        int32_t v;
        std::memcpy(&v, p, 4);
        return v;
      }
      default: {
        return static_cast<int32_t>(*p);
      }
    }
  }

  void worker_loop() {
    const int64_t window = seq_len_ + 1;
    while (running_.load()) {
      Batch b;
      b.data.resize(batch_size_ * window);
      for (int64_t i = 0; i < batch_size_; ++i) {
        int64_t w = draw_window();
        int64_t start = w * window;
        for (int64_t t = 0; t < window; ++t)
          b.data[i * window + t] = token_at(start + t);
      }
      std::unique_lock<std::mutex> g(mu_);
      cv_push_.wait(g, [&] {
        return queue_.size() < static_cast<size_t>(cap_) ||
               !running_.load();
      });
      if (!running_.load()) return;
      queue_.push_back(std::move(b));
      g.unlock();
      cv_pop_.notify_one();
    }
  }

  int64_t seq_len_, batch_size_, cap_;
  int dtype_size_;
  int fd_ = -1;
  size_t bytes_ = 0;
  const uint8_t* base_ = nullptr;
  int64_t n_tokens_ = 0, n_windows_ = 0;
  std::atomic<bool> running_{false};
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_push_, cv_pop_;
  std::deque<Batch> queue_;
  std::mt19937_64 rng_;
  std::mutex rng_mu_;
};

}  // namespace

extern "C" {

void* pd_loader_new(const char* path, long long seq_len,
                    long long batch_size, int n_workers, int queue_cap,
                    unsigned long long seed, int dtype_size) {
  auto* l = new TokenLoader(path, seq_len, batch_size, n_workers,
                            queue_cap, seed, dtype_size);
  if (!l->ok()) {
    delete l;
    return nullptr;
  }
  return l;
}

long long pd_loader_num_windows(void* h) {
  return static_cast<TokenLoader*>(h)->num_windows();
}

int pd_loader_next(void* h, int32_t* out) {
  return static_cast<TokenLoader*>(h)->next(out) ? 0 : -1;
}

void pd_loader_free(void* h) { delete static_cast<TokenLoader*>(h); }

}  // extern "C"
