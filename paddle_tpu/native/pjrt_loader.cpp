// C++ inference runtime: loads a StableHLO artifact produced by
// paddle_tpu.jit.save and executes it on any PJRT plugin (TPU via the
// axon plugin, or any other PJRT .so).
//
// Reference parity: the C++ deployment pair — paddle/fluid/jit/ (C++
// loader for jit.save'd functions) and the AnalysisPredictor C++ API
// (paddle/fluid/inference/) — upstream locations unverified, see
// SURVEY.md §2.1 "C++ JIT" / "Inference engine".
//
// TPU-native design: the portable program format is StableHLO bytecode
// (what jax.export produces) and the portable runtime ABI is the PJRT C
// API — the same plugin interface JAX itself sits on. This file is a
// dependency-free PJRT C-API client (~no XLA build needed): dlopen the
// plugin, GetPjrtApi(), compile the module, move host buffers in, run,
// move results out. Exposed two ways:
//   - C ABI (pd_pjrt_*) consumed by ctypes (paddle_tpu.native.PjrtRunner)
//   - a CLI (build with -DPD_PJRT_MAIN) for pure-C++ deployment:
//       pd_infer <plugin.so> <artifact_prefix> [out_dir [in0.bin ...]]
//
// Compile options: PJRT_Client_Compile wants a serialized
// xla.CompileOptionsProto. We hand-encode the minimal message
// (num_replicas=1, num_partitions=1) with a 10-line protobuf writer
// rather than pulling in protobuf — the schema is stable and tiny.

#include <dlfcn.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <memory>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

struct Ctx {
  void* dso = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;
  PJRT_Device* device = nullptr;  // first addressable device
  std::string last_error;
};

struct Exec {
  Ctx* ctx = nullptr;
  PJRT_LoadedExecutable* le = nullptr;
  size_t num_outputs = 0;
};

struct Result {
  Ctx* ctx = nullptr;
  std::vector<PJRT_Buffer*> bufs;
};

std::string take_error(const PJRT_Api* api, PJRT_Error* err) {
  if (!err) return "";
  PJRT_Error_Message_Args margs;
  memset(&margs, 0, sizeof(margs));
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.error = err;
  api->PJRT_Error_Message(&margs);
  std::string msg(margs.message, margs.message_size);
  PJRT_Error_Destroy_Args dargs;
  memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.error = err;
  api->PJRT_Error_Destroy(&dargs);
  return msg;
}

#define CHECK_PJRT(ctx, call)                      \
  do {                                             \
    PJRT_Error* _e = (call);                       \
    if (_e) {                                      \
      (ctx)->last_error = take_error((ctx)->api, _e); \
      return nullptr;                              \
    }                                              \
  } while (0)

bool await_event(Ctx* c, PJRT_Event* ev) {
  if (!ev) return true;
  PJRT_Event_Await_Args aargs;
  memset(&aargs, 0, sizeof(aargs));
  aargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  aargs.event = ev;
  PJRT_Error* err = c->api->PJRT_Event_Await(&aargs);
  if (err) c->last_error = take_error(c->api, err);
  PJRT_Event_Destroy_Args dargs;
  memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  dargs.event = ev;
  c->api->PJRT_Event_Destroy(&dargs);
  return !err;
}

// -- minimal protobuf writer for xla.CompileOptionsProto ---------------------
// Field numbers verified against jax's own CompileOptions serialization
// (decoded in-session): CompileOptionsProto.executable_build_options is
// field 3; ExecutableBuildOptionsProto.num_replicas/num_partitions are
// fields 4/5 (varint).
void pb_varint(std::string& s, uint64_t v) {
  while (v >= 0x80) { s.push_back(char(v | 0x80)); v >>= 7; }
  s.push_back(char(v));
}
void pb_tag(std::string& s, int field, int wire) {
  pb_varint(s, uint64_t(field) << 3 | wire);
}
std::string compile_options_proto() {
  std::string ebo;
  pb_tag(ebo, 4, 0); pb_varint(ebo, 1);  // num_replicas = 1
  pb_tag(ebo, 5, 0); pb_varint(ebo, 1);  // num_partitions = 1
  std::string co;
  pb_tag(co, 3, 2);  // executable_build_options, length-delimited
  pb_varint(co, ebo.size());
  co += ebo;
  return co;
}

PJRT_Buffer_Type dtype_code(int code) {
  switch (code) {
    case 0: return PJRT_Buffer_Type_F32;
    case 1: return PJRT_Buffer_Type_BF16;
    case 2: return PJRT_Buffer_Type_S32;
    case 3: return PJRT_Buffer_Type_F16;
    case 4: return PJRT_Buffer_Type_F64;
    case 5: return PJRT_Buffer_Type_S64;
    case 6: return PJRT_Buffer_Type_PRED;
    case 7: return PJRT_Buffer_Type_S8;
    case 8: return PJRT_Buffer_Type_U8;
    default: return PJRT_Buffer_Type_INVALID;
  }
}

}  // namespace

namespace {

// "k=v;k=v" option string → NamedValues. Values of all digits become
// kInt64, everything else kString (matches what plugins expect from
// jax's register_plugin options dict).
struct ParsedOptions {
  std::vector<std::string> keys, svals;
  std::vector<int64_t> ivals;
  std::vector<bool> is_int;
  std::vector<PJRT_NamedValue> nv;

  explicit ParsedOptions(const char* spec) {
    if (!spec) return;
    std::string s(spec);
    size_t pos = 0;
    while (pos < s.size()) {
      size_t semi = s.find(';', pos);
      if (semi == std::string::npos) semi = s.size();
      std::string kv = s.substr(pos, semi - pos);
      pos = semi + 1;
      size_t eq = kv.find('=');
      if (eq == std::string::npos) continue;
      keys.push_back(kv.substr(0, eq));
      std::string v = kv.substr(eq + 1);
      bool digits = !v.empty();
      for (size_t ci = 0; ci < v.size(); ++ci) {
        char ch = v[ci];
        if (!(ch >= '0' && ch <= '9') && !(ch == '-' && ci == 0))
          digits = false;
      }
      if (v == "-") digits = false;
      is_int.push_back(digits);
      svals.push_back(v);
      ivals.push_back(digits ? strtoll(v.c_str(), nullptr, 10) : 0);
    }
    nv.resize(keys.size());
    for (size_t i = 0; i < keys.size(); ++i) {
      memset(&nv[i], 0, sizeof(nv[i]));
      nv[i].struct_size = PJRT_NamedValue_STRUCT_SIZE;
      nv[i].name = keys[i].c_str();
      nv[i].name_size = keys[i].size();
      if (is_int[i]) {
        nv[i].type = PJRT_NamedValue_kInt64;
        nv[i].int64_value = ivals[i];
        nv[i].value_size = 1;
      } else {
        nv[i].type = PJRT_NamedValue_kString;
        nv[i].string_value = svals[i].c_str();
        nv[i].value_size = svals[i].size();
      }
    }
  }
};

}  // namespace

extern "C" {

// -- lifecycle ---------------------------------------------------------------

// options: "key=value;key=value" (int-looking values become kInt64).
// nullptr/"" = no options. E.g. for the axon TPU plugin:
//   "remote_compile=1;local_only=0;priority=0;topology=v5e:1x1x1;"
//   "n_slices=1;session_id=<uuid>"
void* pd_pjrt_create(const char* plugin_path, const char* options) {
  auto* c = new Ctx();
  ParsedOptions popts(options);
  c->dso = dlopen(plugin_path, RTLD_NOW | RTLD_LOCAL);
  if (!c->dso) {
    fprintf(stderr, "pd_pjrt: dlopen(%s): %s\n", plugin_path, dlerror());
    delete c;
    return nullptr;
  }
  using GetApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetApiFn>(dlsym(c->dso, "GetPjrtApi"));
  if (!get_api) {
    fprintf(stderr, "pd_pjrt: no GetPjrtApi in %s\n", plugin_path);
    dlclose(c->dso);
    delete c;
    return nullptr;
  }
  c->api = get_api();

  PJRT_Plugin_Initialize_Args iargs;
  memset(&iargs, 0, sizeof(iargs));
  iargs.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  if (PJRT_Error* e = c->api->PJRT_Plugin_Initialize(&iargs)) {
    fprintf(stderr, "pd_pjrt: plugin init: %s\n",
            take_error(c->api, e).c_str());
    delete c;
    return nullptr;
  }

  PJRT_Client_Create_Args cargs;
  memset(&cargs, 0, sizeof(cargs));
  cargs.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  cargs.create_options = popts.nv.empty() ? nullptr : popts.nv.data();
  cargs.num_options = popts.nv.size();
  if (PJRT_Error* e = c->api->PJRT_Client_Create(&cargs)) {
    fprintf(stderr, "pd_pjrt: client create: %s\n",
            take_error(c->api, e).c_str());
    delete c;
    return nullptr;
  }
  c->client = cargs.client;

  PJRT_Client_AddressableDevices_Args dargs;
  memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  dargs.client = c->client;
  if (PJRT_Error* e = c->api->PJRT_Client_AddressableDevices(&dargs)) {
    fprintf(stderr, "pd_pjrt: devices: %s\n", take_error(c->api, e).c_str());
    delete c;
    return nullptr;
  }
  if (dargs.num_addressable_devices == 0) {
    fprintf(stderr, "pd_pjrt: no addressable devices\n");
    delete c;
    return nullptr;
  }
  c->device = dargs.addressable_devices[0];
  return c;
}

const char* pd_pjrt_last_error(void* ctx) {
  return static_cast<Ctx*>(ctx)->last_error.c_str();
}

void pd_pjrt_destroy(void* ctx) {
  auto* c = static_cast<Ctx*>(ctx);
  if (c->client) {
    PJRT_Client_Destroy_Args args;
    memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
    args.client = c->client;
    c->api->PJRT_Client_Destroy(&args);
  }
  // NOTE: not dlclosing — TPU plugins register global state.
  delete c;
}

// -- compile ------------------------------------------------------------------

void* pd_pjrt_compile(void* ctx, const char* code, size_t code_size) {
  auto* c = static_cast<Ctx*>(ctx);
  PJRT_Program prog;
  memset(&prog, 0, sizeof(prog));
  prog.struct_size = PJRT_Program_STRUCT_SIZE;
  prog.code = const_cast<char*>(code);
  prog.code_size = code_size;
  static const char kFormat[] = "mlir";
  prog.format = kFormat;
  prog.format_size = sizeof(kFormat) - 1;

  std::string opts = compile_options_proto();
  PJRT_Client_Compile_Args cargs;
  memset(&cargs, 0, sizeof(cargs));
  cargs.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  cargs.client = c->client;
  cargs.program = &prog;
  cargs.compile_options = opts.data();
  cargs.compile_options_size = opts.size();
  CHECK_PJRT(c, c->api->PJRT_Client_Compile(&cargs));

  auto* e = new Exec();
  e->ctx = c;
  e->le = cargs.executable;

  // number of outputs, via the underlying PJRT_Executable
  PJRT_LoadedExecutable_GetExecutable_Args gargs;
  memset(&gargs, 0, sizeof(gargs));
  gargs.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
  gargs.loaded_executable = e->le;
  if (PJRT_Error* err = c->api->PJRT_LoadedExecutable_GetExecutable(&gargs)) {
    c->last_error = take_error(c->api, err);
    delete e;
    return nullptr;
  }
  PJRT_Executable_NumOutputs_Args nargs;
  memset(&nargs, 0, sizeof(nargs));
  nargs.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
  nargs.executable = gargs.executable;
  if (PJRT_Error* err = c->api->PJRT_Executable_NumOutputs(&nargs)) {
    c->last_error = take_error(c->api, err);
    delete e;
    return nullptr;
  }
  e->num_outputs = nargs.num_outputs;
  return e;
}

size_t pd_pjrt_num_outputs(void* exec) {
  return static_cast<Exec*>(exec)->num_outputs;
}

// -- execute ------------------------------------------------------------------

// dtypes: per-arg code (see dtype_code); dims_flat: concatenated dims,
// ranks[i] entries each; data: host pointers (dense, major-to-minor).
void* pd_pjrt_execute(void* exec, size_t n_args, const int* dtypes,
                      const int* ranks, const int64_t* dims_flat,
                      const void* const* data) {
  auto* e = static_cast<Exec*>(exec);
  Ctx* c = e->ctx;

  std::vector<PJRT_Buffer*> in_bufs(n_args, nullptr);
  size_t off = 0;
  for (size_t i = 0; i < n_args; ++i) {
    PJRT_Client_BufferFromHostBuffer_Args bargs;
    memset(&bargs, 0, sizeof(bargs));
    bargs.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    bargs.client = c->client;
    bargs.data = data[i];
    bargs.type = dtype_code(dtypes[i]);
    bargs.dims = dims_flat + off;
    bargs.num_dims = size_t(ranks[i]);
    off += size_t(ranks[i]);
    bargs.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableOnlyDuringCall;
    bargs.device = c->device;
    if (PJRT_Error* err = c->api->PJRT_Client_BufferFromHostBuffer(&bargs)) {
      c->last_error = take_error(c->api, err);
      return nullptr;
    }
    if (!await_event(c, bargs.done_with_host_buffer)) return nullptr;
    in_bufs[i] = bargs.buffer;
  }

  PJRT_ExecuteOptions opts;
  memset(&opts, 0, sizeof(opts));
  opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

  std::vector<PJRT_Buffer*> outs(e->num_outputs, nullptr);
  PJRT_Buffer* const* arg_list = in_bufs.data();
  PJRT_Buffer** out_list = outs.data();
  PJRT_Event* done = nullptr;

  PJRT_LoadedExecutable_Execute_Args xargs;
  memset(&xargs, 0, sizeof(xargs));
  xargs.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
  xargs.executable = e->le;
  xargs.options = &opts;
  xargs.argument_lists = &arg_list;
  xargs.num_devices = 1;
  xargs.num_args = n_args;
  xargs.output_lists = &out_list;
  xargs.device_complete_events = &done;
  PJRT_Error* err = c->api->PJRT_LoadedExecutable_Execute(&xargs);
  if (err) {
    c->last_error = take_error(c->api, err);
    return nullptr;
  }
  if (!await_event(c, done)) return nullptr;

  for (PJRT_Buffer* b : in_bufs) {
    PJRT_Buffer_Destroy_Args dargs;
    memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
    dargs.buffer = b;
    c->api->PJRT_Buffer_Destroy(&dargs);
  }

  auto* r = new Result();
  r->ctx = c;
  r->bufs = std::move(outs);
  return r;
}

int64_t pd_pjrt_output_size(void* result, size_t i) {
  auto* r = static_cast<Result*>(result);
  Ctx* c = r->ctx;
  PJRT_Buffer_ToHostBuffer_Args args;
  memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
  args.src = r->bufs[i];
  args.dst = nullptr;  // size query
  if (PJRT_Error* err = c->api->PJRT_Buffer_ToHostBuffer(&args)) {
    c->last_error = take_error(c->api, err);
    return -1;
  }
  return int64_t(args.dst_size);
}

int pd_pjrt_output_copy(void* result, size_t i, void* dst, size_t dst_size) {
  auto* r = static_cast<Result*>(result);
  Ctx* c = r->ctx;
  PJRT_Buffer_ToHostBuffer_Args args;
  memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
  args.src = r->bufs[i];
  args.dst = dst;
  args.dst_size = dst_size;
  if (PJRT_Error* err = c->api->PJRT_Buffer_ToHostBuffer(&args)) {
    c->last_error = take_error(c->api, err);
    return -1;
  }
  return await_event(c, args.event) ? 0 : -1;
}

void pd_pjrt_result_destroy(void* result) {
  auto* r = static_cast<Result*>(result);
  for (PJRT_Buffer* b : r->bufs) {
    PJRT_Buffer_Destroy_Args dargs;
    memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
    dargs.buffer = b;
    r->ctx->api->PJRT_Buffer_Destroy(&dargs);
  }
  delete r;
}

void pd_pjrt_exec_destroy(void* exec) {
  auto* e = static_cast<Exec*>(exec);
  PJRT_LoadedExecutable_Destroy_Args args;
  memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
  args.executable = e->le;
  e->ctx->api->PJRT_LoadedExecutable_Destroy(&args);
  delete e;
}

}  // extern "C"

// -- CLI ----------------------------------------------------------------------
// pd_infer <plugin.so> <artifact_prefix> [out_dir]
// Reads <prefix>.mlir (StableHLO bytecode), <prefix>.pdpjrt.txt (arg
// manifest) and <prefix>.pdparams.bin (param blob); writes out_<i>.bin.
#ifdef PD_PJRT_MAIN

static std::string read_file(const std::string& p) {
  FILE* f = fopen(p.c_str(), "rb");
  if (!f) { fprintf(stderr, "pd_infer: cannot open %s\n", p.c_str()); exit(2); }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string s(size_t(n), '\0');
  if (fread(s.data(), 1, size_t(n), f) != size_t(n)) exit(2);
  fclose(f);
  return s;
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: pd_infer <plugin.so> <artifact_prefix> [out_dir]\n");
    return 2;
  }
  std::string prefix = argv[2];
  std::string out_dir = argc > 3 ? argv[3] : ".";
  std::string code = read_file(prefix + ".mlir");
  std::string params = read_file(prefix + ".pdparams.bin");
  std::string manifest = read_file(prefix + ".pdpjrt.txt");

  // manifest lines: "arg <dtype_code> <rank> <d0> ... <param|input> <offset>"
  std::vector<int> dtypes, ranks;
  std::vector<int64_t> dims;
  std::vector<const void*> data;
  std::vector<std::string> input_files;
  char* save = nullptr;
  std::string m = manifest;
  for (char* line = strtok_r(m.data(), "\n", &save); line;
       line = strtok_r(nullptr, "\n", &save)) {
    char kind[16], src[16];
    int dt, rank;
    int consumed;
    if (sscanf(line, "%15s %d %d%n", kind, &dt, &rank, &consumed) != 3)
      continue;
    if (strcmp(kind, "arg") != 0) continue;
    dtypes.push_back(dt);
    ranks.push_back(rank);
    const char* p = line + consumed;
    for (int d = 0; d < rank; ++d) {
      long long v;
      int used;
      sscanf(p, " %lld%n", &v, &used);
      dims.push_back(v);
      p += used;
    }
    long long off;
    sscanf(p, " %15s %lld", src, &off);
    if (strcmp(src, "param") == 0) {
      data.push_back(params.data() + off);
    } else {
      data.push_back(nullptr);  // filled from input files below
      input_files.push_back("");
    }
  }
  // remaining argv entries are input .bin files, in manifest order
  size_t next_in = 0;
  std::vector<std::string> in_blobs;
  for (size_t i = 0; i < data.size(); ++i) {
    if (data[i] != nullptr) continue;
    int ai = 4 + int(next_in);  // argv: 0 prog, 1 plugin, 2 prefix, 3 outdir
    if (ai >= argc) {
      fprintf(stderr, "pd_infer: missing input file %zu\n", next_in);
      return 2;
    }
    in_blobs.push_back(read_file(argv[ai]));
    ++next_in;
  }
  next_in = 0;
  for (size_t i = 0; i < data.size(); ++i)
    if (data[i] == nullptr) data[i] = in_blobs[next_in++].data();

  // plugin options from PD_PJRT_OPTIONS ("k=v;k=v")
  void* ctx = pd_pjrt_create(argv[1], getenv("PD_PJRT_OPTIONS"));
  if (!ctx) return 1;
  void* exec = pd_pjrt_compile(ctx, code.data(), code.size());
  if (!exec) {
    fprintf(stderr, "pd_infer: compile: %s\n", pd_pjrt_last_error(ctx));
    return 1;
  }
  void* res = pd_pjrt_execute(exec, data.size(), dtypes.data(), ranks.data(),
                              dims.data(), data.data());
  if (!res) {
    fprintf(stderr, "pd_infer: execute: %s\n", pd_pjrt_last_error(ctx));
    return 1;
  }
  size_t nout = pd_pjrt_num_outputs(exec);
  for (size_t i = 0; i < nout; ++i) {
    int64_t sz = pd_pjrt_output_size(res, i);
    if (sz < 0) return 1;
    std::string buf(size_t(sz), '\0');
    if (pd_pjrt_output_copy(res, i, buf.data(), size_t(sz)) != 0) return 1;
    std::string path = out_dir + "/out_" + std::to_string(i) + ".bin";
    FILE* f = fopen(path.c_str(), "wb");
    fwrite(buf.data(), 1, buf.size(), f);
    fclose(f);
    printf("out_%zu %lld bytes -> %s\n", i, (long long)sz, path.c_str());
  }
  pd_pjrt_result_destroy(res);
  pd_pjrt_exec_destroy(exec);
  pd_pjrt_destroy(ctx);
  return 0;
}
#endif  // PD_PJRT_MAIN
