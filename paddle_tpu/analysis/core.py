"""graftlint core: the rule-based static-analysis framework.

CLAUDE.md's hard-won architecture invariants (the single-chokepoint
autograd rule, the round-11 thread-local grad-mode incident, the Mosaic
compile hazards, the HTTP-413 jit-constant-capture class, the round-3
dist_spec passthrough, incident #3's kill-on-timeout rule, the serving
engine lock discipline, and the env-knob registry) exist as prose that a
future builder may not read.  This package turns each of them into an
enforced AST check — the Paddle-reference idea of framework
self-policing (op-registry checks, static-graph pass validators) applied
to this repo's own source tree.

Deliberately jax-free: `tools/lint.py` loads this package without
executing `paddle_tpu/__init__` (the axon sitecustomize makes a bare jax
import hazardous on a dead tunnel), so nothing here may import jax or
any sibling paddle_tpu subpackage.

Concepts
--------
- :class:`Rule` — one invariant; ``applies(ctx)`` scopes it by path,
  ``check(ctx)`` yields :class:`Finding`\\ s from the file's AST.
- :class:`FileContext` — parsed file handed to rules: source, lines,
  AST annotated with parent links and decorator markers, plus the
  :class:`Project` for repo-level lookups (the env-knob registry).
- Suppressions — ``# graftlint: disable=<rule>[,<rule>]  (reason)``
  trailing a flagged line (or a standalone comment on the line above).
  ``disable-file=`` in the file head suppresses for the whole file.
  An EMPTY reason is itself a finding (``bad-suppression``): every
  suppression must say why (ISSUE-6 acceptance rule).
- Baseline — a checked-in JSON file of grandfathered findings, matched
  by (rule, path, stripped source line) so plain line-number churn does
  not resurrect them.  Baseline entries also require a non-empty
  ``reason``.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

__all__ = [
    "Finding", "Rule", "FileContext", "Project", "run_paths",
    "run_source", "load_baseline", "save_baseline", "apply_baseline",
    "iter_py_files", "dotted_name", "BAD_SUPPRESSION", "BAD_BASELINE",
]

BAD_SUPPRESSION = "bad-suppression"
BAD_BASELINE = "bad-baseline"

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<whole>-file)?="
    r"(?P<rules>[A-Za-z0-9_,-]+)\s*(?:\((?P<reason>[^)]*)\))?")


@dataclasses.dataclass
class Finding:
    """One rule violation at file:line.  ``snippet`` (the stripped
    source line) is the baseline fingerprint — stable across pure
    line-number churn."""
    rule: str
    path: str
    line: int
    message: str
    snippet: str = ""

    def key(self):
        return (self.rule, self.path, self.snippet)

    def to_json(self):
        return dataclasses.asdict(self)

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Project:
    """Repo-level context shared across files (lazy, cached)."""

    def __init__(self, root):
        self.root = os.path.abspath(root) if root else None
        self._knobs = None

    def knob_registry(self):
        """Set of PADDLE_TPU_* knob names listed in docs/ENV_KNOBS.md
        (first table column).  Empty set when the doc is missing — the
        env-knob rule then flags every knob, which is the honest signal
        to run ``tools/lint.py --gen-knobs``."""
        if self._knobs is None:
            self._knobs = set()
            if self.root:
                doc = os.path.join(self.root, "docs", "ENV_KNOBS.md")
                if os.path.exists(doc):
                    with open(doc, encoding="utf-8") as f:
                        text = f.read()
                    self._knobs = set(
                        re.findall(r"^\|\s*`(PADDLE_TPU_[A-Z0-9_]+)`",
                                   text, re.M))
        return self._knobs


class FileContext:
    """A parsed source file as rules see it."""

    def __init__(self, relpath, source, project=None):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.project = project if project is not None else Project(None)
        self.tree = ast.parse(source)
        self._annotate()

    def _annotate(self):
        """Parent links + decorator-subtree markers, once per file."""
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for dec in node.decorator_list:
                    for sub in ast.walk(dec):
                        sub._gl_in_decorator = True
            for child in ast.iter_child_nodes(node):
                child._gl_parent = node

    def snippet(self, line):
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule, node_or_line, message):
        line = node_or_line if isinstance(node_or_line, int) \
            else getattr(node_or_line, "lineno", 1)
        return Finding(rule=rule, path=self.relpath, line=line,
                       message=message, snippet=self.snippet(line))

    # -- AST helpers shared by the rules -----------------------------------
    def parent(self, node):
        return getattr(node, "_gl_parent", None)

    def ancestors(self, node):
        p = self.parent(node)
        while p is not None:
            yield p
            p = self.parent(p)

    def enclosing_function(self, node):
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def in_decorator(self, node):
        return getattr(node, "_gl_in_decorator", False)

    def functions_by_name(self):
        """Every FunctionDef in the module keyed by name (methods
        included; later defs win — good enough for target resolution)."""
        out = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[node.name] = node
        return out


def dotted_name(node):
    """'jax.lax.fori_loop' for an Attribute/Name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Rule:
    """Base class: subclass with ``id``, ``description`` and ``check``."""

    id = ""
    description = ""

    def applies(self, ctx: FileContext) -> bool:
        return True

    def check(self, ctx: FileContext):
        raise NotImplementedError
        yield  # pragma: no cover


# ---------------------------------------------------------------------------
# Suppressions

def _parse_suppressions(ctx, known_ids):
    """Returns (line -> set(rule_ids), file_wide set, bad findings).

    A trailing comment suppresses its own line; a standalone comment
    line suppresses the NEXT line (so multi-line calls annotate the
    ``pl.BlockSpec(`` line or the line above it).  Real COMMENT tokens
    only — directive-looking text inside string literals (test
    fixtures, docs) is ignored.
    """
    per_line: dict[int, set] = {}
    file_wide: set = set()
    bad = []
    comments = []
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(ctx.source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError):
        pass  # ast.parse succeeded, so this is practically unreachable
    for i, col, comment in comments:
        m = _DISABLE_RE.search(comment)
        if not m:
            continue
        raw = ctx.lines[i - 1] if i <= len(ctx.lines) else ""
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        reason = (m.group("reason") or "").strip()
        if not reason:
            bad.append(ctx.finding(
                BAD_SUPPRESSION, i,
                "graftlint disable without a reason — write "
                "`# graftlint: disable=<rule>  (why this is intended)`"))
        unknown = rules - set(known_ids)
        if unknown:
            bad.append(ctx.finding(
                BAD_SUPPRESSION, i,
                f"graftlint disable names unknown rule(s) "
                f"{sorted(unknown)} — typo? known: {sorted(known_ids)}"))
        if m.group("whole"):
            file_wide |= rules
            continue
        standalone = raw[:col].strip() == ""
        target = i + 1 if standalone else i
        per_line.setdefault(target, set()).update(rules)
        # a standalone disable also covers its own line so a finding
        # anchored to the comment itself (rare) stays suppressible
        if standalone:
            per_line.setdefault(i, set()).update(rules)
    return per_line, file_wide, bad


# ---------------------------------------------------------------------------
# Runner

def check_context(ctx, rules):
    """Run rules over one FileContext, honoring suppressions.  Returns
    (kept findings, suppressed count); bad-suppression findings are
    included in the kept list."""
    known = [r.id for r in rules]
    per_line, file_wide, bad = _parse_suppressions(ctx, known)
    kept, suppressed = list(bad), 0
    for rule in rules:
        if not rule.applies(ctx):
            continue
        for f in rule.check(ctx):
            if f.rule in file_wide or f.rule in per_line.get(f.line, ()):
                suppressed += 1
                continue
            kept.append(f)
    return kept, suppressed


def run_source(source, relpath, rules, project=None):
    """Test/driver helper: lint one in-memory source blob."""
    ctx = FileContext(relpath, source, project)
    return check_context(ctx, rules)[0]


def iter_py_files(paths, root):
    """Yield repo-relative posix paths of .py files under ``paths``
    (files or directories, resolved against ``root``)."""
    seen = set()
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            cands = [full]
        else:
            cands = []
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git",
                                            ".bench_r4", "node_modules")]
                cands.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        for c in cands:
            rel = os.path.relpath(os.path.abspath(c), root)
            rel = rel.replace(os.sep, "/")
            if rel not in seen:
                seen.add(rel)
                yield rel


def run_paths(paths, root, rules):
    """Lint every .py file under paths.  Returns (findings, stats)."""
    project = Project(root)
    findings, suppressed, files = [], 0, 0
    for rel in iter_py_files(paths, root):
        files += 1
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            source = f.read()
        try:
            ctx = FileContext(rel, source, project)
        except SyntaxError as exc:
            findings.append(Finding(
                rule="syntax-error", path=rel,
                line=getattr(exc, "lineno", 1) or 1,
                message=f"file does not parse: {exc.msg}"))
            continue
        kept, sup = check_context(ctx, rules)
        findings.extend(kept)
        suppressed += sup
    return findings, {"files": files, "suppressed": suppressed}


# ---------------------------------------------------------------------------
# Baseline

def load_baseline(path):
    """Returns (key -> entry dict, bad findings).  Every entry must name
    a rule, a path, a snippet fingerprint, and a non-empty reason."""
    if not path or not os.path.exists(path):
        return {}, []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries, bad = {}, []
    for e in data.get("entries", []):
        rule = e.get("rule", "")
        reason = (e.get("reason") or "").strip()
        if not rule or not reason:
            bad.append(Finding(
                rule=BAD_BASELINE, path=os.path.basename(path), line=1,
                message=f"baseline entry {e!r} needs both a rule id and "
                        "a non-empty reason"))
            continue
        entries[(rule, e.get("path", ""), e.get("snippet", ""))] = e
    return entries, bad


def save_baseline(path, findings, reason):
    entries = [{"rule": f.rule, "path": f.path, "snippet": f.snippet,
                "reason": reason} for f in findings]
    entries.sort(key=lambda e: (e["path"], e["rule"], e["snippet"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"entries": entries}, f, indent=1)
        f.write("\n")


def apply_baseline(findings, baseline):
    """Split findings into (new, grandfathered-by-baseline)."""
    new, old = [], []
    for f in findings:
        (old if f.key() in baseline else new).append(f)
    return new, old
