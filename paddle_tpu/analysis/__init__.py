"""paddle_tpu.analysis — graftlint, the repo's AST-based invariant
checker (ISSUE 6).  Turns CLAUDE.md's hard-won architecture rules into
enforced static checks; see docs/ANALYSIS.md for the rule catalog and
``python tools/lint.py --help`` for the CLI.

jax-free on purpose: ``tools/lint.py`` imports this package through a
stub parent module so linting never touches jax (the axon sitecustomize
makes a bare jax import hang on a dead tunnel).  Nothing under
``paddle_tpu.analysis`` may import jax or sibling subpackages.
"""
from __future__ import annotations

from .core import (BAD_BASELINE, BAD_SUPPRESSION, FileContext, Finding,
                   Project, Rule, apply_baseline, load_baseline,
                   run_paths, run_source, save_baseline)
from .rules import ALL_RULES, RULES_BY_ID
from . import knobs

__all__ = [
    "ALL_RULES", "RULES_BY_ID", "BAD_BASELINE", "BAD_SUPPRESSION",
    "FileContext", "Finding", "Project", "Rule", "apply_baseline",
    "knobs", "load_baseline", "run_paths", "run_source",
    "save_baseline",
]
