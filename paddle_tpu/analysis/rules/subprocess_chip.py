"""chip-kill-on-timeout: never kill a mid-Mosaic-compile child
(PERF.md incident #3: a subprocess.run(timeout=600) kill of the
monolithic on-chip test wedged the grant ~50 min and then took the
tunnel down)."""
from __future__ import annotations

import ast
import re

from ..core import Rule, dotted_name

# kill-on-expiry subprocess entry points (subprocess.run & friends
# SIGKILL the child when the timeout fires)
_KILLING_CALLS = {"run", "check_output", "check_call", "call"}
# the ONE killable class of chip work: bounded device-open probes
# (CLAUDE.md round-6 addenda) — match on the enclosing function name
_PROBE_FN = re.compile(r"(?i)(probe|usable|watch|alive|health)")
# a file is "chip-touching" when it talks about the chip/compiler as a
# word (paddle_tpu / PADDLE_TPU_* have no word boundary and don't match)
_CHIP_MARKER = re.compile(r"(?i)\b(tpu|chip|mosaic|axon)\b")


class ChipKillOnTimeout(Rule):
    """``subprocess.run(..., timeout=)``/``check_output`` kill semantics
    and explicit SIGKILLs in chip-touching tools/tests.

    The blessed pattern is Popen + ``communicate(timeout=)`` +
    SIGTERM-with-grace, leaving an unresponsive child to finish
    detached (``test_tpu_chip.py::_run_on_chip``); budget 30-90 s per
    first-time Mosaic compile when sizing timeouts.  Probe functions
    (name matching probe/usable/watch/alive/health) are exempt — bare
    device-open attempts are the one killable class."""

    id = "chip-kill-on-timeout"
    description = ("kill-on-timeout subprocess semantics in chip-"
                   "touching code wedges the grant mid-Mosaic-compile "
                   "(incident #3)")

    def applies(self, ctx):
        in_scope = (ctx.relpath.startswith(("tools/", "tests/"))
                    or "/" not in ctx.relpath)  # repo-root drivers
        return in_scope and bool(_CHIP_MARKER.search(ctx.source))

    def _exempt(self, ctx, node):
        fn = ctx.enclosing_function(node)
        return fn is not None and _PROBE_FN.search(fn.name)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            tail = name.split(".")[-1]
            has_timeout = any(kw.arg == "timeout" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None) for kw in node.keywords)
            if tail in _KILLING_CALLS and "subprocess" in name \
                    and has_timeout:
                if self._exempt(ctx, node):
                    continue
                yield ctx.finding(
                    self.id, node,
                    f"`{name}(..., timeout=)` SIGKILLs the child on "
                    "expiry — killing a mid-Mosaic-compile chip process "
                    "wedges the grant (incident #3); use Popen + "
                    "communicate(timeout=) + SIGTERM-with-grace, and "
                    "leave an unresponsive child to finish detached")
            elif tail == "kill" and isinstance(node.func, ast.Attribute) \
                    and not node.args:
                # p.kill() == SIGKILL; p.terminate()/SIGTERM is blessed
                if self._exempt(ctx, node):
                    continue
                yield ctx.finding(
                    self.id, node,
                    f"`{name}()` sends SIGKILL — never SIGKILL a "
                    "chip-touching child (wedges the grant); SIGTERM "
                    "with grace, then leave it to exit on its own")
            elif tail == "killpg":
                if self._exempt(ctx, node):
                    continue
                yield ctx.finding(
                    self.id, node,
                    "`os.killpg` on a chip-touching process group — "
                    "the harness-style group kill is exactly what "
                    "wedges the grant; run chip work detached "
                    "(setsid) and poll its log instead")
