"""kvtier-blessed-access (round 20): page-pool payload movement and
pool internals belong to :mod:`paddle_tpu.serving.kvtier`.

The host/disk page pools store PAGEWIRE payloads keyed by token-chain
bytes; the geometry metadata, CRC validation, chain-walk semantics and
the spill dedup all live in ``KVTier`` (spill/flush/restore/prewarm/
invalidate).  Library code that calls ``pool.put``/``get``/``pop``
directly bypasses every one of those — a raw put drops the geometry
meta a restore needs, a raw get skips the corrupt-entry disposal path,
and both skirt the tier's best-effort error contract.  Reaching into
``pool._entries``/``pool._lock`` from outside kvtier.py breaks the
LRU/accounting invariants the cross-tier conservation check audits.

Blessed for everyone: constructing pools, the ``KVTier`` entry points,
and the read-only/lifecycle surface — ``stats``/``snapshot``/
``contains``/``hottest``/``clear``/``pages``/``budget_bytes``
(``snapshot`` is what chaos' ``verify_tier_conservation`` audits
against).  Tests construct and poke pools directly and are out of
scope, like the engine-lock rule."""
from __future__ import annotations

import ast

from ..core import Rule, dotted_name

# the pool implementation itself (internals + put/get/pop are its own)
_ALLOWED_FILES = {
    "paddle_tpu/serving/kvtier.py",
}
# payload movement: only KVTier's spill/restore/prewarm may call these
_POOL_MUTATORS = {"put", "get", "pop"}
# receiver-name heuristic, same shape as the engine-lock rule: a pool
# object is named after what it is at every real call site
_POOL_RECEIVERS = ("pool", "host_pool", "_pool", "page_pool", "disk",
                   "_disk", "kvtier", "_tier", "tier")


def _pool_parts(node):
    recv = dotted_name(node) or ""
    return [p for p in recv.split(".") if p in _POOL_RECEIVERS], recv


class KvtierBlessedAccess(Rule):
    """Direct pool payload mutation or pool-internals access outside
    kvtier.py.

    Route spills/restores through ``KVTier`` (or the engine/front-end
    wrappers above it); read occupancy through ``stats()``/
    ``snapshot()``/``contains()``."""

    id = "kvtier-blessed-access"
    description = ("direct HostPagePool/DiskPagePool put/get/pop or "
                   "_internals outside kvtier.py bypass the tier's "
                   "geometry/CRC/best-effort contract")

    def applies(self, ctx):
        return ((ctx.relpath.startswith("paddle_tpu/")
                 or ctx.relpath.startswith("tools/"))
                and ctx.relpath not in _ALLOWED_FILES)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr in _POOL_MUTATORS:
                parts, recv = _pool_parts(node.value)
                if parts:
                    yield ctx.finding(
                        self.id, node,
                        f"direct `{recv}.{node.attr}()` outside "
                        "kvtier.py — page payloads must move through "
                        "KVTier.spill/restore/prewarm (geometry meta, "
                        "CRC disposal, best-effort contract); read "
                        "through stats()/snapshot()/contains()")
            elif node.attr.startswith("_"):
                parts, recv = _pool_parts(node.value)
                if parts:
                    yield ctx.finding(
                        self.id, node,
                        f"pool internals access `{recv}.{node.attr}` "
                        "outside kvtier.py — LRU order and byte "
                        "accounting are the tier's own (the cross-tier "
                        "conservation check audits them); use the "
                        "blessed read-only surface")
