"""fleet-process-spawn: replica server processes are spawned through
:class:`paddle_tpu.serving.fleet.ProcessReplicaBackend`, never by a
bare ``subprocess.Popen``.

Round-19 invariant (ISSUE 12): the backend is where the fleet's
process hygiene lives — bounded ``/healthz`` readiness under the
startup deadline, restart-with-backoff under a per-replica budget,
ephemeral-port allocation, and reaping on EVERY exit path (close,
atexit, the worker's parent-death watchdog).  A hand-rolled spawn
bypasses all of it and recreates the stale-orphan-process class the
round-4 addenda documents (leftover suite processes starving the VM
for hours).  Two shapes are flagged:

- ANY subprocess call inside ``paddle_tpu/serving/`` outside
  ``fleet.py`` — serving library code has no business forking;
- a subprocess call anywhere in tools/tests whose arguments name the
  replica server entry (``fleet_worker`` / ``serving.server``) — the
  hand-rolled replica spawn itself.
"""
from __future__ import annotations

import ast
import re

from ..core import Rule, dotted_name

# the ONE blessed home of serving-process spawns
_BACKEND_HOME = "paddle_tpu/serving/fleet.py"

_SPAWN_CALLS = {"Popen", "run", "check_output", "check_call", "call"}
# strings that mark a spawned command as a replica server process
_SERVER_ENTRY = re.compile(r"fleet_worker|serving\.server")


def _call_strings(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


class FleetProcessSpawn(Rule):
    """Bare subprocess spawns of replica server processes outside
    ``ProcessReplicaBackend``."""

    id = "fleet-process-spawn"
    description = ("replica server processes spawned outside "
                   "ProcessReplicaBackend bypass startup-deadline/"
                   "restart-budget/port hygiene and reaping (orphan "
                   "process class, round-4 addenda)")

    def applies(self, ctx):
        if ctx.relpath == _BACKEND_HOME:
            return False
        return ctx.relpath.startswith(("paddle_tpu/serving/",
                                       "tools/", "tests/"))

    def check(self, ctx):
        in_serving = ctx.relpath.startswith("paddle_tpu/serving/")
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if "subprocess" not in name \
                    or name.split(".")[-1] not in _SPAWN_CALLS:
                continue
            spawns_server = any(_SERVER_ENTRY.search(s)
                                for s in _call_strings(node))
            if not (in_serving or spawns_server):
                continue
            what = ("serving code must not fork" if in_serving
                    and not spawns_server
                    else "a replica server process")
            yield ctx.finding(
                self.id, node,
                f"`{name}` spawning {what} outside "
                "ProcessReplicaBackend — the backend owns startup "
                "deadlines, restart budgets, port allocation and "
                "reap-on-every-exit-path; route the spawn through "
                "paddle_tpu.serving.fleet (round-19 invariant)")
