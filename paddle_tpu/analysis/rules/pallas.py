"""Pallas/Mosaic compile-hazard rules (CLAUDE.md round-5/round-6
addenda: constructs with no interpret-mode lowering or O(seq) VMEM)."""
from __future__ import annotations

import ast
import re

from ..core import Rule, dotted_name

# Bare names that read as a sequence length when used as a BlockSpec
# block-shape element: a block sized by one of these scales VMEM with
# the sequence instead of staying O(block) (the 16 MB scoped-VMEM
# invariant; stream via grid axes with output accumulation instead).
# Round 22 adds the token-packed names (t/tok*/n_tok*/tcap): the ragged
# kernel's T axis is batch*seq-scaled, so a T-sized block is the same
# hazard — the unified kernel streams it as the grid axis, one token
# cell per instance.
_SEQ_NAME = re.compile(
    r"(?i)^(s|sk|sq|skv|seq\w*|\w*seq|\w*_len|\w*len|n_ctx|ctx\w*"
    r"|t|nt|tcap|tok(en)?s?|n_tok\w*|ntok\w*|\w*_toks?)$")
# short names that merely END in "len"/"s" but are clearly not lengths
_SEQ_NAME_EXCLUDES = {"lanes", "len"}


class PallasHazards(Rule):
    """Four Mosaic/interpret-mode/GSPMD hazards in one rule:

    1. ``pl.program_id`` inside a ``fori_loop``/``while_loop``/``scan``
       body — interpret mode fails with "MLIR translation rule not
       found"; read it at kernel top level and close over the value.
    2. ``pltpu.prng_seed``/``pltpu.prng_random_bits`` — no
       interpret-mode lowering; use the counter-hash (plain i32 vector
       ops) for in-kernel RNG.
    3. BlockSpec block shapes scaling with a sequence axis (or the
       ragged kernel's packed-token axis, which is batch*seq-scaled) —
       per-instance VMEM must stay O(block), never O(sequence).
    4. A file that both calls ``pallas_call`` and builds GSPMD sharding
       machinery (``NamedSharding`` / ``Mesh(...)`` construction /
       ``with_sharding_constraint``) — ``pallas_call`` has no GSPMD
       partitioning rule, so a kernel traced into an SPMD program is
       silent wrongness.  Keep kernels and mesh plumbing in separate
       modules (serving/tp.py vs serving/attention.py is the blessed
       split; multi-device programs take ``use_pallas=False``-style
       flags, round-23 ISSUE-19 satellite)."""

    id = "pallas-hazards"
    description = ("program_id in loop bodies, pltpu.prng_*, "
                   "seq-scaled BlockSpec shapes, and pallas_call mixed "
                   "with GSPMD sharding constructs hang, fail, or "
                   "silently mis-partition Mosaic/interpret/SPMD "
                   "programs")

    # -- helpers -----------------------------------------------------------
    def _loop_bodies(self, ctx):
        """(lambda | FunctionDef) nodes passed as loop bodies."""
        fns = ctx.functions_by_name()
        bodies = []

        def _resolve(arg):
            if isinstance(arg, ast.Lambda):
                bodies.append(arg)
            elif isinstance(arg, ast.Name) and arg.id in fns:
                bodies.append(fns[arg.id])

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = (dotted_name(node.func) or "").split(".")[-1]
            if tail == "fori_loop" and len(node.args) >= 3:
                _resolve(node.args[2])
            elif tail == "while_loop" and len(node.args) >= 2:
                _resolve(node.args[1])
            elif tail == "scan" and node.args:
                _resolve(node.args[0])
        return bodies

    def check(self, ctx):
        # 1. program_id inside loop bodies
        for body in self._loop_bodies(ctx):
            for node in ast.walk(body):
                if isinstance(node, ast.Call) and \
                        (dotted_name(node.func) or "").endswith(
                            "program_id"):
                    yield ctx.finding(
                        self.id, node,
                        "`program_id` read inside a loop body — "
                        "interpret mode has no MLIR rule for it there; "
                        "hoist the read to kernel top level and close "
                        "over the value")
        # 2. pltpu.prng_*
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                if name.split(".")[-1] in ("prng_seed",
                                           "prng_random_bits"):
                    yield ctx.finding(
                        self.id, node,
                        f"`{name}` has no interpret-mode lowering — "
                        "kernels using it cannot be validated off-chip; "
                        "use the i32 counter-hash pattern instead")
        # 3. seq-scaled BlockSpec block shapes
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and (dotted_name(node.func) or "").endswith(
                        "BlockSpec")
                    and node.args
                    and isinstance(node.args[0], ast.Tuple)):
                continue
            for elt in node.args[0].elts:
                if isinstance(elt, ast.Name) \
                        and elt.id.lower() not in _SEQ_NAME_EXCLUDES \
                        and _SEQ_NAME.match(elt.id):
                    yield ctx.finding(
                        self.id, node,
                        f"BlockSpec block shape uses `{elt.id}` — a "
                        "sequence-sized block makes per-instance VMEM "
                        "O(seq), not O(block); stream via a grid axis "
                        "with output accumulation (16 MB scoped-VMEM "
                        "limit)")
        # 4. pallas_call mixed with GSPMD sharding constructs in one
        # module (pallas_call has no GSPMD partitioning rule)
        pallas_calls = []
        sharding_refs = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                tail = name.split(".")[-1]
                if tail == "pallas_call":
                    pallas_calls.append(node)
                elif tail in ("NamedSharding", "Mesh",
                              "with_sharding_constraint"):
                    sharding_refs.append((tail, node))
        if pallas_calls and sharding_refs:
            tails = sorted({t for t, _ in sharding_refs})
            for node in pallas_calls:
                yield ctx.finding(
                    self.id, node,
                    "`pallas_call` in a module that also builds GSPMD "
                    f"sharding machinery ({', '.join(tails)}) — "
                    "pallas_call has no GSPMD partitioning rule, so a "
                    "kernel traced into an SPMD program silently "
                    "mis-partitions; keep kernels and mesh plumbing in "
                    "separate modules and gate the kernel off under "
                    "SPMD (serving/tp.py vs attention.py is the "
                    "blessed split)")
