"""jit-constant-capture: weights must be ARGUMENTS of compiled programs
(CLAUDE.md axon measurement hygiene — baked-in constants blow the
remote-compile transport with HTTP 413, and jit caches keyed on such
programs go stale when weights change)."""
from __future__ import annotations

import ast
import re

from ..core import Rule, dotted_name

_JIT_NAMES = {"jax.jit", "jit"}
# closure-variable names / assignment sources that read as model state
_ARRAYISH_NAME = re.compile(r"(?i)(param|weight|state_dict|_data\b)")


def _is_jit_decorator(dec):
    """@jax.jit, @jit, @functools.partial(jax.jit, ...), @jax.jit(...)"""
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_NAMES:
            return True
        if fname in ("functools.partial", "partial") and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


class JitConstantCapture(Rule):
    """jit-wrapped callables closing over module/instance arrays.

    A jit-captured weight is a CONSTANT of the compiled program: the
    remote-compile transport rejects the resulting big request bodies
    (HTTP 413 / broken pipe), and any cache of such programs silently
    serves stale weights after an update.  Weights must be arguments.

    Flags, inside a jit-wrapped function:
    - any ``self.<attr>`` use when ``self`` is captured from an
      enclosing method (a closure baking instance state in);
    - ``@jax.jit`` directly on a method (``self`` becomes a traced/
      static arg — instance arrays become constants either way);
    - closure variables from an enclosing function whose name or
      assignment source looks like model state (``params``, ``weights``,
      ``state_dict()``, ``._data``)."""

    id = "jit-constant-capture"
    description = ("jit-wrapped callable closes over module/instance "
                   "arrays — weights must be arguments (HTTP-413 / "
                   "stale-cache hazard)")

    def applies(self, ctx):
        return ctx.relpath.startswith("paddle_tpu/")

    # -- jit-function discovery --------------------------------------------
    def _jit_functions(self, ctx):
        fns = ctx.functions_by_name()
        out = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) and any(
                    _is_jit_decorator(d) for d in node.decorator_list):
                out[node.name] = node
            elif isinstance(node, ast.Call) \
                    and dotted_name(node.func) in _JIT_NAMES \
                    and node.args \
                    and isinstance(node.args[0], ast.Name):
                target = fns.get(node.args[0].id)
                if target is not None:
                    out[target.name] = target
        return out.values()

    # -- scope analysis ----------------------------------------------------
    def _local_bindings(self, fn):
        """Names bound inside fn: params, assignments, imports, defs."""
        bound = {a.arg for a in fn.args.args + fn.args.posonlyargs
                 + fn.args.kwonlyargs}
        if fn.args.vararg:
            bound.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            bound.add(fn.args.kwarg.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                bound.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                bound.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    bound.add((a.asname or a.name).split(".")[0])
        return bound

    def _enclosing_arrayish(self, ctx, fn):
        """Closure-candidate names bound in enclosing FUNCTION scopes
        whose name or assignment RHS looks like model state."""
        arrayish = {}
        for anc in ctx.ancestors(fn):
            if not isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(anc):
                if node is fn or isinstance(node, ast.FunctionDef) \
                        and node is not anc:
                    continue
                if isinstance(node, ast.Assign):
                    rhs = ast.dump(node.value)
                    looks = bool(_ARRAYISH_NAME.search(rhs)) or \
                        ".parameters" in rhs or "state_dict" in rhs
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) and (
                                looks or _ARRAYISH_NAME.search(tgt.id)):
                            arrayish.setdefault(tgt.id, node.lineno)
            for a in anc.args.args:
                if _ARRAYISH_NAME.search(a.arg):
                    arrayish.setdefault(a.arg, anc.lineno)
        return arrayish

    def check(self, ctx):
        for fn in self._jit_functions(ctx):
            local = self._local_bindings(fn)
            if "self" in local:
                # @jax.jit straight on a method
                yield ctx.finding(
                    self.id, fn,
                    f"`{fn.name}` is jit-wrapped with `self` as a "
                    "parameter — instance arrays become compile-time "
                    "constants; compile a pure function taking weights "
                    "as explicit arguments instead")
                continue
            arrayish = self._enclosing_arrayish(ctx, fn)
            reported = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self":
                    key = f"self.{node.attr}"
                    if key not in reported:
                        reported.add(key)
                        yield ctx.finding(
                            self.id, node,
                            f"jit-wrapped `{fn.name}` reads `{key}` — "
                            "instance state is baked into the compiled "
                            "program as a constant (413/stale-cache "
                            "hazard); pass it as an argument")
                elif isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id not in local \
                        and node.id in arrayish \
                        and node.id not in reported:
                    reported.add(node.id)
                    yield ctx.finding(
                        self.id, node,
                        f"jit-wrapped `{fn.name}` closes over "
                        f"`{node.id}` (bound at line "
                        f"{arrayish[node.id]}, looks like model state) "
                        "— weights must be ARGUMENTS of compiled "
                        "programs, never jit-captured constants")
