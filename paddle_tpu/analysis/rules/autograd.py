"""Autograd invariants: the single-chokepoint rule and the round-11
thread-local grad-mode rule (CLAUDE.md "Architecture invariants" +
"Round-11 addenda")."""
from __future__ import annotations

import ast

from ..core import Rule, dotted_name

# Modules that ARE differentiation engines: they legitimately call the
# raw jax AD API (everything else must route through autograd.apply).
_AD_ENGINE_FILES = {
    "paddle_tpu/core/autograd.py",       # the chokepoint itself
    "paddle_tpu/incubate/autograd.py",   # paddle.incubate.autograd jvp/vjp
    "paddle_tpu/static/program.py",      # static-graph append_backward
}

_FLAGGED = {"jax.vjp", "jax.grad", "jax.custom_vjp"}


class AutogradBypass(Rule):
    """`jax.vjp`/`jax.grad`/`jax.custom_vjp` invoked outside the
    autograd chokepoint in differentiable-op code.

    Every differentiable op flows through ``core/autograd.py::apply``;
    eagerly calling ``jax.vjp`` at tracers strips custom_vjp rules
    (Pallas kernels silently fall back / remat breaks).  Allowed:
    the AD-engine modules, ``jax.custom_vjp`` used as a decorator
    (defining a custom rule is the blessed pattern anywhere), and
    ``jax.vjp`` inside functions registered via ``*.defvjp(...)``
    (a custom rule's fwd/bwd may re-trace the core)."""

    id = "autograd-bypass"
    description = ("raw jax AD API outside core.autograd.apply strips "
                   "custom_vjp under tracing (single-chokepoint invariant)")

    def applies(self, ctx):
        return (ctx.relpath.startswith("paddle_tpu/")
                and ctx.relpath not in _AD_ENGINE_FILES)

    def _import_aliases(self, ctx):
        """Names bound by `from jax import vjp/grad/custom_vjp`."""
        aliases = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name in ("vjp", "grad", "custom_vjp"):
                        aliases[a.asname or a.name] = f"jax.{a.name}"
        return aliases

    def _defvjp_registered(self, ctx):
        names = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "defvjp":
                for a in node.args:
                    if isinstance(a, ast.Name):
                        names.add(a.id)
        return names

    def check(self, ctx):
        aliases = self._import_aliases(ctx)
        registered = self._defvjp_registered(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            full = aliases.get(name, name)
            if full not in _FLAGGED:
                continue
            if full == "jax.custom_vjp" and ctx.in_decorator(node):
                continue  # @functools.partial(jax.custom_vjp, ...) etc.
            if full == "jax.vjp":
                fn = ctx.enclosing_function(node)
                if fn is not None and fn.name in registered:
                    continue  # fwd/bwd of a registered custom rule
            yield ctx.finding(
                self.id, node,
                f"direct `{full}` call outside the autograd chokepoint — "
                "differentiable ops must route through "
                "core.autograd.apply (eager vjp at tracers strips "
                "custom_vjp rules; Pallas kernels silently fall back)")


_GRAD_STATE_CALLS = {"set_grad_enabled"}
_GRAD_CTX_CALLS = {"no_grad", "enable_grad"}


class ThreadGradState(Rule):
    """Thread/executor targets that toggle grad mode manually instead of
    via a scoped ``with no_grad():`` block.

    Round-11 incident: concurrent engine loop threads interleaving
    save/restore of a (then process-global) grad flag disabled autograd
    for the whole process — 23 later test files failed in-suite.  Grad
    mode is thread-local now, but manual save/restore across statements
    in a thread target re-creates the hazard the moment the state is
    shared again (and relies on ambient mode that thread-locals do NOT
    inherit from the spawning thread).  Scoped context-manager use is
    the per-thread-safe pattern and passes."""

    id = "thread-grad-state"
    description = ("manual grad-mode toggling in a thread target "
                   "(round-11 interleaving bug class) — use a scoped "
                   "`with no_grad():` instead")

    def applies(self, ctx):
        return (ctx.relpath.startswith("paddle_tpu/")
                or ctx.relpath.startswith("tools/"))

    def _thread_targets(self, ctx):
        """Function names used as Thread targets / executor submits."""
        targets = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            tgt = None
            if name.split(".")[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = kw.value
                if tgt is None and len(node.args) >= 2:
                    tgt = node.args[1]  # Thread(group, target, ...)
            elif name.split(".")[-1] == "submit" and node.args:
                tgt = node.args[0]
            if tgt is None:
                continue
            if isinstance(tgt, ast.Name):
                targets.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                targets.add(tgt.attr)  # self._loop -> "_loop"
        return targets

    def _called_names(self, fn):
        out = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name:
                    out.add(name.split(".")[-1])
        return out

    def _violations(self, ctx, fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = (dotted_name(node.func) or "").split(".")[-1]
            if name in _GRAD_STATE_CALLS:
                yield node, name
            elif name in _GRAD_CTX_CALLS:
                parent = ctx.parent(node)
                if isinstance(parent, ast.withitem) or \
                        ctx.in_decorator(node):
                    continue  # `with no_grad():` / decorator — scoped, safe
                yield node, name

    def check(self, ctx):
        targets = self._thread_targets(ctx)
        if not targets:
            return
        fns = ctx.functions_by_name()
        for tname in sorted(targets):
            fn = fns.get(tname)
            if fn is None:
                continue
            # the target body plus one level of same-module callees —
            # the round-11 loop called a helper that did the toggling
            bodies = [(tname, fn)]
            for callee in sorted(self._called_names(fn)):
                if callee in fns and callee != tname:
                    bodies.append((f"{tname} -> {callee}", fns[callee]))
            for label, body in bodies:
                for node, api in self._violations(ctx, body):
                    yield ctx.finding(
                        self.id, node,
                        f"thread target `{label}` calls `{api}` outside "
                        "a scoped `with` block — manual grad-mode "
                        "save/restore across threads is the round-11 "
                        "interleaving bug; keep grad-mode handling "
                        "per-thread and scoped")
