"""serving-raw-sleep: every latency/backoff/poll sleep in the serving
tier must route through the chaos layer's injected sleeper
(``ChaosInjector.sleep`` — ``paddle_tpu/serving/chaos.py``), never raw
``time.sleep``.

Round-17 invariant: the chaos harness drives deterministic, seeded
fault schedules against the whole fleet.  A raw ``time.sleep`` in an
engine/router/replica loop path (a) makes those schedules
nondeterministic — wall-clock sleeps interleave fault firings
differently per run — and (b) makes the chaos fuzz and every retry
test wall-clock slow, because a fake sleeper cannot collapse the wait.
The round-11 addenda's fixed-sleep test flakes are the same bug class
on the test side."""
from __future__ import annotations

import ast

from ..core import Rule, dotted_name

# the injected sleeper's home — the ONE place a real time.sleep belongs
_SLEEPER_HOME = "paddle_tpu/serving/chaos.py"


class ServingRawSleep(Rule):
    """Raw ``time.sleep`` calls inside ``paddle_tpu/serving/``."""

    id = "serving-raw-sleep"
    description = ("raw time.sleep in serving code defeats the chaos "
                   "layer's injected sleeper (nondeterministic fault "
                   "schedules, wall-clock-slow tests)")

    def applies(self, ctx):
        return (ctx.relpath.startswith("paddle_tpu/serving/")
                and ctx.relpath != _SLEEPER_HOME)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) != "time.sleep":
                continue
            yield ctx.finding(
                self.id, node,
                "raw `time.sleep` in serving code — route the wait "
                "through the chaos sleeper (`chaos.sleep(...)` / "
                "`ChaosInjector.sleep`) so fault schedules stay "
                "deterministic and tests can collapse time "
                "(round-17 invariant)")
