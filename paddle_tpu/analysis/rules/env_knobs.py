"""env-knob-registry: every PADDLE_TPU_* knob referenced in code must
appear in the generated docs/ENV_KNOBS.md registry (knobs documented
only in commit messages and scattered docstrings kept getting lost —
the registry is the one greppable catalog)."""
from __future__ import annotations

from ..core import Rule
from ..knobs import knob_literals


class EnvKnobRegistry(Rule):
    """Flags PADDLE_TPU_* string constants not listed in the registry.

    Any full-string ``PADDLE_TPU_[A-Z0-9_]+`` constant counts as a
    reference (environ reads, helper wrappers, env writes in tests) —
    the same extraction drives ``tools/lint.py --gen-knobs``, so a
    regenerated registry always satisfies this rule."""

    id = "env-knob-registry"
    description = ("PADDLE_TPU_* knob referenced in code but missing "
                   "from the generated docs/ENV_KNOBS.md registry")

    def check(self, ctx):
        registry = ctx.project.knob_registry()
        seen = set()
        for knob, line in knob_literals(ctx.tree):
            if knob in registry or (knob, line) in seen:
                continue
            seen.add((knob, line))
            yield ctx.finding(
                self.id, line,
                f"`{knob}` is not in docs/ENV_KNOBS.md — run "
                "`python tools/lint.py --gen-knobs` and document it")
