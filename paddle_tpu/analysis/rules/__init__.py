"""graftlint rule registry — one Rule instance per CLAUDE.md invariant.

Adding a rule: subclass :class:`paddle_tpu.analysis.core.Rule` in a
module here, instantiate it in ``ALL_RULES``, give it a bad/good
fixture pair in ``tests/test_analysis.py``, and document the incident
it encodes in ``docs/ANALYSIS.md`` (same-commit, like the round-7
sweep rule for new API surfaces)."""
from __future__ import annotations

from .autograd import AutogradBypass, ThreadGradState
from .chaos_clock import ServingRawSleep
from .dist_spec import DistSpecPassthrough
from .env_knobs import EnvKnobRegistry
from .fleet_spawn import FleetProcessSpawn
from .jit_capture import JitConstantCapture
from .kvtier_access import KvtierBlessedAccess
from .pallas import PallasHazards
from .serving_lock import EngineLockDiscipline, PageMigrationLock
from .subprocess_chip import ChipKillOnTimeout
from .weight_swap import WeightSwapLock

ALL_RULES = [
    AutogradBypass(),
    ThreadGradState(),
    PallasHazards(),
    JitConstantCapture(),
    DistSpecPassthrough(),
    ChipKillOnTimeout(),
    EngineLockDiscipline(),
    PageMigrationLock(),
    EnvKnobRegistry(),
    ServingRawSleep(),
    FleetProcessSpawn(),
    KvtierBlessedAccess(),
    WeightSwapLock(),
]

RULES_BY_ID = {r.id: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_ID", "AutogradBypass",
           "ThreadGradState", "PallasHazards", "JitConstantCapture",
           "DistSpecPassthrough", "ChipKillOnTimeout",
           "EngineLockDiscipline", "PageMigrationLock",
           "EnvKnobRegistry", "ServingRawSleep", "FleetProcessSpawn",
           "KvtierBlessedAccess", "WeightSwapLock"]
