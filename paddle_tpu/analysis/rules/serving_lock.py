"""engine-lock-discipline: the serving engine is single-threaded behind
ONE lock (CLAUDE.md round-9 addenda) — engine.step()/engine.cancel()
must never run concurrently; all multi-threaded use goes through
ServingFrontend."""
from __future__ import annotations

import ast

from ..core import Rule, dotted_name

# the blessed homes of direct engine driving
_ALLOWED_FILES = {
    "paddle_tpu/serving/engine.py",    # the engine itself
    "paddle_tpu/serving/frontend.py",  # owns the lock + loop thread
}
_ENGINE_METHODS = {"step", "cancel"}


class EngineLockDiscipline(Rule):
    """Direct ``engine.step()``/``engine.cancel()`` calls outside
    ServingFrontend/engine internals.

    Any new call site that drives an engine from library code races the
    loop thread unless it holds the front-end lock; route through
    ``ServingFrontend`` (tests and single-threaded drivers construct
    engines directly and are out of scope — the lint CLI's tests/ scope
    skips this rule)."""

    id = "engine-lock-discipline"
    description = ("direct engine.step()/cancel() outside "
                   "ServingFrontend races the single engine lock")

    def applies(self, ctx):
        return (ctx.relpath.startswith("paddle_tpu/")
                and ctx.relpath not in _ALLOWED_FILES)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ENGINE_METHODS):
                continue
            recv = dotted_name(node.func.value) or ""
            parts = recv.split(".")
            if not any(p in ("engine", "eng", "_engine") for p in parts):
                continue
            yield ctx.finding(
                self.id, node,
                f"direct `{recv}.{node.func.attr}()` outside "
                "ServingFrontend — the engine is single-threaded "
                "behind ONE lock; step()/cancel() must not run "
                "concurrently (round-9 invariant), go through the "
                "front-end")
