"""engine-lock-discipline: the serving engine is single-threaded behind
ONE lock (CLAUDE.md round-9 addenda) — engine.step()/engine.cancel()
must never run concurrently; all multi-threaded use goes through
ServingFrontend.

page-migration-lock (round 14): the same lock also guards KV page
migration — import_pages/export_pages scatter into (and fetch from)
the SAME device buffers the compiled step program is about to swap, so
an import racing a step silently loses whole pages of K/V.  Direct
engine/cache-level migration calls belong in kv_cache.py (the
allocator), engine.py (the driver) and frontend.py (the lock owner);
everything else — router, disagg tier, server handlers, autoscaler —
must go through the ServingFrontend methods."""
from __future__ import annotations

import ast

from ..core import Rule, dotted_name

# the blessed homes of direct engine driving
_ALLOWED_FILES = {
    "paddle_tpu/serving/engine.py",    # the engine itself
    "paddle_tpu/serving/frontend.py",  # owns the lock + loop thread
}
_ENGINE_METHODS = {"step", "cancel"}

# direct page-migration mutators (cache/engine level); replica- and
# frontend-level wrappers of the same names are lock-taking and fine —
# the receiver filter below tells them apart.  Round 18 adds the fleet
# prefix-transfer family: prefix export/import/drop touch the same
# device buffers and radix tree, so they ride the same lock contract.
_MIGRATION_FILES = _ALLOWED_FILES | {
    "paddle_tpu/serving/kv_cache.py",  # the allocator itself
    "paddle_tpu/serving/kvtier.py",    # host-tier restore (round 20):
    # KVTier.restore re-enters through import_prefix_pages and is only
    # reachable via engine.restore_prefix / add_request, both under
    # the engine lock (kvtier-blessed-access guards the pool side)
}
_MIGRATION_METHODS = {"import_pages", "export_pages", "adopt_request",
                      "export_request", "release_request",
                      "export_prefix_pages", "import_prefix_pages",
                      "export_prefix", "import_prefix", "drop_prefix"}
_ENGINE_RECEIVERS = ("engine", "eng", "_engine", "cache", "_cache",
                     "kv_cache", "_draft_cache")


class EngineLockDiscipline(Rule):
    """Direct ``engine.step()``/``engine.cancel()`` calls outside
    ServingFrontend/engine internals.

    Any new call site that drives an engine from library code races the
    loop thread unless it holds the front-end lock; route through
    ``ServingFrontend`` (tests and single-threaded drivers construct
    engines directly and are out of scope — the lint CLI's tests/ scope
    skips this rule)."""

    id = "engine-lock-discipline"
    description = ("direct engine.step()/cancel() outside "
                   "ServingFrontend races the single engine lock")

    def applies(self, ctx):
        return (ctx.relpath.startswith("paddle_tpu/")
                and ctx.relpath not in _ALLOWED_FILES)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ENGINE_METHODS):
                continue
            recv = dotted_name(node.func.value) or ""
            parts = recv.split(".")
            if not any(p in ("engine", "eng", "_engine") for p in parts):
                continue
            yield ctx.finding(
                self.id, node,
                f"direct `{recv}.{node.func.attr}()` outside "
                "ServingFrontend — the engine is single-threaded "
                "behind ONE lock; step()/cancel() must not run "
                "concurrently (round-9 invariant), go through the "
                "front-end")


class PageMigrationLock(Rule):
    """Engine/cache-level KV page migration calls outside the
    allocator, the engine, and the lock-owning front-end.

    A page import/export mutates the cache's device buffers and host
    bookkeeping; racing the step loop silently corrupts K/V.  Library
    code must call the ``ServingFrontend`` migration methods (which
    hold the engine lock) — never ``cache.import_pages`` /
    ``engine.adopt_request`` directly."""

    id = "page-migration-lock"
    description = ("direct cache/engine page-migration calls outside "
                   "the frontend lock corrupt in-flight step buffers")

    def applies(self, ctx):
        return (ctx.relpath.startswith("paddle_tpu/")
                and ctx.relpath not in _MIGRATION_FILES)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MIGRATION_METHODS):
                continue
            recv = dotted_name(node.func.value) or ""
            parts = recv.split(".")
            if not any(p in _ENGINE_RECEIVERS for p in parts):
                continue  # replica/frontend wrapper: lock-taking
            yield ctx.finding(
                self.id, node,
                f"direct `{recv}.{node.func.attr}()` outside the "
                "front-end lock — page migration shares the engine "
                "lock with the step loop (round-14 invariant); go "
                "through ServingFrontend.probe_prefix/export_request/"
                "release_request/adopt (or, for fleet prefix ships, "
                "export_prefix/import_prefix/drop_prefix)")
