"""dist-spec-passthrough: sharding specs COMPOSE (CLAUDE.md
architecture invariants; the round-3 7B TP4 feasibility run caught
params at total/mp instead of total/(mp·sharding))."""
from __future__ import annotations

import ast

from ..core import Rule, dotted_name

_COMPOSERS = {"_add_sharding", "_pp_param_spec"}


def _reads_dist_spec(node):
    """True for `<x>.dist_spec` or getattr(<x>, "dist_spec"[, d])."""
    if isinstance(node, ast.Attribute) and node.attr == "dist_spec":
        return True
    if isinstance(node, ast.Call) and dotted_name(node.func) == "getattr":
        return any(isinstance(a, ast.Constant) and a.value == "dist_spec"
                   for a in node.args)
    return False


class DistSpecPassthrough(Rule):
    """Spec functions returning a TP ``dist_spec`` verbatim.

    An explicit TP ``dist_spec`` must never be returned as-is by a spec
    function: ZeRO adds 'sharding' on the largest free divisible dim on
    top (``spmd.py::_add_sharding`` / ``pipeline.py::_pp_param_spec``).
    Returning it directly silently replicates TP weights across the
    whole sharding group.  A function that calls one of the composers
    anywhere is exempt (returning the uncomposed spec is its documented
    no-free-dim fallback)."""

    id = "dist-spec-passthrough"
    description = ("spec function returns dist_spec verbatim instead of "
                   "composing via _add_sharding/_pp_param_spec — TP "
                   "weights silently replicate across the sharding group")

    def applies(self, ctx):
        return ctx.relpath.startswith("paddle_tpu/")

    def _tainted_names(self, fn):
        """Names holding (a derivative of) the raw dist_spec: the
        literal `dist_spec` parameter plus assignments whose RHS reads
        `.dist_spec` or an already-tainted name."""
        tainted = {a.arg for a in fn.args.args if a.arg == "dist_spec"}
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                rhs_tainted = False
                for sub in ast.walk(node.value):
                    if _reads_dist_spec(sub) or (
                            isinstance(sub, ast.Name)
                            and isinstance(sub.ctx, ast.Load)
                            and sub.id in tainted):
                        rhs_tainted = True
                        break
                if rhs_tainted:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) \
                                and tgt.id not in tainted:
                            tainted.add(tgt.id)
                            changed = True
        return tainted

    def _verbatim_return(self, ret, tainted):
        """return <tainted> | return <x>.dist_spec |
        return P(*<tainted>) with no other args."""
        v = ret.value
        if v is None:
            return False
        if isinstance(v, ast.Name) and v.id in tainted:
            return True
        if _reads_dist_spec(v):
            return True
        if isinstance(v, ast.Call) and len(v.args) == 1 \
                and not v.keywords \
                and isinstance(v.args[0], ast.Starred):
            inner = v.args[0].value
            if isinstance(inner, ast.Name) and inner.id in tainted:
                return True
            if _reads_dist_spec(inner):
                return True
        return False

    def check(self, ctx):
        for fn in [n for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.FunctionDef)
                   and "spec" in n.name.lower()]:
            uses_dist_spec = any(_reads_dist_spec(n)
                                 for n in ast.walk(fn)) or \
                any(a.arg == "dist_spec" for a in fn.args.args)
            if not uses_dist_spec:
                continue
            composes = any(
                isinstance(n, ast.Call)
                and (dotted_name(n.func) or "").split(".")[-1]
                in _COMPOSERS
                for n in ast.walk(fn))
            if composes:
                continue
            tainted = self._tainted_names(fn)
            for ret in ast.walk(fn):
                if isinstance(ret, ast.Return) \
                        and self._verbatim_return(ret, tainted):
                    yield ctx.finding(
                        self.id, ret,
                        f"spec function `{fn.name}` returns the TP "
                        "dist_spec verbatim — compose the ZeRO/pp axis "
                        "on top via `_add_sharding`/`_pp_param_spec`, "
                        "or TP weights replicate across the whole "
                        "sharding group (round-3 TP4 incident)")
