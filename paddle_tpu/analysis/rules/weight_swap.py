"""weight-swap-lock (round 21): a serving engine's weight pytree only
changes through the deployer's quiesce path.

Weights are ARGUMENTS of the compiled step program — swapping a
tensor's ``_data`` between steps IS the hot-swap, which is exactly why
an unguarded write is dangerous: done off the front-end lock it races
the step's argument gather (a half-swapped pytree dispatched to the
device), and done outside ``engine.set_weights`` it skips the
all-or-nothing payload validation, the stale-K/V prefix flush, and the
``weight_version`` advertisement the router's per-stream version pin
depends on.  The blessed chain is::

    RollingDeployer -> replica.swap_weights
        -> ServingFrontend.swap_weights   (takes the engine lock)
        -> engine.set_weights             (validates, writes, flushes,
                                           bumps weight_version)

so serving-layer code never assigns ``<tensor>._data`` directly and
never calls ``engine.set_weights`` without the lock-owning front-end
in between."""
from __future__ import annotations

import ast

from ..core import Rule, dotted_name

# the engine owns its pytree writes: set_weights (the blessed mutation
# site) plus the pure-step argument restore helpers
_ALLOWED_FILES = {
    "paddle_tpu/serving/engine.py",
}
# files allowed to call engine.set_weights directly (the lock owner)
_SET_WEIGHTS_FILES = _ALLOWED_FILES | {
    "paddle_tpu/serving/frontend.py",
}
_ENGINE_RECEIVERS = ("engine", "eng", "_engine")


class WeightSwapLock(Rule):
    """Serving-layer weight-pytree mutation outside the deployer's
    quiesce path.

    Flags (1) any ``<recv>._data = ...`` assignment in
    ``paddle_tpu/serving/`` outside the engine — the weight hot-swap
    write must go through ``engine.set_weights`` so validation, the
    prefix flush, and the version bump cannot be skipped — and (2)
    direct ``engine.set_weights(...)`` calls outside the front-end,
    which alone holds the engine lock across the write."""

    id = "weight-swap-lock"
    description = ("weight-pytree writes outside the deployer quiesce "
                   "path race the compiled step's argument gather")

    def applies(self, ctx):
        return (ctx.relpath.startswith("paddle_tpu/serving/")
                and ctx.relpath not in _ALLOWED_FILES)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr == "_data"):
                        recv = dotted_name(tgt.value) or "<expr>"
                        yield ctx.finding(
                            self.id, node,
                            f"direct `{recv}._data = ...` in serving "
                            "code — the weight pytree only changes "
                            "through engine.set_weights under the "
                            "front-end lock (deployer quiesce path); "
                            "a raw write races the step's argument "
                            "gather and skips validation/flush/"
                            "version-bump")
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "set_weights"
                  and ctx.relpath not in _SET_WEIGHTS_FILES):
                recv = dotted_name(node.func.value) or ""
                parts = recv.split(".")
                if not any(p in _ENGINE_RECEIVERS for p in parts):
                    continue  # replica/frontend wrapper: lock-taking
                yield ctx.finding(
                    self.id, node,
                    f"direct `{recv}.set_weights()` outside "
                    "ServingFrontend — the swap must hold the engine "
                    "lock for its one-step quiesce; go through "
                    "frontend.swap_weights (or replica.swap_weights)")
