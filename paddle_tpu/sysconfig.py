"""paddle.sysconfig parity: get_include/get_lib (reference:
python/paddle/sysconfig.py). Points at this package's native artifacts
(C ABI shared objects built by paddle_tpu.native)."""
import os

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include():
    return os.path.join(_PKG, "native")


def get_lib():
    return os.path.join(_PKG, "native")
