"""GoogLeNet (Inception v1).

Reference parity: paddle.vision.models.googlenet (upstream
python/paddle/vision/models/googlenet.py — unverified, SURVEY.md §2.2).
Returns (main, aux1, aux2) logits in train mode like the reference.
"""
from ... import nn
from ...ops import manipulation as M


def _conv(cin, cout, k, **kw):
    return nn.Sequential(nn.Conv2D(cin, cout, k, **kw), nn.ReLU())


class _Inception(nn.Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = _conv(cin, c1, 1)
        self.b2 = nn.Sequential(_conv(cin, c3r, 1),
                                _conv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_conv(cin, c5r, 1),
                                _conv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _conv(cin, pp, 1))

    def forward(self, x):
        return M.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                        axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = _conv(cin, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.drop = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x)).flatten(1)
        return self.fc2(self.drop(self.relu(self.fc1(x))))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_aux=True):
        super().__init__()
        self.with_aux = with_aux
        self.stem = nn.Sequential(
            _conv(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _conv(64, 64, 1), _conv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.drop = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)
        if with_aux:
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        a1 = self.aux1(x) if self.with_aux and self.training else None
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = self.aux2(x) if self.with_aux and self.training else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        out = self.fc(self.drop(self.avgpool(x).flatten(1)))
        if self.training and self.with_aux:
            return out, a1, a2
        return out


def googlenet(pretrained=False, **kw):
    assert not pretrained
    return GoogLeNet(**kw)
