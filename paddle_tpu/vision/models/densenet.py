"""DenseNet-121/161/169/201/264.

Reference parity: paddle.vision.models.densenet121 et al. (upstream
python/paddle/vision/models/densenet.py — unverified, SURVEY.md §2.2).
"""
from ... import nn
from ...ops import manipulation as M

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, cin, growth, bn_size=4):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(cin)
        self.conv1 = nn.Conv2D(cin, bn_size * growth, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        y = self.conv1(self.relu(self.norm1(x)))
        y = self.conv2(self.relu(self.norm2(y)))
        return M.concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.norm = nn.BatchNorm2D(cin)
        self.conv = nn.Conv2D(cin, cout, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, num_classes=1000):
        super().__init__()
        init_c, growth, blocks = _CFG[layers]
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        c = init_c
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth))
                c += growth
            if i != len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.avgpool(self.features(x)).flatten(1)
        return self.classifier(x)


def _make(layers):
    def f(pretrained=False, **kw):
        assert not pretrained
        return DenseNet(layers, **kw)
    f.__name__ = f"densenet{layers}"
    return f


densenet121 = _make(121)
densenet161 = _make(161)
densenet169 = _make(169)
densenet201 = _make(201)
densenet264 = _make(264)
