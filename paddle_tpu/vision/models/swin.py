"""Swin Transformer family (hierarchical shifted-window attention).

Reference surface: the Paddle-ecosystem Swin (upstream PaddleClas
ppcls/arch/backbone/model_zoo/swin_transformer.py, unverified — see
SURVEY.md §2.2 "Vision"): 4-stage hierarchy (patch merging halves the
grid and doubles channels), W×W windowed attention with a learned
relative-position-bias table, and a cyclic-shift on every second block
whose cross-region pairs are masked. Parity is tested against the
`transformers` torch implementation by weight transplant
(tests/test_models_swin.py).

TPU-first notes:
- Window partitioning is pure STATIC reshapes/transposes ([B, H/w, w,
  W/w, w, C] → [B·nW, w², C]) — no gather, no dynamic shapes; XLA fuses
  them into the surrounding matmuls' layouts.
- The shifted-window attention mask and the relative-position index are
  compile-time numpy constants (per stage resolution), so the whole
  forward is one XLA program with only MXU matmuls and elementwise ops.
- The cyclic shift is jnp.roll (lax.concatenate of two slices) — cheap
  on TPU, differentiable, and shape-preserving.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import paddle_tpu as P
from ...nn import Dropout, GELU, Layer, LayerList, LayerNorm, Linear
from ...nn import functional as F
from ...nn.conv import Conv2D

__all__ = ["SwinTransformer", "SwinConfig", "swin_t", "swin_s", "swin_b"]


@dataclass
class SwinConfig:
    image_size: int = 224
    patch_size: int = 4
    num_channels: int = 3
    embed_dim: int = 96
    depths: tuple = (2, 2, 6, 2)
    num_heads: tuple = (3, 6, 12, 24)
    window_size: int = 7
    mlp_ratio: float = 4.0
    dropout: float = 0.0
    attention_dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    num_classes: int = 1000

    @staticmethod
    def tiny(**kw):
        return SwinConfig(**{**dict(
            image_size=32, patch_size=4, embed_dim=32, depths=(2, 2),
            num_heads=(2, 4), window_size=4, mlp_ratio=2.0,
            num_classes=10), **kw})


def _rel_index(w):
    """[w², w²] int index into the (2w-1)² relative-bias table."""
    coords = np.stack(np.meshgrid(np.arange(w), np.arange(w),
                                  indexing="ij")).reshape(2, -1)
    rel = (coords[:, :, None] - coords[:, None, :]).transpose(1, 2, 0)
    rel = rel + np.array([w - 1, w - 1])
    return (rel[..., 0] * (2 * w - 1) + rel[..., 1]).astype(np.int32)


def _shift_mask(h, w_grid, w, s):
    """[nW, w², w²] additive mask (-100 across shifted-region pairs)."""
    img = np.zeros((h, w_grid), np.int32)
    cnt = 0
    for hs in (slice(0, -w), slice(-w, -s), slice(-s, None)):
        for ws in (slice(0, -w), slice(-w, -s), slice(-s, None)):
            img[hs, ws] = cnt
            cnt += 1
    m = img.reshape(h // w, w, w_grid // w, w).transpose(
        0, 2, 1, 3).reshape(-1, w * w)
    return np.where(m[:, None, :] != m[:, :, None], -100.0,
                    0.0).astype(np.float32)


def _partition(x, w):
    """[B, H, W, C] -> [B·nW, w², C] (static reshapes only)."""
    b, h, wg, c = x.shape
    x = x.reshape([b, h // w, w, wg // w, w, c])
    x = x.transpose([0, 1, 3, 2, 4, 5])
    return x.reshape([-1, w * w, c])


def _unpartition(x, w, h, wg):
    """[B·nW, w², C] -> [B, H, W, C]."""
    c = x.shape[-1]
    x = x.reshape([-1, h // w, wg // w, w, w, c])
    x = x.transpose([0, 1, 3, 2, 4, 5])
    return x.reshape([-1, h, wg, c])


class WindowAttention(Layer):
    def __init__(self, d, nh, w, attn_dropout=0.0):
        super().__init__()
        self.nh = nh
        self.hd = d // nh
        self.w = w
        self.attn_dropout = attn_dropout
        self.query = Linear(d, d)
        self.key = Linear(d, d)
        self.value = Linear(d, d)
        self.proj = Linear(d, d)
        self.relative_position_bias_table = self.create_parameter(
            ((2 * w - 1) ** 2, nh))
        self._rel_idx = _rel_index(w).reshape(-1)  # static constant

    def _bias(self):
        """[1, nh, w², w²] gathered from the learned table."""
        tbl = self.relative_position_bias_table
        flat = tbl[P.to_tensor(self._rel_idx)]  # [w⁴, nh]
        w2 = self.w * self.w
        return flat.reshape([w2, w2, self.nh]).transpose(
            [2, 0, 1]).unsqueeze(0)

    def forward(self, x, mask=None):
        """x [Bw, w², C]; mask [nW, w², w²] additive or None."""
        bw, n = x.shape[0], x.shape[1]
        qkv_w = P.concat([self.query.weight, self.key.weight,
                          self.value.weight], axis=1)
        qkv_b = P.concat([self.query.bias, self.key.bias,
                          self.value.bias])
        qkv = F.linear(x, qkv_w, qkv_b).reshape([bw, n, 3, self.nh,
                                                 self.hd])
        q = qkv[:, :, 0].transpose([0, 2, 1, 3]) * (self.hd ** -0.5)
        k = qkv[:, :, 1].transpose([0, 2, 1, 3])
        v = qkv[:, :, 2].transpose([0, 2, 1, 3])
        attn = P.matmul(q, k.transpose([0, 1, 3, 2])) + self._bias()
        if mask is not None:
            nw = mask.shape[0]
            attn = attn.reshape([bw // nw, nw, self.nh, n, n]) + \
                mask.unsqueeze(1).unsqueeze(0)
            attn = attn.reshape([bw, self.nh, n, n])
        attn = F.softmax(attn, axis=-1)
        if self.attn_dropout > 0.0:
            # reference semantics: dropout on the attention
            # PROBABILITIES (links), after the softmax
            attn = F.dropout(attn, p=self.attn_dropout,
                             training=self.training)
        out = P.matmul(attn, v).transpose([0, 2, 1, 3]).reshape(
            [bw, n, self.nh * self.hd])
        return self.proj(out)


class SwinBlock(Layer):
    def __init__(self, d, nh, resolution, w, shift, mlp_ratio, eps,
                 dropout, attn_dropout=0.0, shift_mask=None):
        super().__init__()
        self.res = resolution
        # reference behavior: no window beyond the grid, no shift then
        self.w = min(w, resolution)
        self.shift = 0 if resolution <= w else shift
        self.norm_before = LayerNorm(d, eps)
        self.attn = WindowAttention(d, nh, self.w,
                                    attn_dropout=attn_dropout)
        self.norm_after = LayerNorm(d, eps)
        hidden = int(d * mlp_ratio)
        self.mlp_in = Linear(d, hidden)
        self.mlp_out = Linear(hidden, d)
        self.act = GELU()
        self.dropout = Dropout(dropout)
        # the [nW, w², w²] mask is shared per stage (SwinStage owns the
        # single device copy) — per-block copies would bake duplicate
        # constants into jitted programs (CLAUDE.md large-constant rule)
        self._mask = shift_mask if self.shift > 0 else None

    def forward(self, x):
        """x [B, H·W, C] (token layout between blocks, matching the
        reference)."""
        b, c = x.shape[0], x.shape[2]
        h = wg = self.res
        shortcut = x
        x = self.norm_before(x).reshape([b, h, wg, c])
        if self.shift:
            x = P.roll(x, shifts=[-self.shift, -self.shift], axis=[1, 2])
        xw = _partition(x, self.w)
        xw = self.attn(xw, mask=self._mask)
        x = _unpartition(xw, self.w, h, wg)
        if self.shift:
            x = P.roll(x, shifts=[self.shift, self.shift],
                       axis=[1, 2])
        x = shortcut + self.dropout(x.reshape([b, h * wg, c]))
        y = self.mlp_out(self.act(self.mlp_in(self.norm_after(x))))
        return x + self.dropout(y)


class PatchMerging(Layer):
    """[B, H·W, C] -> [B, (H/2)·(W/2), 2C]: 2×2 concat → norm →
    bias-free reduction (reference order)."""

    def __init__(self, d, resolution, eps):
        super().__init__()
        self.res = resolution
        self.norm = LayerNorm(4 * d, eps)
        self.reduction = Linear(4 * d, 2 * d, bias_attr=False)

    def forward(self, x):
        b, c = x.shape[0], x.shape[2]
        h = wg = self.res
        x = x.reshape([b, h, wg, c])
        x = P.concat([x[:, 0::2, 0::2], x[:, 1::2, 0::2],
                      x[:, 0::2, 1::2], x[:, 1::2, 1::2]], axis=-1)
        x = x.reshape([b, (h // 2) * (wg // 2), 4 * c])
        return self.reduction(self.norm(x))


class SwinStage(Layer):
    def __init__(self, d, nh, depth, resolution, w, mlp_ratio, eps,
                 dropout, downsample, attn_dropout=0.0):
        super().__init__()
        weff = min(w, resolution)
        shift = 0 if resolution <= w else weff // 2
        mask = (P.to_tensor(_shift_mask(resolution, resolution, weff,
                                        shift))
                if shift > 0 and depth > 1 else None)  # one device copy
        self.blocks = LayerList([
            SwinBlock(d, nh, resolution, w,
                      shift=(0 if i % 2 == 0 else w // 2),
                      mlp_ratio=mlp_ratio, eps=eps, dropout=dropout,
                      attn_dropout=attn_dropout, shift_mask=mask)
            for i in range(depth)])
        self.downsample = (PatchMerging(d, resolution, eps)
                           if downsample else None)

    def forward(self, x):
        for blk in self.blocks:
            x = blk(x)
        if self.downsample is not None:
            x = self.downsample(x)
        return x


class SwinTransformer(Layer):
    def __init__(self, cfg: SwinConfig):
        super().__init__()
        self.cfg = cfg
        self.patch_embed = Conv2D(cfg.num_channels, cfg.embed_dim,
                                  cfg.patch_size, stride=cfg.patch_size)
        self.embed_norm = LayerNorm(cfg.embed_dim, cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.dropout)
        res = cfg.image_size // cfg.patch_size
        # Unlike the reference (which pads odd grids), this build keeps
        # every shape static for XLA — validate divisibility up front
        # instead of crashing with an opaque reshape error mid-forward.
        r = res
        for i in range(len(cfg.depths)):
            w = min(cfg.window_size, r)
            if r % w != 0:
                raise ValueError(
                    f"stage {i} grid {r}x{r} is not divisible by "
                    f"window_size {w}; pick image_size/patch_size/"
                    f"window_size so every stage grid divides the "
                    f"window (reference behavior pads instead)")
            if i < len(cfg.depths) - 1 and r % 2 != 0:
                raise ValueError(
                    f"stage {i} grid {r}x{r} is odd — PatchMerging "
                    f"needs even grids at every non-final stage")
            r //= 2
        stages = []
        d = cfg.embed_dim
        for i, (depth, nh) in enumerate(zip(cfg.depths, cfg.num_heads)):
            last = i == len(cfg.depths) - 1
            stages.append(SwinStage(
                d, nh, depth, res, cfg.window_size, cfg.mlp_ratio,
                cfg.layer_norm_eps, cfg.dropout, downsample=not last,
                attn_dropout=cfg.attention_dropout))
            if not last:
                d *= 2
                res //= 2
        self.stages = LayerList(stages)
        self.norm = LayerNorm(d, cfg.layer_norm_eps)
        self.head = (Linear(d, cfg.num_classes)
                     if cfg.num_classes else None)

    def forward_features(self, x):
        """[B, C, H, W] -> (tokens [B, N, D], pooled [B, D])."""
        x = self.patch_embed(x)
        b, d = x.shape[0], x.shape[1]
        x = x.reshape([b, d, -1]).transpose([0, 2, 1])
        x = self.dropout(self.embed_norm(x))
        for stage in self.stages:
            x = stage(x)
        x = self.norm(x)
        return x, x.mean(axis=1)

    def forward(self, x):
        tokens, pooled = self.forward_features(x)
        if self.head is None:
            return tokens, pooled
        return self.head(pooled)


def swin_t(num_classes=1000, **kw):
    return SwinTransformer(SwinConfig(num_classes=num_classes, **kw))


def swin_s(num_classes=1000, **kw):
    return SwinTransformer(SwinConfig(
        depths=(2, 2, 18, 2), num_classes=num_classes, **kw))


def swin_b(num_classes=1000, **kw):
    return SwinTransformer(SwinConfig(
        embed_dim=128, num_heads=(4, 8, 16, 32), depths=(2, 2, 18, 2),
        num_classes=num_classes, **kw))
