"""U-Net semantic-segmentation family.

Reference surface: the Paddle-ecosystem segmentation stack (upstream
PaddleSeg paddleseg/models/unet.py, unverified — see SURVEY.md §2.2
"Vision"): double-conv encoder stages with max-pool downsampling,
transposed-conv upsampling with skip concatenation, and a 1×1
classifier head; trained with cross-entropy (+ optional dice). The
end-to-end evidence is a synthetic-mask overfit that must reach high
IoU (tests/test_models_unet.py).

TPU-first notes:
- Static-shape conv/pool/transpose-conv chain — one XLA program per
  image size; the transposed convs ride the grouped-kernel-transpose
  lowering in nn.functional.conv2d_transpose.
- Per-pixel cross-entropy reshapes [B, C, H, W] → [B·H·W, C] once; XLA
  fuses the softmax into the final 1×1 conv epilogue.
"""
from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as P
from ...nn import (BatchNorm2D, Conv2D, Conv2DTranspose, Layer,
                   LayerList, MaxPool2D, ReLU, Sequential)
from ...nn import functional as F

__all__ = ["UNet", "UNetConfig", "unet"]


@dataclass
class UNetConfig:
    in_channels: int = 3
    num_classes: int = 19
    base_channels: int = 64
    depth: int = 4   # number of down/up stages

    @staticmethod
    def tiny(**kw):
        return UNetConfig(**{**dict(
            in_channels=1, num_classes=3, base_channels=8,
            depth=2), **kw})


def _double_conv(cin, cout):
    return Sequential(
        Conv2D(cin, cout, 3, padding=1, bias_attr=False),
        BatchNorm2D(cout), ReLU(),
        Conv2D(cout, cout, 3, padding=1, bias_attr=False),
        BatchNorm2D(cout), ReLU())


class UNet(Layer):
    def __init__(self, cfg: UNetConfig):
        super().__init__()
        self.cfg = cfg
        c = cfg.base_channels
        self.inc = _double_conv(cfg.in_channels, c)
        downs = []
        for i in range(cfg.depth):
            downs.append(_double_conv(c * 2 ** i, c * 2 ** (i + 1)))
        self.downs = LayerList(downs)
        self.pool = MaxPool2D(2)
        ups, upconvs = [], []
        for i in reversed(range(cfg.depth)):
            upconvs.append(Conv2DTranspose(c * 2 ** (i + 1), c * 2 ** i,
                                           2, stride=2))
            ups.append(_double_conv(c * 2 ** (i + 1), c * 2 ** i))
        self.upconvs = LayerList(upconvs)
        self.ups = LayerList(ups)
        self.head = Conv2D(c, cfg.num_classes, 1)

    def forward(self, x):
        """[B, C, H, W] -> per-pixel logits [B, num_classes, H, W]
        (H, W divisible by 2**depth)."""
        h = self.inc(x)
        skips = [h]
        for down in self.downs:
            h = down(self.pool(h))
            skips.append(h)
        skips.pop()
        for upconv, up in zip(self.upconvs, self.ups):
            h = upconv(h)
            h = up(P.concat([skips.pop(), h], axis=1))
        return self.head(h)

    def loss(self, logits, labels, dice_weight=0.0):
        """Per-pixel CE (+ optional dice). labels [B, H, W] int."""
        c = logits.shape[1]
        flat = logits.transpose([0, 2, 3, 1]).reshape([-1, c])
        ce = F.cross_entropy(flat, labels.reshape([-1]))
        if dice_weight:
            probs = F.softmax(logits, axis=1)
            oneh = F.one_hot(labels, c).transpose([0, 3, 1, 2])
            inter = (probs * oneh).sum(axis=[2, 3])
            denom = probs.sum(axis=[2, 3]) + oneh.sum(axis=[2, 3])
            dice = 1.0 - (2.0 * inter / (denom + 1e-5)).mean()
            ce = ce + dice_weight * dice
        return ce


def unet(num_classes=19, **kw):
    return UNet(UNetConfig(num_classes=num_classes, **kw))
