"""Inception v3.

Reference parity: paddle.vision.models.inception_v3 (upstream
python/paddle/vision/models/inceptionv3.py — unverified, SURVEY.md §2.2).
Compact faithful topology (A/B/C/D/E blocks); aux head omitted in eval.
"""
from ... import nn
from ...ops import manipulation as M


def _conv(cin, cout, k, **kw):
    return nn.Sequential(nn.Conv2D(cin, cout, k, bias_attr=False, **kw),
                         nn.BatchNorm2D(cout), nn.ReLU())


def _cat(xs):
    return M.concat(xs, axis=1)


class _InceptionA(nn.Layer):
    def __init__(self, cin, pool_c):
        super().__init__()
        self.b1 = _conv(cin, 64, 1)
        self.b5 = nn.Sequential(_conv(cin, 48, 1),
                                _conv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_conv(cin, 64, 1),
                                _conv(64, 96, 3, padding=1),
                                _conv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _conv(cin, pool_c, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)])


class _InceptionB(nn.Layer):  # grid reduction 35->17
    def __init__(self, cin):
        super().__init__()
        self.b3 = _conv(cin, 384, 3, stride=2)
        self.b3d = nn.Sequential(_conv(cin, 64, 1),
                                 _conv(64, 96, 3, padding=1),
                                 _conv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat([self.b3(x), self.b3d(x), self.pool(x)])


class _InceptionC(nn.Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = _conv(cin, 192, 1)
        self.b7 = nn.Sequential(
            _conv(cin, c7, 1), _conv(c7, c7, (1, 7), padding=(0, 3)),
            _conv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _conv(cin, c7, 1), _conv(c7, c7, (7, 1), padding=(3, 0)),
            _conv(c7, c7, (1, 7), padding=(0, 3)),
            _conv(c7, c7, (7, 1), padding=(3, 0)),
            _conv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _conv(cin, 192, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)])


class _InceptionD(nn.Layer):  # grid reduction 17->8
    def __init__(self, cin):
        super().__init__()
        self.b3 = nn.Sequential(_conv(cin, 192, 1),
                                _conv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _conv(cin, 192, 1), _conv(192, 192, (1, 7), padding=(0, 3)),
            _conv(192, 192, (7, 1), padding=(3, 0)),
            _conv(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat([self.b3(x), self.b7(x), self.pool(x)])


class _InceptionE(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = _conv(cin, 320, 1)
        self.b3_stem = _conv(cin, 384, 1)
        self.b3_a = _conv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _conv(384, 384, (3, 1), padding=(1, 0))
        self.bd_stem = nn.Sequential(_conv(cin, 448, 1),
                                     _conv(448, 384, 3, padding=1))
        self.bd_a = _conv(384, 384, (1, 3), padding=(0, 1))
        self.bd_b = _conv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _conv(cin, 192, 1))

    def forward(self, x):
        s3 = self.b3_stem(x)
        sd = self.bd_stem(x)
        return _cat([self.b1(x),
                     _cat([self.b3_a(s3), self.b3_b(s3)]),
                     _cat([self.bd_a(sd), self.bd_b(sd)]),
                     self.bp(x)])


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.stem = nn.Sequential(
            _conv(3, 32, 3, stride=2), _conv(32, 32, 3),
            _conv(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _conv(64, 80, 1), _conv(80, 192, 3), nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.drop = nn.Dropout(0.5)
        self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        return self.fc(self.drop(self.avgpool(x).flatten(1)))


def inception_v3(pretrained=False, **kw):
    assert not pretrained
    return InceptionV3(**kw)
