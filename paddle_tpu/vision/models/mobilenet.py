"""MobileNetV2 (reference: paddle.vision.models.mobilenet_v2)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout, Layer,
                   Linear, ReLU6, Sequential)


class ConvBNReLU(Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1):
        super().__init__(
            Conv2D(in_c, out_c, kernel, stride=stride,
                   padding=(kernel - 1) // 2, groups=groups,
                   bias_attr=False),
            BatchNorm2D(out_c), ReLU6())


class InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(inp, hidden, kernel=1))
        layers += [
            ConvBNReLU(hidden, hidden, stride=stride, groups=hidden),
            Conv2D(hidden, oup, 1, bias_attr=False),
            BatchNorm2D(oup)]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
               (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
               (6, 320, 1, 1)]
        in_c = int(32 * scale)
        features = [ConvBNReLU(3, in_c, stride=2)]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        self.last_c = int(1280 * max(1.0, scale))
        features.append(ConvBNReLU(in_c, self.last_c, kernel=1))
        self.features = Sequential(*features)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(self.last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("no pretrained weights in this env")
    return MobileNetV2(scale=scale, **kwargs)
