"""Vision Transformer family (ViT-B/16, ViT-B/32, ViT-L/16, ViT-H/14).

Reference surface: the standard pre-LN ViT (Dosovitskiy et al.) as
shipped in the Paddle ecosystem's classification model zoo (upstream
PaddleClas ppcls/arch/backbone/model_zoo/vision_transformer.py,
unverified — see SURVEY.md §2.2 "Vision"). Parity is tested against the
`transformers` torch implementation by weight transplant
(tests/test_models_vit_t5.py).

TPU-first notes:
- Patch embedding is a Conv2D with kernel=stride=patch — XLA lowers a
  non-overlapping conv to one [N_patches, P²·C]×[P²·C, H] matmul, which
  is exactly the MXU-friendly shape (ViT-B/16: 768-wide, 6 MXU tiles).
- The encoder is pre-LN (LN → attn → residual, LN → MLP → residual) —
  one fused attention per layer via scaled_dot_product_attention, which
  routes to the Pallas flash kernel at supported shapes.
- CLS token + learned position table are plain parameters broadcast in
  the traced program; no dynamic shapes anywhere, so a single XLA
  computation covers the whole forward.
"""
from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as P
from ...nn import (Conv2D, Dropout, GELU, Layer, LayerList, LayerNorm,
                   Linear)
from ...nn import functional as F

__all__ = ["VisionTransformer", "ViTConfig", "vit_b_16", "vit_b_32",
           "vit_l_16", "vit_h_14"]


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    layer_norm_eps: float = 1e-12
    num_classes: int = 1000

    @staticmethod
    def tiny(**kw):
        return ViTConfig(**{**dict(
            image_size=32, patch_size=8, hidden_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            intermediate_size=128, num_classes=10), **kw})


class PatchEmbed(Layer):
    def __init__(self, cfg: ViTConfig):
        super().__init__()
        self.projection = Conv2D(cfg.num_channels, cfg.hidden_size,
                                 cfg.patch_size, stride=cfg.patch_size)
        self.num_patches = (cfg.image_size // cfg.patch_size) ** 2

    def forward(self, x):
        # [B, C, H, W] -> [B, hidden, H/P, W/P] -> [B, N, hidden]
        x = self.projection(x)
        b, h = x.shape[0], x.shape[1]
        return x.reshape([b, h, -1]).transpose([0, 2, 1])


class ViTLayer(Layer):
    """Pre-LN transformer block (LN→MHA→res, LN→MLP→res)."""

    def __init__(self, cfg: ViTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.nh = cfg.num_attention_heads
        self.hd = h // self.nh
        self.norm_before = LayerNorm(h, cfg.layer_norm_eps)
        self.q = Linear(h, h)
        self.k = Linear(h, h)
        self.v = Linear(h, h)
        self.attn_out = Linear(h, h)
        self.norm_after = LayerNorm(h, cfg.layer_norm_eps)
        self.mlp_in = Linear(h, cfg.intermediate_size)
        self.mlp_out = Linear(cfg.intermediate_size, h)
        self.act = GELU()
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.attn_dropout_p = cfg.attention_probs_dropout_prob

    def forward(self, x):
        b, s = x.shape[0], x.shape[1]
        y = self.norm_before(x)
        # fused QKV (one [h, 3h] matmul; see models/bert.py for the MXU
        # rationale) while keeping the reference per-projection params
        qkv_w = P.concat([self.q.weight, self.k.weight, self.v.weight],
                         axis=1)
        qkv_b = P.concat([self.q.bias, self.k.bias, self.v.bias])
        qkv = F.linear(y, qkv_w, qkv_b).reshape([b, s, 3, self.nh,
                                                 self.hd])
        q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        ctx = F.scaled_dot_product_attention(
            q, k, v, dropout_p=self.attn_dropout_p,
            training=self.training)
        x = x + self.dropout(self.attn_out(
            ctx.reshape([b, s, self.nh * self.hd])))
        y = self.mlp_out(self.act(self.mlp_in(self.norm_after(x))))
        return x + self.dropout(y)


class VisionTransformer(Layer):
    def __init__(self, cfg: ViTConfig):
        super().__init__()
        self.cfg = cfg
        self.patch_embed = PatchEmbed(cfg)
        n = self.patch_embed.num_patches
        self.cls_token = self.create_parameter((1, 1, cfg.hidden_size))
        self.position_embeddings = self.create_parameter(
            (1, n + 1, cfg.hidden_size))
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.encoder = LayerList([ViTLayer(cfg)
                                  for _ in range(cfg.num_hidden_layers)])
        self.norm = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps)
        self.head = (Linear(cfg.hidden_size, cfg.num_classes)
                     if cfg.num_classes else None)

    def forward_features(self, x):
        x = self.patch_embed(x)
        cls = P.expand(self.cls_token, [x.shape[0], 1, self.cfg.hidden_size])
        x = P.concat([cls, x], axis=1) + self.position_embeddings
        x = self.dropout(x)
        for layer in self.encoder:
            x = layer(x)
        return self.norm(x)

    def forward(self, x):
        feats = self.forward_features(x)
        if self.head is None:
            return feats
        return self.head(feats[:, 0])


def _vit(**kw):
    return VisionTransformer(ViTConfig(**kw))


def vit_b_16(num_classes=1000, **kw):
    return _vit(num_classes=num_classes, **kw)


def vit_b_32(num_classes=1000, **kw):
    return _vit(patch_size=32, num_classes=num_classes, **kw)


def vit_l_16(num_classes=1000, **kw):
    return _vit(hidden_size=1024, num_hidden_layers=24,
                num_attention_heads=16, intermediate_size=4096,
                num_classes=num_classes, **kw)


def vit_h_14(num_classes=1000, **kw):
    return _vit(patch_size=14, hidden_size=1280, num_hidden_layers=32,
                num_attention_heads=16, intermediate_size=5120,
                num_classes=num_classes, **kw)
