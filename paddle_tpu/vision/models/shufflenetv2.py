"""ShuffleNetV2.

Reference parity: paddle.vision.models.shufflenet_v2_* (upstream
python/paddle/vision/models/shufflenetv2.py — unverified, SURVEY.md §2.2).
Channel shuffle is a reshape/transpose pair — pure layout ops XLA folds.
"""
from ... import nn
from ...ops import manipulation as M

_CFG = {
    "0.5": (24, (48, 96, 192), 1024),
    "1.0": (24, (116, 232, 464), 1024),
    "1.5": (24, (176, 352, 704), 1024),
    "2.0": (24, (244, 488, 976), 2048),
}
_REPEATS = (4, 8, 4)


def _channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = x.reshape([b, groups, c // groups, h, w])
    x = x.transpose([0, 2, 1, 3, 4])
    return x.reshape([b, c, h, w])


def _conv_bn(cin, cout, k, stride=1, groups=1, act=True):
    pad = k // 2
    layers = [nn.Conv2D(cin, cout, k, stride=stride, padding=pad,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(cout)]
    if act:
        layers.append(nn.ReLU())
    return nn.Sequential(*layers)


class _InvertedResidual(nn.Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.stride = stride
        branch = cout // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(cin // 2, branch, 1),
                _conv_bn(branch, branch, 3, stride, groups=branch,
                         act=False),
                _conv_bn(branch, branch, 1))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(cin, cin, 3, stride, groups=cin, act=False),
                _conv_bn(cin, branch, 1))
            self.branch2 = nn.Sequential(
                _conv_bn(cin, branch, 1),
                _conv_bn(branch, branch, 3, stride, groups=branch,
                         act=False),
                _conv_bn(branch, branch, 1))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = M.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = M.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale="1.0", num_classes=1000):
        super().__init__()
        init_c, stages, final_c = _CFG[str(scale)]
        self.conv1 = _conv_bn(3, init_c, 3, stride=2)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        cin = init_c
        for cout, rep in zip(stages, _REPEATS):
            blocks.append(_InvertedResidual(cin, cout, stride=2))
            for _ in range(rep - 1):
                blocks.append(_InvertedResidual(cout, cout, stride=1))
            cin = cout
        self.stages = nn.Sequential(*blocks)
        self.conv5 = _conv_bn(cin, final_c, 1)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(final_c, num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv5(self.stages(x))
        return self.fc(self.pool(x).flatten(1))


def _make(scale):
    def f(pretrained=False, **kw):
        assert not pretrained
        return ShuffleNetV2(scale, **kw)
    f.__name__ = f"shufflenet_v2_x{scale.replace('.', '_')}"
    return f


shufflenet_v2_x0_5 = _make("0.5")
shufflenet_v2_x1_0 = _make("1.0")
shufflenet_v2_x1_5 = _make("1.5")
shufflenet_v2_x2_0 = _make("2.0")
