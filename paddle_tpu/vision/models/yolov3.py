"""YOLOv3 detection family: DarkNet-53 backbone + FPN neck + 3 heads.

Reference surface: the Paddle-ecosystem YOLOv3 (upstream
PaddleDetection ppdet/modeling/architectures/yolo.py +
backbones/darknet.py + necks/yolo_fpn.py, unverified — see SURVEY.md
§2.2 "Vision"). This assembles the already-oracle-tested op layer —
`vision.ops.yolo_loss` (analytic-oracle-exact), `yolo_box`, `nms` —
into the full trainable/deployable architecture: conv-BN-LeakyReLU
DarkNet residual stages → per-level 5-conv blocks with upsample routes
→ A·(5+C)-channel raw heads; training sums the three per-level YOLO
losses, inference decodes all levels with `yolo_box` and fuses them
through class-aware NMS.

TPU-first notes:
- The whole forward is static-shape convs (MXU via XLA) — one program
  per image size; nearest-neighbor upsampling is a reshape-broadcast.
- Training targets are built inside `yolo_loss`'s dense scatter maps —
  no ragged per-image host work in the step.
- Inference: the forward + yolo_box decode + mask-scan NMS ops are all
  jit-able device programs; `predict`'s per-image box assembly
  (thresholding, row packing) is host-side by design, after ONE
  batched device→host fetch.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

import paddle_tpu as P
from ... import vision
from ...nn import (BatchNorm2D, Layer, LayerList, LeakyReLU,
                   Sequential)
from ...nn import functional as F
from ...nn.conv import Conv2D

__all__ = ["YOLOv3", "YOLOv3Config", "DarkNet53", "yolov3_darknet53"]

_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119, 116, 90,
            156, 198, 373, 326]
_MASKS = ([6, 7, 8], [3, 4, 5], [0, 1, 2])


@dataclass
class YOLOv3Config:
    num_classes: int = 80
    anchors: tuple = tuple(_ANCHORS)
    anchor_masks: tuple = _MASKS
    ignore_thresh: float = 0.7
    stem_channels: int = 32
    depths: tuple = (1, 2, 8, 8, 4)  # DarkNet-53 residual counts
    nms_top_k: int = 100
    score_thresh: float = 0.01
    nms_iou: float = 0.45

    @staticmethod
    def tiny(**kw):
        return YOLOv3Config(**{**dict(
            num_classes=2, stem_channels=8, depths=(1, 1, 1, 1, 1),
            ignore_thresh=0.5), **kw})


def _conv_bn(cin, cout, k, stride=1):
    return Sequential(
        Conv2D(cin, cout, k, stride=stride, padding=k // 2,
               bias_attr=False),
        BatchNorm2D(cout), LeakyReLU(0.1))


class _Residual(Layer):
    def __init__(self, c):
        super().__init__()
        self.conv1 = _conv_bn(c, c // 2, 1)
        self.conv2 = _conv_bn(c // 2, c, 3)

    def forward(self, x):
        return x + self.conv2(self.conv1(x))


class DarkNet53(Layer):
    """Returns (C3, C4, C5) features at strides 8/16/32."""

    def __init__(self, cfg: YOLOv3Config):
        super().__init__()
        c = cfg.stem_channels
        self.stem = _conv_bn(3, c, 3)
        downs, stages = [], []
        for i, depth in enumerate(cfg.depths):
            downs.append(_conv_bn(c * 2 ** i, c * 2 ** (i + 1), 3,
                                  stride=2))
            stages.append(Sequential(*[
                _Residual(c * 2 ** (i + 1)) for _ in range(depth)]))
        self.downs = LayerList(downs)
        self.stages = LayerList(stages)

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for down, stage in zip(self.downs, self.stages):
            x = stage(down(x))
            feats.append(x)
        return feats[-3], feats[-2], feats[-1]


class _NeckBlock(Layer):
    """The 5-conv YOLOv3 block; exposes the route (for upsampling) and
    the head input."""

    def __init__(self, cin, cmid):
        super().__init__()
        self.body = Sequential(
            _conv_bn(cin, cmid, 1), _conv_bn(cmid, cmid * 2, 3),
            _conv_bn(cmid * 2, cmid, 1), _conv_bn(cmid, cmid * 2, 3),
            _conv_bn(cmid * 2, cmid, 1))
        self.tip = _conv_bn(cmid, cmid * 2, 3)

    def forward(self, x):
        route = self.body(x)
        return route, self.tip(route)


class YOLOv3(Layer):
    def __init__(self, cfg: YOLOv3Config):
        super().__init__()
        if len(cfg.depths) != 5:
            # neck widths and head strides (32/16/8) assume the 5-stage
            # DarkNet pyramid; other depths would silently corrupt
            # target assignment via wrong downsample ratios
            raise ValueError(
                f"YOLOv3 requires exactly 5 backbone stages, got "
                f"depths={cfg.depths}")
        self.cfg = cfg
        self.backbone = DarkNet53(cfg)
        c = cfg.stem_channels
        c5, c4, c3 = c * 32, c * 16, c * 8
        a = len(cfg.anchor_masks[0])
        out_ch = a * (5 + cfg.num_classes)
        self.block5 = _NeckBlock(c5, c5 // 2)
        self.route5 = _conv_bn(c5 // 2, c4 // 2, 1)
        self.block4 = _NeckBlock(c4 + c4 // 2, c4 // 2)
        self.route4 = _conv_bn(c4 // 2, c3 // 2, 1)
        self.block3 = _NeckBlock(c3 + c3 // 2, c3 // 2)
        self.head5 = Conv2D(c5, out_ch, 1)
        self.head4 = Conv2D(c4, out_ch, 1)
        self.head3 = Conv2D(c3, out_ch, 1)

    def forward(self, img):
        """img [N, 3, H, W] -> three raw head maps (strides 32/16/8)."""
        c3, c4, c5 = self.backbone(img)
        r5, t5 = self.block5(c5)
        up5 = F.interpolate(self.route5(r5), scale_factor=2,
                            mode="nearest")
        r4, t4 = self.block4(P.concat([up5, c4], axis=1))
        up4 = F.interpolate(self.route4(r4), scale_factor=2,
                            mode="nearest")
        _, t3 = self.block3(P.concat([up4, c3], axis=1))
        return self.head5(t5), self.head4(t4), self.head3(t3)

    def get_loss(self, outputs, gt_box, gt_label, gt_score=None):
        """Sum of the three per-level YOLO losses (mean over batch)."""
        cfg = self.cfg
        total = None
        for out, mask, down in zip(outputs, cfg.anchor_masks,
                                   (32, 16, 8)):
            loss = vision.ops.yolo_loss(
                out, gt_box, gt_label, list(cfg.anchors), list(mask),
                cfg.num_classes, cfg.ignore_thresh, down,
                gt_score=gt_score).mean()
            total = loss if total is None else total + loss
        return total

    def predict(self, img, img_size):
        """Decode + class-aware NMS. Returns per-image lists of
        (label, score, x1, y1, x2, y2) arrays (host-side assembly over
        device-computed decode/NMS)."""
        cfg = self.cfg
        outputs = self.forward(img)
        boxes_all, scores_all = [], []
        for out, mask, down in zip(outputs, cfg.anchor_masks,
                                   (32, 16, 8)):
            sub_anchors = []
            for m in mask:
                sub_anchors += [cfg.anchors[2 * m],
                                cfg.anchors[2 * m + 1]]
            b, s = vision.ops.yolo_box(
                out, img_size, sub_anchors, cfg.num_classes,
                conf_thresh=cfg.score_thresh, downsample_ratio=down)
            boxes_all.append(b)       # [N, M, 4]
            scores_all.append(s)      # [N, M, C]
        boxes = P.concat(boxes_all, axis=1)
        scores = P.concat(scores_all, axis=1)
        # ONE device->host fetch for the whole batch (each fetch pays
        # fixed relay overhead — CLAUDE.md axon measurement hygiene)
        sc_all = np.asarray(scores._data)         # [N, M, C]
        bx_all = np.asarray(boxes._data)          # [N, M, 4]
        results = []
        n, c = sc_all.shape[0], sc_all.shape[2]
        for i in range(n):
            sc = sc_all[i]                        # [M, C]
            bx = bx_all[i]                        # [M, 4]
            cls = sc.argmax(axis=1)
            best = sc.max(axis=1)
            keep_mask = best > cfg.score_thresh
            idx = np.nonzero(keep_mask)[0]
            if idx.size == 0:
                results.append(np.zeros((0, 6), np.float32))
                continue
            keep = vision.ops.nms(
                P.to_tensor(bx[idx]), iou_threshold=cfg.nms_iou,
                scores=P.to_tensor(best[idx]),
                category_idxs=P.to_tensor(cls[idx].astype(np.int64)),
                categories=list(range(c)), top_k=cfg.nms_top_k)
            kept = np.asarray(keep._data)
            rows = np.concatenate(
                [cls[idx][kept][:, None].astype(np.float32),
                 best[idx][kept][:, None].astype(np.float32),
                 bx[idx][kept]], axis=1)
            results.append(rows.astype(np.float32))
        return results


def yolov3_darknet53(num_classes=80, **kw):
    return YOLOv3(YOLOv3Config(num_classes=num_classes, **kw))
