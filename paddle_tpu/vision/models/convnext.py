"""ConvNeXt family (modernized convolutional backbone).

Reference surface: the Paddle-ecosystem ConvNeXt (upstream PaddleClas
ppcls/arch/backbone/model_zoo/convnext.py, unverified — see SURVEY.md
§2.2 "Vision"): 4-stage hierarchy of depthwise-7×7 blocks with
channels-last LayerNorm, a 4× pointwise MLP, learnable per-channel
layer scale, and 2×2 stride-2 downsample convs between stages. Parity
is tested against the `transformers` torch implementation by weight
transplant (tests/test_models_convnext.py).

TPU-first notes:
- The block body (LN → Linear 4C → GELU → Linear C → scale) runs in
  NHWC token layout, so both pointwise convs ARE MXU matmuls; only the
  depthwise 7×7 rides the conv unit (XLA feature_group_count).
- Layer scale is a [C] parameter broadcast — XLA fuses it into the
  pwconv2 epilogue.
"""
from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as P
from ...nn import GELU, Layer, LayerList, LayerNorm, Linear
from ...nn.conv import Conv2D

__all__ = ["ConvNeXt", "ConvNeXtConfig", "convnext_tiny",
           "convnext_small", "convnext_base"]

_INTERNAL_EPS = 1e-6  # reference-hardcoded for all non-final norms


@dataclass
class ConvNeXtConfig:
    num_channels: int = 3
    patch_size: int = 4
    hidden_sizes: tuple = (96, 192, 384, 768)
    depths: tuple = (3, 3, 9, 3)
    layer_scale_init: float = 1e-6
    layer_norm_eps: float = 1e-12
    num_classes: int = 1000

    @staticmethod
    def tiny(**kw):
        return ConvNeXtConfig(**{**dict(
            hidden_sizes=(16, 32, 64, 96), depths=(2, 2, 2, 2),
            num_classes=10), **kw})


class ConvNeXtBlock(Layer):
    def __init__(self, d, cfg: ConvNeXtConfig):
        super().__init__()
        self.dwconv = Conv2D(d, d, 7, padding=3, groups=d)
        # reference hardcodes eps=1e-6 on block/embed/downsample
        # norms; cfg.layer_norm_eps applies only to the final LN
        self.layernorm = LayerNorm(d, _INTERNAL_EPS)
        self.pwconv1 = Linear(d, 4 * d)
        self.pwconv2 = Linear(4 * d, d)
        self.act = GELU()
        self.layer_scale_parameter = self.create_parameter((d,))
        self.layer_scale_parameter.set_value(
            P.full([d], cfg.layer_scale_init))

    def forward(self, x):
        """x [B, C, H, W]."""
        y = self.dwconv(x)
        y = y.transpose([0, 2, 3, 1])  # NHWC: pointwise convs = matmuls
        y = self.pwconv2(self.act(self.pwconv1(self.layernorm(y))))
        y = self.layer_scale_parameter * y
        return x + y.transpose([0, 3, 1, 2])


class _ChannelsFirstLN(Layer):
    """LayerNorm over C of an NCHW tensor (reference embedding/downsample
    norm) — one transpose round-trip; XLA folds it into neighbors."""

    def __init__(self, d, eps):
        super().__init__()
        self.norm = LayerNorm(d, eps)

    def forward(self, x):
        return self.norm(x.transpose([0, 2, 3, 1])).transpose(
            [0, 3, 1, 2])


class ConvNeXt(Layer):
    def __init__(self, cfg: ConvNeXtConfig):
        super().__init__()
        self.cfg = cfg
        hs = cfg.hidden_sizes
        self.patch_embed = Conv2D(cfg.num_channels, hs[0],
                                  cfg.patch_size, stride=cfg.patch_size)
        self.embed_norm = _ChannelsFirstLN(hs[0], _INTERNAL_EPS)
        self.down_norms = LayerList([
            _ChannelsFirstLN(hs[i], _INTERNAL_EPS)
            for i in range(len(hs) - 1)])
        self.down_convs = LayerList([
            Conv2D(hs[i], hs[i + 1], 2, stride=2)
            for i in range(len(hs) - 1)])
        self.stages = LayerList([
            LayerList([ConvNeXtBlock(hs[i], cfg)
                       for _ in range(cfg.depths[i])])
            for i in range(len(hs))])
        self.norm = LayerNorm(hs[-1], cfg.layer_norm_eps)
        self.head = (Linear(hs[-1], cfg.num_classes)
                     if cfg.num_classes else None)

    def forward_features(self, x):
        """[B, C, H, W] -> pooled [B, D] (reference: LN of spatial
        mean)."""
        x = self.embed_norm(self.patch_embed(x))
        for i, stage in enumerate(self.stages):
            if i > 0:
                x = self.down_convs[i - 1](self.down_norms[i - 1](x))
            for blk in stage:
                x = blk(x)
        return self.norm(x.mean(axis=[2, 3]))

    def forward(self, x):
        pooled = self.forward_features(x)
        if self.head is None:
            return pooled
        return self.head(pooled)


def convnext_tiny(num_classes=1000, **kw):
    return ConvNeXt(ConvNeXtConfig(num_classes=num_classes, **kw))


def convnext_small(num_classes=1000, **kw):
    return ConvNeXt(ConvNeXtConfig(
        depths=(3, 3, 27, 3), num_classes=num_classes, **kw))


def convnext_base(num_classes=1000, **kw):
    return ConvNeXt(ConvNeXtConfig(
        hidden_sizes=(128, 256, 512, 1024), depths=(3, 3, 27, 3),
        num_classes=num_classes, **kw))
