"""paddle_tpu.vision.models — the reference's model zoo, TPU-native."""
from .alexnet import AlexNet, alexnet  # noqa: F401
from .densenet import (DenseNet, densenet121, densenet161,  # noqa: F401
                       densenet169, densenet201, densenet264)
from .googlenet import GoogLeNet, googlenet  # noqa: F401
from .inceptionv3 import InceptionV3, inception_v3  # noqa: F401
from .lenet import LeNet  # noqa: F401
from .mobilenet import MobileNetV2, mobilenet_v2  # noqa: F401
from .mobilenetv1 import MobileNetV1, mobilenet_v1  # noqa: F401
from .mobilenetv3 import (MobileNetV3, mobilenet_v3_large,  # noqa: F401
                          mobilenet_v3_small)
from .resnet import (ResNet, resnet18, resnet34, resnet50,  # noqa: F401
                     resnet101, resnet152, resnext50_32x4d,
                     resnext50_64x4d, resnext101_32x4d, resnext101_64x4d,
                     resnext152_32x4d, resnext152_64x4d,
                     wide_resnet50_2, wide_resnet101_2)
from .shufflenetv2 import (ShuffleNetV2, shufflenet_v2_x0_5,  # noqa: F401
                           shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                           shufflenet_v2_x2_0)
from .squeezenet import (SqueezeNet, squeezenet1_0,  # noqa: F401
                         squeezenet1_1)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .vit import (VisionTransformer, ViTConfig, vit_b_16,  # noqa: F401
                  vit_b_32, vit_l_16, vit_h_14)
from .swin import (SwinTransformer, SwinConfig, swin_t,  # noqa: F401
                   swin_s, swin_b)
from .convnext import (ConvNeXt, ConvNeXtConfig,  # noqa: F401
                       convnext_tiny, convnext_small, convnext_base)
from .yolov3 import (YOLOv3, YOLOv3Config, DarkNet53,  # noqa: F401
                     yolov3_darknet53)
from .unet import UNet, UNetConfig, unet  # noqa: F401
