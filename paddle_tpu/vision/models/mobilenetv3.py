"""MobileNetV3 small/large.

Reference parity: paddle.vision.models.mobilenet_v3_small/_large (upstream
python/paddle/vision/models/mobilenetv3.py — unverified, SURVEY.md §2.2).
"""
from ... import nn


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _conv_bn(cin, cout, k, stride=1, groups=1, act=None):
    layers = [nn.Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(cout)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "hardswish":
        layers.append(nn.Hardswish())
    return nn.Sequential(*layers)


class _SEModule(nn.Layer):
    def __init__(self, c, reduction=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, c // reduction, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(c // reduction, c, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, cin, exp, cout, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if exp != cin:
            layers.append(_conv_bn(cin, exp, 1, act=act))
        layers.append(_conv_bn(exp, exp, k, stride=stride, groups=exp,
                               act=act))
        if use_se:
            layers.append(_SEModule(exp))
        layers.append(_conv_bn(exp, cout, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        y = self.block(x)
        return x + y if self.use_res else y


_LARGE = [
    # k, exp, c, se, act, s
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_c, scale=1.0, num_classes=1000):
        super().__init__()
        cin = _make_divisible(16 * scale)
        layers = [_conv_bn(3, cin, 3, stride=2, act="hardswish")]
        for k, exp, c, se, act, s in config:
            cout = _make_divisible(c * scale)
            layers.append(_InvertedResidual(
                cin, _make_divisible(exp * scale), cout, k, s, se, act))
            cin = cout
        last_exp = _make_divisible(config[-1][1] * scale)
        layers.append(_conv_bn(cin, last_exp, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(
            nn.Linear(last_exp, last_c), nn.Hardswish(),
            nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x)).flatten(1)
        return self.classifier(x)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    assert not pretrained
    return MobileNetV3(_LARGE, 1280, scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    assert not pretrained
    return MobileNetV3(_SMALL, 1024, scale=scale, **kw)
