"""MobileNetV1.

Reference parity: paddle.vision.models.mobilenet_v1 (upstream
python/paddle/vision/models/mobilenetv1.py — unverified, SURVEY.md §2.2).
"""
from ... import nn


def _conv_bn(cin, cout, k, stride=1, groups=1):
    return nn.Sequential(
        nn.Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(cout), nn.ReLU())


class _DepthwiseSep(nn.Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.dw = _conv_bn(cin, cin, 3, stride=stride, groups=cin)
        self.pw = _conv_bn(cin, cout, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()

        def c(ch):
            return max(8, int(ch * scale))

        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
               (1024, 2), (1024, 1)]
        layers = [_conv_bn(3, c(32), 3, stride=2)]
        cin = c(32)
        for cout, stride in cfg:
            layers.append(_DepthwiseSep(cin, c(cout), stride))
            cin = c(cout)
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(cin, num_classes)

    def forward(self, x):
        return self.fc(self.pool(self.features(x)).flatten(1))


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    assert not pretrained
    return MobileNetV1(scale=scale, **kw)
