"""Vision datasets (reference: paddle.vision.datasets — upstream
python/paddle/vision/datasets/, unverified; see SURVEY.md §2.2).

Zero-egress environment: loaders read local archives when present
(`data_file=` arg); otherwise raise with a clear message. `FakeData`
provides deterministic synthetic data for tests/benchmarks (the config-1
CIFAR-10 milestone runs on it when the real archive is absent).
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100", "MNIST", "FashionMNIST", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic labelled images."""

    def __init__(self, num_samples=1000, image_shape=(3, 32, 32),
                 num_classes=10, mode="train", transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.images = rng.standard_normal(
            (num_samples,) + self.image_shape).astype(np.float32)
        self.labels = rng.integers(0, num_classes,
                                   (num_samples,)).astype(np.int32)
        # make labels learnable: bias the mean of each image by its label
        self.images += self.labels[:, None, None, None].astype(
            np.float32) / num_classes

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.num_samples


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.transform = transform
        self.mode = mode
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "CIFAR-10 archive not found (no network access). Pass "
                "data_file=/path/to/cifar-10-python.tar.gz, or use "
                "paddle_tpu.vision.datasets.FakeData for synthetic data.")
        self.data, self.labels = self._load(data_file, mode)

    def _load(self, path, mode):
        imgs, labels = [], []
        with tarfile.open(path) as tf:
            names = [n for n in tf.getnames()
                     if ("data_batch" in n if mode == "train"
                         else "test_batch" in n)]
            for n in sorted(names):
                f = tf.extractfile(n)
                d = pickle.load(f, encoding="bytes")
                imgs.append(d[b"data"].reshape(-1, 3, 32, 32))
                labels.extend(d[b"labels"])
        return (np.concatenate(imgs).astype(np.float32) / 255.0,
                np.asarray(labels, dtype=np.int32))

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    def _load(self, path, mode):
        with tarfile.open(path) as tf:
            name = "train" if mode == "train" else "test"
            member = [n for n in tf.getnames() if n.endswith(name)][0]
            d = pickle.load(tf.extractfile(member), encoding="bytes")
            imgs = d[b"data"].reshape(-1, 3, 32, 32)
            labels = d[b"fine_labels"]
        return (imgs.astype(np.float32) / 255.0,
                np.asarray(labels, dtype=np.int32))


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.transform = transform
        if image_path is None or not os.path.exists(image_path):
            raise FileNotFoundError(
                "MNIST files not found (no network). Pass image_path/"
                "label_path to local idx.gz files, or use FakeData.")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _read_images(path):
        with gzip.open(path, "rb") as f:
            data = f.read()
        n = int.from_bytes(data[4:8], "big")
        arr = np.frombuffer(data, np.uint8, offset=16).reshape(n, 1, 28, 28)
        return arr.astype(np.float32) / 255.0

    @staticmethod
    def _read_labels(path):
        with gzip.open(path, "rb") as f:
            data = f.read()
        return np.frombuffer(data, np.uint8, offset=8).astype(np.int32)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass
