"""Vision datasets (reference: paddle.vision.datasets — upstream
python/paddle/vision/datasets/, unverified; see SURVEY.md §2.2).

Zero-egress environment: loaders read local archives when present
(`data_file=` arg); otherwise raise with a clear message. `FakeData`
provides deterministic synthetic data for tests/benchmarks (the config-1
CIFAR-10 milestone runs on it when the real archive is absent).
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100", "MNIST", "FashionMNIST", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic labelled images."""

    def __init__(self, num_samples=1000, image_shape=(3, 32, 32),
                 num_classes=10, mode="train", transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        self.images = rng.standard_normal(
            (num_samples,) + self.image_shape).astype(np.float32)
        self.labels = rng.integers(0, num_classes,
                                   (num_samples,)).astype(np.int32)
        # make labels learnable: bias the mean of each image by its label
        self.images += self.labels[:, None, None, None].astype(
            np.float32) / num_classes

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.num_samples


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.transform = transform
        self.mode = mode
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "CIFAR-10 archive not found (no network access). Pass "
                "data_file=/path/to/cifar-10-python.tar.gz, or use "
                "paddle_tpu.vision.datasets.FakeData for synthetic data.")
        self.data, self.labels = self._load(data_file, mode)

    def _load(self, path, mode):
        imgs, labels = [], []
        with tarfile.open(path) as tf:
            names = [n for n in tf.getnames()
                     if ("data_batch" in n if mode == "train"
                         else "test_batch" in n)]
            for n in sorted(names):
                f = tf.extractfile(n)
                d = pickle.load(f, encoding="bytes")
                imgs.append(d[b"data"].reshape(-1, 3, 32, 32))
                labels.extend(d[b"labels"])
        return (np.concatenate(imgs).astype(np.float32) / 255.0,
                np.asarray(labels, dtype=np.int32))

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    def _load(self, path, mode):
        with tarfile.open(path) as tf:
            name = "train" if mode == "train" else "test"
            member = [n for n in tf.getnames() if n.endswith(name)][0]
            d = pickle.load(tf.extractfile(member), encoding="bytes")
            imgs = d[b"data"].reshape(-1, 3, 32, 32)
            labels = d[b"fine_labels"]
        return (imgs.astype(np.float32) / 255.0,
                np.asarray(labels, dtype=np.int32))


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.transform = transform
        if image_path is None or not os.path.exists(image_path):
            raise FileNotFoundError(
                "MNIST files not found (no network). Pass image_path/"
                "label_path to local idx.gz files, or use FakeData.")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _read_images(path):
        with gzip.open(path, "rb") as f:
            data = f.read()
        n = int.from_bytes(data[4:8], "big")
        arr = np.frombuffer(data, np.uint8, offset=16).reshape(n, 1, 28, 28)
        return arr.astype(np.float32) / 255.0

    @staticmethod
    def _read_labels(path):
        with gzip.open(path, "rb") as f:
            data = f.read()
        return np.frombuffer(data, np.uint8, offset=8).astype(np.int32)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Flowers(Dataset):
    """Reference parity: paddle.vision.datasets.Flowers (upstream
    python/paddle/vision/datasets/flowers.py — unverified, SURVEY.md
    blocker notice). Oxford-102 layout from LOCAL files (no network):
    `data_file` = 102flowers.tgz (jpg/image_XXXXX.jpg), `label_file` =
    imagelabels.mat, `setid_file` = setid.mat. Splits per setid keys
    trnid/valid/tstid; labels 1-based in the .mat → kept 1-based like
    the reference. Images decode lazily per __getitem__ (PIL), HWC
    uint8 numpy (backend='cv2'-style array output).
    """

    _SET_KEYS = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend="cv2"):
        if mode not in self._SET_KEYS:
            raise ValueError(f"mode must be one of "
                             f"{sorted(self._SET_KEYS)}, got {mode!r}")
        if not all(p and os.path.exists(p)
                   for p in (data_file, label_file, setid_file)):
            raise FileNotFoundError(
                "Flowers needs local copies (no network access): "
                "data_file=102flowers.tgz, label_file=imagelabels.mat, "
                "setid_file=setid.mat")
        import scipy.io as sio
        self.transform = transform
        labels = sio.loadmat(label_file)["labels"].ravel()
        ids = sio.loadmat(setid_file)[self._SET_KEYS[mode]].ravel()
        self.indexes = ids.astype(np.int64)          # 1-based image ids
        self.labels = {int(i): np.int64(labels[int(i) - 1])
                       for i in self.indexes}
        self._tar_path = data_file
        self._tf = None

    def _image(self, image_id):
        from PIL import Image
        # gzip tars have no random access: a shuffled sampler reading
        # members directly would re-decompress from the archive start on
        # every backward seek. Extract once per process (lazy — after
        # DataLoader workers fork), then reads are O(image).
        if self._tf is None:
            import tempfile
            d = tempfile.mkdtemp(prefix="pd_flowers_")
            with tarfile.open(self._tar_path) as tf:
                tf.extractall(d, filter="data")
            self._tf = d
        name = os.path.join(self._tf, "jpg", f"image_{image_id:05d}.jpg")
        return np.asarray(Image.open(name).convert("RGB"))

    def __getitem__(self, idx):
        image_id = int(self.indexes[idx])
        img = self._image(image_id)
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[image_id]

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Reference parity: paddle.vision.datasets.VOC2012 (segmentation
    split; upstream python/paddle/vision/datasets/voc2012.py —
    unverified). Parses a LOCAL VOCtrainval tar: JPEGImages/*.jpg +
    SegmentationClass/*.png, split lists under
    ImageSets/Segmentation/{train,val,trainval}.txt. Yields
    (image HWC uint8, label HW uint8) numpy arrays.
    """

    _SPLITS = {"train": "train.txt", "valid": "val.txt",
               "test": "val.txt", "trainval": "trainval.txt"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        if mode not in self._SPLITS:
            raise ValueError(f"mode must be one of "
                             f"{sorted(self._SPLITS)}, got {mode!r}")
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "VOC2012 needs a local VOCtrainval tar (no network "
                "access): pass data_file=")
        self.transform = transform
        self._tar_path = data_file
        with tarfile.open(data_file) as tf:
            names = tf.getnames()

            def _find(suffix):
                hits = [n for n in names if n.endswith(suffix)]
                if not hits:
                    raise ValueError(
                        f"{suffix} not found in {data_file!r} — "
                        "expected the VOC2012 layout")
                return hits[0]

            split = tf.extractfile(
                _find("ImageSets/Segmentation/" + self._SPLITS[mode]))
            self.keys = [l.strip() for l in
                         split.read().decode().splitlines() if l.strip()]
            self._jpeg_dir = os.path.dirname(_find("JPEGImages/" +
                                                   self.keys[0] + ".jpg"))
            self._seg_dir = os.path.dirname(_find("SegmentationClass/" +
                                                  self.keys[0] + ".png"))
        # handle opened lazily PER PROCESS: DataLoader workers fork
        # after __init__, and a shared fd's seek/read would interleave
        self._tf = None

    def _read(self, name):
        import io as _io
        from PIL import Image
        if self._tf is None:
            self._tf = tarfile.open(self._tar_path)
        data = self._tf.extractfile(name).read()
        return Image.open(_io.BytesIO(data))

    def __getitem__(self, idx):
        key = self.keys[idx]
        img = np.asarray(self._read(
            f"{self._jpeg_dir}/{key}.jpg").convert("RGB"))
        lbl = np.asarray(self._read(f"{self._seg_dir}/{key}.png"))
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.keys)


__all__ += ["Flowers", "VOC2012"]
