"""paddle.vision.transforms.functional parity (reference:
python/paddle/vision/transforms/functional*.py — unverified, SURVEY.md
§2.2 Vision). Host-side numpy ops on CHW (or HW/HWC) float arrays, as
the transform pipeline runs pre-device-transfer. Geometry ops
(rotate/affine/perspective) use inverse-mapped bilinear sampling —
vectorized numpy, no scipy dependency.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["to_tensor", "normalize", "resize", "crop", "center_crop",
           "hflip", "vflip", "pad", "erase", "rotate", "to_grayscale",
           "adjust_brightness", "adjust_contrast", "adjust_hue",
           "affine", "perspective"]


def _chw(img):
    img = np.asarray(img, dtype=np.float32)
    if img.ndim == 2:
        return img[None], "HW"
    # HWC (PIL/cv2 convention, what the reference's transforms see
    # pre-ToTensor) wins when both dims look channel-like — matches
    # the geometric transforms' _hwc heuristic
    if img.ndim == 3 and img.shape[-1] in (1, 3, 4):
        return np.transpose(img, (2, 0, 1)), "HWC"
    return img, "CHW"


def _restore(img, fmt):
    if fmt == "HW":
        return img[0]
    if fmt == "HWC":
        return np.transpose(img, (1, 2, 0))
    return img




def _max_value(img):
    """Value-range ceiling: trust the ORIGINAL dtype (uint8 => 255)
    before any float conversion; for float inputs fall back to the
    magnitude heuristic (a dark uint8-range image passed as float is
    ambiguous — prefer 255 when any value exceeds 2)."""
    arr = np.asarray(img)
    if arr.dtype == np.uint8:
        return 255.0
    return 255.0 if arr.size and arr.max() > 2 else 1.0


def to_tensor(img, data_format="CHW"):
    from . import ToTensor
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from . import Normalize
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    from . import Resize
    return Resize(size, interpolation)(img)


def crop(img, top, left, height, width):
    c, fmt = _chw(img)
    return _restore(c[:, top:top + height, left:left + width], fmt)


def center_crop(img, output_size):
    size = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    c, fmt = _chw(img)
    h, w = c.shape[1:]
    top = max((h - size[0]) // 2, 0)
    left = max((w - size[1]) // 2, 0)
    return _restore(c[:, top:top + size[0], left:left + size[1]], fmt)


def hflip(img):
    c, fmt = _chw(img)
    return _restore(c[:, :, ::-1].copy(), fmt)


def vflip(img):
    c, fmt = _chw(img)
    return _restore(c[:, ::-1, :].copy(), fmt)


def pad(img, padding, fill=0, padding_mode="constant"):
    c, fmt = _chw(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl = pr = padding[0]
        pt = pb = padding[1]
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(c, ((0, 0), (pt, pb), (pl, pr)), mode=mode, **kw)
    return _restore(out, fmt)


def erase(img, i, j, h, w, v, inplace=False):
    c, fmt = _chw(img)
    if not inplace:
        c = c.copy()
    c[:, i:i + h, j:j + w] = v
    return _restore(c, fmt)


def to_grayscale(img, num_output_channels=1):
    c, fmt = _chw(img)
    if c.shape[0] >= 3:
        g = (0.299 * c[0] + 0.587 * c[1] + 0.114 * c[2])[None]
    else:
        g = c[:1]
    out = np.repeat(g, num_output_channels, axis=0)
    return _restore(out, fmt)


def adjust_brightness(img, brightness_factor):
    mx = _max_value(img)
    c, fmt = _chw(img)
    return _restore(np.clip(c * brightness_factor, 0, mx), fmt)


def adjust_contrast(img, contrast_factor):
    c, fmt = _chw(img)
    mean = (0.299 * c[0] + 0.587 * c[1] + 0.114 * c[2]).mean() \
        if c.shape[0] >= 3 else c.mean()
    out = mean + contrast_factor * (c - mean)
    return _restore(np.clip(out, 0, _max_value(img)), fmt)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via RGB→HSV→RGB."""
    scale = _max_value(img)
    c, fmt = _chw(img)
    rgb = np.clip(c[:3] / scale, 0, 1)
    r, g, b = rgb
    mx = rgb.max(0)
    mn = rgb.min(0)
    d = mx - mn
    # hue in [0, 1)
    with np.errstate(divide="ignore", invalid="ignore"):
        h = np.where(
            d == 0, 0.0,
            np.where(mx == r, ((g - b) / d) % 6,
                     np.where(mx == g, (b - r) / d + 2,
                              (r - g) / d + 4)) / 6.0)
    s = np.where(mx == 0, 0.0, d / np.maximum(mx, 1e-12))
    v = mx
    h = (h + hue_factor) % 1.0
    # HSV -> RGB
    i = np.floor(h * 6).astype(np.int32) % 6
    f = h * 6 - np.floor(h * 6)
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2]) * scale
    if c.shape[0] > 3:
        out = np.concatenate([out, c[3:]], axis=0)
    return _restore(out.astype(np.float32), fmt)


def _sample_bilinear(c, ys, xs, fill=0.0):
    """Sample CHW image at fractional (ys, xs) grids [H, W]."""
    C, H, W = c.shape
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    wy = ys - y0
    wx = xs - x0
    out = np.zeros((C,) + ys.shape, np.float32)
    total_w = np.zeros(ys.shape, np.float32)
    for dy, wgt_y in ((0, 1 - wy), (1, wy)):
        for dx, wgt_x in ((0, 1 - wx), (1, wx)):
            yy = y0 + dy
            xx = x0 + dx
            valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yc = np.clip(yy, 0, H - 1)
            xc = np.clip(xx, 0, W - 1)
            w = (wgt_y * wgt_x) * valid
            out += c[:, yc, xc] * w
            total_w += w
    return out + fill * (1 - total_w)


def _inverse_affine_sample(img, matrix, fill=0.0):
    """matrix: 2x3 inverse map (output coords -> input coords), centered
    at the image center."""
    c, fmt = _chw(img)
    H, W = c.shape[1:]
    cy, cx = (H - 1) / 2.0, (W - 1) / 2.0
    yy, xx = np.meshgrid(np.arange(H) - cy, np.arange(W) - cx,
                         indexing="ij")
    a, b, tx, d, e, ty = matrix
    xs = a * xx + b * yy + tx + cx
    ys = d * xx + e * yy + ty + cy
    out = _sample_bilinear(c, ys, xs, fill)
    return _restore(out, fmt)


def rotate(img, angle, interpolation="bilinear", expand=False,
           center=None, fill=0):
    th = math.radians(angle)
    # inverse rotation (output -> input)
    m = [math.cos(th), math.sin(th), 0.0,
         -math.sin(th), math.cos(th), 0.0]
    return _inverse_affine_sample(img, m, fill)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    th = math.radians(angle)
    sx = math.radians(shear[0] if isinstance(shear, (list, tuple))
                      else shear)
    sy = math.radians(shear[1] if isinstance(shear, (list, tuple)) and
                      len(shear) > 1 else 0.0)
    # forward map M = R(angle) @ Shear @ diag(scale); invert analytically
    a = math.cos(th + sy) / math.cos(sy)
    b = -(math.cos(th + sy) * math.tan(sx) / math.cos(sy) + math.sin(th))
    d = math.sin(th + sy) / math.cos(sy)
    e = -(math.sin(th + sy) * math.tan(sx) / math.cos(sy) - math.cos(th))
    fwd = np.array([[a * scale, b * scale], [d * scale, e * scale]])
    inv = np.linalg.inv(fwd)
    tx, ty = translate
    m = [inv[0, 0], inv[0, 1], -(inv[0, 0] * tx + inv[0, 1] * ty),
         inv[1, 0], inv[1, 1], -(inv[1, 0] * tx + inv[1, 1] * ty)]
    return _inverse_affine_sample(img, m, fill)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0):
    """Projective warp mapping endpoints back to startpoints."""
    c, fmt = _chw(img)
    H, W = c.shape[1:]
    # solve the 8-dof homography endpoints -> startpoints
    A, bvec = [], []
    for (sx_, sy_), (ex_, ey_) in zip(startpoints, endpoints):
        A.append([ex_, ey_, 1, 0, 0, 0, -sx_ * ex_, -sx_ * ey_])
        bvec.append(sx_)
        A.append([0, 0, 0, ex_, ey_, 1, -sy_ * ex_, -sy_ * ey_])
        bvec.append(sy_)
    h = np.linalg.solve(np.asarray(A, np.float64),
                        np.asarray(bvec, np.float64))
    yy, xx = np.meshgrid(np.arange(H, dtype=np.float64),
                         np.arange(W, dtype=np.float64), indexing="ij")
    den = h[6] * xx + h[7] * yy + 1.0
    xs = (h[0] * xx + h[1] * yy + h[2]) / den
    ys = (h[3] * xx + h[4] * yy + h[5]) / den
    out = _sample_bilinear(c, ys.astype(np.float32),
                           xs.astype(np.float32), fill)
    return _restore(out, fmt)
