"""Vision transforms (reference: paddle.vision.transforms — upstream,
unverified; see SURVEY.md §2.2). Operate on numpy CHW float arrays (host
side, pre-device-transfer, as the reference does on PIL/cv2 images).
"""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Transpose", "Resize",
           "RandomCrop", "CenterCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "BrightnessTransform", "Pad"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (img - m) / s


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if img.ndim == 2:
            img = img[None]
        elif img.ndim == 3 and img.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        if img.max() > 2.0:
            img = img / 255.0
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = jnp.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + (arr.shape[-1],) if arr.ndim == 3 \
                else self.size
        return np.asarray(jax.image.resize(arr, out_shape, "linear"))


class RandomCrop:
    def __init__(self, size, padding=0, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self._rng = np.random.default_rng(0)

    def __call__(self, img):
        img = np.asarray(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, [(0, 0), (p, p), (p, p)], mode="constant")
        h, w = img.shape[-2:]
        th, tw = self.size
        i = self._rng.integers(0, h - th + 1)
        j = self._rng.integers(0, w - tw + 1)
        return img[..., i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[-2:]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[..., i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob
        self._rng = np.random.default_rng(0)

    def __call__(self, img):
        if self._rng.random() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob
        self._rng = np.random.default_rng(0)

    def __call__(self, img):
        if self._rng.random() < self.prob:
            return np.asarray(img)[..., ::-1, :].copy()
        return img


class BrightnessTransform:
    def __init__(self, value):
        self.value = value
        self._rng = np.random.default_rng(0)

    def __call__(self, img):
        if self.value <= 0:
            return img
        factor = self._rng.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.asarray(img) * factor


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding

    def __call__(self, img):
        p = self.padding
        if isinstance(p, int):
            cfg = [(0, 0), (p, p), (p, p)]
        else:
            cfg = [(0, 0), (p[1], p[3]), (p[0], p[2])]
        return np.pad(np.asarray(img), cfg, mode="constant")
