"""Vision transforms (reference: paddle.vision.transforms — upstream,
unverified; see SURVEY.md §2.2). Host-side numpy, layout-ADAPTIVE like
the reference pipeline: a 3-D array whose LAST dim is 1/3/4 is treated
as HWC (the PIL/cv2 convention the reference's geometric transforms see
before ToTensor/Transpose), anything else as CHW. Geometric transforms
(crops, pads, flips) resolve their spatial axes per input.
"""
from __future__ import annotations

import warnings

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Transpose", "Resize",
           "RandomCrop", "CenterCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "BrightnessTransform", "Pad"]




def _hwc(img, data_format=None):
    """True when a 3-D array is HWC (last dim a channel count) — the
    layout the reference's geometric transforms always see (PIL/cv2,
    pre-ToTensor).

    ``data_format`` ("HWC"/"CHW", case-insensitive) overrides the
    heuristic — the geometric transforms expose it as a constructor
    kwarg. Without an override, an AMBIGUOUS shape (both first and last
    dims look channel-like, e.g. 3×H×3) warns and falls back to the HWC
    reading — the reference pipeline order — instead of silently
    guessing (ADVICE.md #2)."""
    if data_format is not None:
        df = str(data_format).upper()
        if df not in ("HWC", "CHW"):
            raise ValueError(
                f"data_format must be 'HWC' or 'CHW', got {data_format!r}")
        return df == "HWC"
    if img.ndim != 3:
        return False
    last = img.shape[-1] in (1, 3, 4)
    if last and img.shape[0] in (1, 3, 4):
        warnings.warn(
            f"ambiguous 3-D image layout {img.shape}: both first and "
            "last dims look channel-like; assuming HWC. Pass "
            "data_format='CHW' (or 'HWC') to the transform to resolve "
            "explicitly.", stacklevel=3)
    return last


def _spatial(img, data_format=None):
    """(h_axis, w_axis) for this layout."""
    return (0, 1) if _hwc(img, data_format) \
        else (img.ndim - 2, img.ndim - 1)


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (img - m) / s


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if img.ndim == 2:
            img = img[None]
        elif img.ndim == 3 and img.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        if img.max() > 2.0:
            img = img / 255.0
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear", data_format=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.data_format = data_format

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = jnp.asarray(img)
        chw = arr.ndim == 3 and not _hwc(arr, self.data_format)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + (arr.shape[-1],) if arr.ndim == 3 \
                else self.size
        return np.asarray(jax.image.resize(arr, out_shape, "linear"))


class RandomCrop:
    def __init__(self, size, padding=0, pad_if_needed=False,
                 data_format=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.data_format = data_format
        self._rng = np.random.default_rng(0)

    def __call__(self, img):
        img = np.asarray(img)
        ha, wa = _spatial(img, self.data_format)
        if self.padding:
            p = self.padding
            cfg = [(0, 0)] * img.ndim
            cfg[ha] = cfg[wa] = (p, p)
            img = np.pad(img, cfg, mode="constant")
        h, w = img.shape[ha], img.shape[wa]
        th, tw = self.size
        i = self._rng.integers(0, h - th + 1)
        j = self._rng.integers(0, w - tw + 1)
        sl = [slice(None)] * img.ndim
        sl[ha] = slice(i, i + th)
        sl[wa] = slice(j, j + tw)
        return img[tuple(sl)]


class CenterCrop:
    def __init__(self, size, data_format=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img)
        ha, wa = _spatial(img, self.data_format)
        h, w = img.shape[ha], img.shape[wa]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        sl = [slice(None)] * img.ndim
        sl[ha] = slice(i, i + th)
        sl[wa] = slice(j, j + tw)
        return img[tuple(sl)]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, data_format=None):
        self.prob = prob
        self.data_format = data_format
        self._rng = np.random.default_rng(0)

    def __call__(self, img):
        if self._rng.random() < self.prob:
            img = np.asarray(img)
            return np.flip(img,
                           axis=_spatial(img, self.data_format)[1]).copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5, data_format=None):
        self.prob = prob
        self.data_format = data_format
        self._rng = np.random.default_rng(0)

    def __call__(self, img):
        if self._rng.random() < self.prob:
            img = np.asarray(img)
            return np.flip(img,
                           axis=_spatial(img, self.data_format)[0]).copy()
        return img


class BrightnessTransform:
    def __init__(self, value):
        self.value = value
        self._rng = np.random.default_rng(0)

    def __call__(self, img):
        if self.value <= 0:
            return img
        factor = self._rng.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.asarray(img) * factor


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant",
                 data_format=None):
        self.padding = padding
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img)
        ha, wa = _spatial(img, self.data_format)
        p = self.padding
        cfg = [(0, 0)] * img.ndim
        if isinstance(p, int):
            cfg[ha] = cfg[wa] = (p, p)
        else:  # reference order: (left, top, right, bottom)
            cfg[ha] = (p[1], p[3])
            cfg[wa] = (p[0], p[2])
        return np.pad(img, cfg, mode="constant")


from . import functional  # noqa: E402
from .functional import (adjust_brightness, adjust_contrast,  # noqa: E402,F401
                         adjust_hue, affine, crop, erase, hflip,
                         normalize, pad, perspective, resize, rotate,
                         to_grayscale, to_tensor, vflip)
from .functional import center_crop  # noqa: E402,F401


class RandomResizedCrop:
    """Random area/aspect crop then resize (reference semantics)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", data_format=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.data_format = data_format

    def __call__(self, img):
        import random as _r
        arr = np.asarray(img)
        chw = arr.ndim == 3 and not _hwc(arr, self.data_format)
        # Resolve the layout ONCE and thread it through: the random
        # crop can land on an ambiguous shape (e.g. width 3 or 4), so
        # the internal crop/resize must inherit this resolution, never
        # re-run the heuristic on the cropped array.
        df = ("CHW" if chw else "HWC") if arr.ndim == 3 else None
        rs = Resize(self.size, data_format=df)

        def _crop(top, left, ch, cw):
            ha, wa = _spatial(arr, df)
            sl = [slice(None)] * arr.ndim
            sl[ha] = slice(top, top + ch)
            sl[wa] = slice(left, left + cw)
            return arr[tuple(sl)]

        h, w = (arr.shape[1:] if chw else arr.shape[:2])
        area = h * w
        for _ in range(10):
            target = area * _r.uniform(*self.scale)
            ar = _r.uniform(*self.ratio)
            cw = int(round((target * ar) ** 0.5))
            ch = int(round((target / ar) ** 0.5))
            if 0 < cw <= w and 0 < ch <= h:
                top = _r.randint(0, h - ch)
                left = _r.randint(0, w - cw)
                return rs(_crop(top, left, ch, cw))
        m = min(h, w)
        return rs(_crop(max((h - m) // 2, 0), max((w - m) // 2, 0), m, m))


class ColorJitter:
    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0,
                 hue=0.0):
        self.b, self.c, self.s, self.h = brightness, contrast, \
            saturation, hue

    def __call__(self, img):
        import random as _r
        if self.b:
            img = adjust_brightness(img, _r.uniform(max(0, 1 - self.b),
                                                    1 + self.b))
        if self.c:
            img = adjust_contrast(img, _r.uniform(max(0, 1 - self.c),
                                                  1 + self.c))
        if self.s:
            img = SaturationTransform(self.s)(img)
        if self.h:
            img = adjust_hue(img, _r.uniform(-self.h, self.h))
        return img


class RandomRotation:
    def __init__(self, degrees, interpolation="bilinear", expand=False,
                 center=None, fill=0):
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, (int, float)) else tuple(degrees)
        self.fill = fill

    def __call__(self, img):
        import random as _r
        return rotate(img, _r.uniform(*self.degrees), fill=self.fill)


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.n)


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        import random as _r
        return adjust_contrast(img, _r.uniform(max(0, 1 - self.value),
                                               1 + self.value))


class SaturationTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        import random as _r
        f = _r.uniform(max(0, 1 - self.value), 1 + self.value)
        arr = np.asarray(img, np.float32)
        gray = np.asarray(to_grayscale(arr, 3), np.float32) \
            if arr.ndim == 3 else arr
        from .functional import _max_value
        return np.clip(gray + f * (arr - gray), 0, _max_value(img))


class HueTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        import random as _r
        return adjust_hue(img, _r.uniform(-self.value, self.value))


class RandomErasing:
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, data_format=None):
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value = value
        self.data_format = data_format

    def __call__(self, img):
        import random as _r
        if _r.random() > self.prob:
            return img
        arr = np.asarray(img)
        chw = arr.ndim == 3 and not _hwc(arr, self.data_format)
        h, w = (arr.shape[1:] if chw else arr.shape[:2])
        for _ in range(10):
            target = h * w * _r.uniform(*self.scale)
            ar = _r.uniform(*self.ratio)
            eh = int(round((target / ar) ** 0.5))
            ew = int(round((target * ar) ** 0.5))
            if eh < h and ew < w:
                top = _r.randint(0, h - eh)
                left = _r.randint(0, w - ew)
                return erase(img, top, left, eh, ew, self.value)
        return img


class RandomAffine:
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="bilinear", fill=0, center=None,
                 data_format=None):
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, (int, float)) else tuple(degrees)
        self.translate, self.scale_rng, self.shear = translate, scale, \
            shear
        self.fill = fill
        self.data_format = data_format

    def __call__(self, img):
        import random as _r
        arr = np.asarray(img)
        chw = arr.ndim == 3 and not _hwc(arr, self.data_format)
        h, w = (arr.shape[1:] if chw else arr.shape[:2])
        angle = _r.uniform(*self.degrees)
        tx = ty = 0
        if self.translate:
            tx = _r.uniform(-self.translate[0], self.translate[0]) * w
            ty = _r.uniform(-self.translate[1], self.translate[1]) * h
        sc = _r.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sh = _r.uniform(-self.shear, self.shear) \
            if isinstance(self.shear, (int, float)) and self.shear else 0.0
        return affine(img, angle=angle, translate=(tx, ty), scale=sc,
                      shear=(sh, 0.0), fill=self.fill)


class RandomPerspective:
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="bilinear", fill=0, data_format=None):
        self.prob = prob
        self.d = distortion_scale
        self.fill = fill
        self.data_format = data_format

    def __call__(self, img):
        import random as _r
        if _r.random() > self.prob:
            return img
        arr = np.asarray(img)
        chw = arr.ndim == 3 and not _hwc(arr, self.data_format)
        h, w = (arr.shape[1:] if chw else arr.shape[:2])
        dx = self.d * w / 2
        dy = self.d * h / 2
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(int(_r.uniform(0, dx)), int(_r.uniform(0, dy))),
               (int(w - 1 - _r.uniform(0, dx)), int(_r.uniform(0, dy))),
               (int(w - 1 - _r.uniform(0, dx)),
                int(h - 1 - _r.uniform(0, dy))),
               (int(_r.uniform(0, dx)), int(h - 1 - _r.uniform(0, dy)))]
        return perspective(img, start, end, fill=self.fill)


__all__ += ["RandomResizedCrop", "ColorJitter", "RandomRotation",
            "Grayscale", "ContrastTransform", "SaturationTransform",
            "HueTransform", "RandomErasing", "RandomAffine",
            "RandomPerspective", "functional"]
