"""paddle_tpu.vision.ops — detection ops (reference: paddle.vision.ops
nms/roi_align/roi_pool/deform_conv2d/box_coder/yolo_box — upstream
python/paddle/vision/ops.py + CUDA kernels in paddle/phi/kernels/gpu/,
unverified; see SURVEY.md §2.2 "Vision").

TPU-native design: every op is expressed with static shapes and
vectorized gathers so it compiles under jit —
- `nms` is the O(n²) mask formulation (pairwise IoU matrix + a lax scan
  over score rank) instead of the reference's dynamic worklist: no
  data-dependent shapes, MXU/VPU-friendly, exact same result;
- `roi_align`/`roi_pool` sample with batched bilinear gathers (one
  gather per pooling bin sample, vmapped over ROIs);
- `deform_conv2d` is im2col-with-deformed-offsets: bilinear-sample the
  input at offset positions → one big matmul (the MXU path);
- `box_coder`/`yolo_box` are pure elementwise decodes.
Outputs are fixed-size with validity masks where the reference returns
ragged results (the XLA static-shape contract; callers slice by the
returned count).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply as _apply
from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..ops._base import ensure_tensor

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "yolo_box",
           "deform_conv2d", "RoIAlign", "RoIPool", "DeformConv2D"]


def _box_iou(boxes):
    """Pairwise IoU of [N, 4] (x1, y1, x2, y2) boxes."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices sorted by descending score
    (fixed length N with -1 padding when compiled; eager returns the
    trimmed result like the reference).

    Multi-class (category_idxs given) offsets boxes per class so
    suppression never crosses classes (the reference's batched_nms
    trick).
    """
    b = ensure_tensor(boxes)._data.astype(jnp.float32)
    n = b.shape[0]
    sc = (ensure_tensor(scores)._data.astype(jnp.float32)
          if scores is not None else jnp.arange(n, 0, -1, jnp.float32))
    if category_idxs is not None:
        cat = ensure_tensor(category_idxs)._data
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (cat.astype(jnp.float32) * span)[:, None]

    order = jnp.argsort(-sc)
    iou = _box_iou(b)[order][:, order]

    def step(keep, i):
        # keep[i] stays True only if no higher-ranked kept box overlaps
        sup = jnp.any(keep & (jnp.arange(n) < i) & (iou[i] > iou_threshold))
        keep = keep.at[i].set(~sup)
        return keep, None

    keep0 = jnp.ones((n,), bool)
    keep, _ = jax.lax.scan(step, keep0, jnp.arange(n))
    kept_sorted = jnp.where(keep, order, -1)  # rank order, -1 = suppressed
    # compact: kept indices first (stable), -1 padding after
    key = jnp.where(keep, jnp.arange(n), n)
    perm = jnp.argsort(key)
    out = kept_sorted[perm]
    if isinstance(out, jax.core.Tracer):
        # top_k is a Python int, so the slice is shape-static and legal
        # under trace; -1 padding semantics are preserved.
        if top_k is not None:
            out = out[:top_k]
        return Tensor(out)
    out = out[out >= 0]
    if top_k is not None:
        out = out[:top_k]
    return Tensor(out)


def _bilinear(feat, y, x):
    """Sample feat [C, H, W] at fractional (y, x) — zero outside."""
    H, W = feat.shape[1], feat.shape[2]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = y - y0
    wx1 = x - x0
    wy0, wx0 = 1 - wy1, 1 - wx1

    def tap(yi, xi, w):
        valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        v = feat[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
        return v * (w * valid)

    return (tap(y0, x0, wy0 * wx0) + tap(y0, x1, wy0 * wx1) +
            tap(y1, x0, wy1 * wx0) + tap(y1, x1, wy1 * wx1))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (reference semantics incl. `aligned` half-pixel shift).

    x: [N, C, H, W]; boxes: [R, 4] in input coords; boxes_num: [N] ROIs
    per image (prefix-assigns ROIs to images). Returns [R, C, ph, pw].

    Numerics note: with sampling_ratio<=0 the reference adapts the
    sub-sample count per ROI (ceil(roi_size/pooled_size)); that is a
    data-dependent shape, illegal under XLA's static-shape contract, so
    this implementation uses a fixed ratio of 2 (the common detector
    setting). Outputs deviate slightly from reference numerics for ROIs
    much larger than the output grid; pass an explicit sampling_ratio to
    pin the reference behavior you need.
    """
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    bx = ensure_tensor(boxes)._data.astype(jnp.float32)
    bn = ensure_tensor(boxes_num)._data
    ph, pw = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    ratio = 2 if sampling_ratio <= 0 else int(sampling_ratio)
    off = 0.5 if aligned else 0.0
    img_of_roi = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                            total_repeat_length=bx.shape[0])

    # sample positions inside the ROI: `ratio` uniform sub-samples per
    # output cell (uniform over the whole ROI == per-bin sampling)
    cell = jnp.arange(ph * ratio, dtype=jnp.float32)
    frac_y = (cell + 0.5) / (ph * ratio)  # uniform — equals per-bin sampling
    cellx = jnp.arange(pw * ratio, dtype=jnp.float32)
    frac_x = (cellx + 0.5) / (pw * ratio)

    def one_roi(box, img):
        x1, y1, x2, y2 = box * spatial_scale
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        h = y2 - y1 if aligned else jnp.maximum(y2 - y1, 1.0)
        w = x2 - x1 if aligned else jnp.maximum(x2 - x1, 1.0)
        ys = y1 + frac_y * h                      # [ph*ratio]
        xs = x1 + frac_x * w                      # [pw*ratio]
        yy = jnp.repeat(ys, pw * ratio)
        xx = jnp.tile(xs, ph * ratio)
        vals = _bilinear(xd[img], yy, xx)         # [C, ph*r*pw*r]
        C = vals.shape[0]
        vals = vals.reshape(C, ph, ratio, pw, ratio)
        return vals.mean(axis=(2, 4))             # [C, ph, pw]

    out = jax.vmap(one_roi)(bx, img_of_roi)
    return Tensor(out)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """RoIPool via dense max over adaptive bins (gather formulation)."""
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    bx = ensure_tensor(boxes)._data.astype(jnp.float32)
    bn = ensure_tensor(boxes_num)._data
    ph, pw = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    H, W = xd.shape[2], xd.shape[3]
    img_of_roi = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                            total_repeat_length=bx.shape[0])
    iy = jnp.arange(H)
    ix = jnp.arange(W)

    def one_roi(box, img):
        x1 = jnp.floor(box[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.floor(box[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.ceil(box[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.ceil(box[3] * spatial_scale).astype(jnp.int32)
        hh = jnp.maximum(y2 - y1, 1).astype(jnp.float32)
        ww = jnp.maximum(x2 - x1, 1).astype(jnp.float32)
        # bin index of every pixel (pixels outside the ROI get -1)
        by = jnp.floor((iy - y1).astype(jnp.float32) * ph / hh).astype(
            jnp.int32)
        bxx = jnp.floor((ix - x1).astype(jnp.float32) * pw / ww).astype(
            jnp.int32)
        by = jnp.where((iy >= y1) & (iy < jnp.maximum(y2, y1 + 1)),
                       jnp.clip(by, 0, ph - 1), -1)
        bxx = jnp.where((ix >= x1) & (ix < jnp.maximum(x2, x1 + 1)),
                        jnp.clip(bxx, 0, pw - 1), -1)
        onehot_y = (by[:, None] == jnp.arange(ph)[None, :])   # [H, ph]
        onehot_x = (bxx[:, None] == jnp.arange(pw)[None, :])  # [W, pw]
        feat = xd[img]                                        # [C, H, W]
        neg = jnp.finfo(jnp.float32).min
        masked = jnp.where(onehot_y[None, :, None, :, None] &
                           onehot_x[None, None, :, None, :],
                           feat[:, :, :, None, None], neg)
        pooled = masked.max(axis=(1, 2))                      # [C, ph, pw]
        return jnp.where(pooled == neg, 0.0, pooled)

    return Tensor(jax.vmap(one_roi)(bx, img_of_roi))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """Encode/decode boxes against priors (reference box_coder)."""
    pb = ensure_tensor(prior_box)._data.astype(jnp.float32)
    pbv = (ensure_tensor(prior_box_var)._data.astype(jnp.float32)
           if prior_box_var is not None else None)
    tb = ensure_tensor(target_box)._data.astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    phh = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + phh * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / phh[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / phh[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if pbv is not None:
            out = out / pbv[None, :, :]
        return Tensor(out)
    # decode: target [N, M, 4] deltas against priors on `axis`
    if tb.ndim == 2:
        tb = tb[:, None, :]
    d = tb * (pbv[None, :, :] if pbv is not None else 1.0)
    shp = (1, -1) if axis == 0 else (-1, 1)
    pw_, ph_ = pw.reshape(shp), phh.reshape(shp)
    pcx_, pcy_ = pcx.reshape(shp), pcy.reshape(shp)
    cx = d[..., 0] * pw_ + pcx_
    cy = d[..., 1] * ph_ + pcy_
    w = jnp.exp(d[..., 2]) * pw_
    h = jnp.exp(d[..., 3]) * ph_
    return Tensor(jnp.stack([cx - w / 2, cy - h / 2,
                             cx + w / 2 - norm, cy + h / 2 - norm],
                            axis=-1))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output [N, A*(5+C), H, W] → boxes + scores."""
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    imgs = ensure_tensor(img_size)._data.astype(jnp.float32)
    N, _, H, W = xd.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    feat = xd.reshape(N, A, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)
    gy = jnp.arange(H, dtype=jnp.float32)
    bx = (jax.nn.sigmoid(feat[:, :, 0]) * scale_x_y -
          (scale_x_y - 1) / 2 + gx[None, None, None, :]) / W
    by = (jax.nn.sigmoid(feat[:, :, 1]) * scale_x_y -
          (scale_x_y - 1) / 2 + gy[None, None, :, None]) / H
    bw = jnp.exp(feat[:, :, 2]) * an[None, :, 0, None, None] / \
        (W * downsample_ratio)
    bh = jnp.exp(feat[:, :, 3]) * an[None, :, 1, None, None] / \
        (H * downsample_ratio)
    obj = jax.nn.sigmoid(feat[:, :, 4])
    cls = jax.nn.sigmoid(feat[:, :, 5:])
    score = obj[:, :, None] * cls                      # [N, A, C, H, W]
    imw = imgs[:, 1].reshape(N, 1, 1, 1)
    imh = imgs[:, 0].reshape(N, 1, 1, 1)
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
    mask = (obj > conf_thresh)[:, :, None]
    scores = jnp.where(mask, score, 0.0)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
    return Tensor(boxes), Tensor(scores)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2 as bilinear im2col + MXU matmul.

    x: [N, Cin, H, W]; offset: [N, 2*dg*kh*kw, Ho, Wo];
    weight: [Cout, Cin/g, kh, kw]; mask (v2): [N, dg*kh*kw, Ho, Wo].
    """
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    od = ensure_tensor(offset)._data.astype(jnp.float32)
    wd = ensure_tensor(weight)._data.astype(jnp.float32)
    md = ensure_tensor(mask)._data.astype(jnp.float32) \
        if mask is not None else None
    sh, sw = (stride if isinstance(stride, (tuple, list))
              else (stride, stride))
    ph, pw = (padding if isinstance(padding, (tuple, list))
              else (padding, padding))
    dh, dw = (dilation if isinstance(dilation, (tuple, list))
              else (dilation, dilation))
    N, Cin, H, W = xd.shape
    Cout, _, kh, kw = wd.shape
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = deformable_groups
    off = od.reshape(N, dg, kh * kw, 2, Ho, Wo)

    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    # per kernel tap (kh*kw), per out position
    tap_y = (oy[None, :, None] +
             jnp.repeat(ky, kw)[:, None, None]).astype(jnp.float32)
    tap_x = (ox[None, None, :] +
             jnp.tile(kx, kh)[:, None, None]).astype(jnp.float32)

    cg = Cin // dg  # channels per deformable group
    # v2 mask defaults to all-ones (v1 semantics)
    msk_r = (md.reshape(N, dg, kh * kw, Ho * Wo) if md is not None
             else jnp.ones((N, dg, kh * kw, Ho * Wo), jnp.float32))

    def one_image(img, offs, msk):
        def one_group(g):
            feat = jax.lax.dynamic_slice_in_dim(img, g * cg, cg, axis=0)
            yy = tap_y + offs[g, :, 0]            # [kk, Ho, Wo]
            xx = tap_x + offs[g, :, 1]
            vals = jax.vmap(
                lambda y_, x_: _bilinear(feat, y_.reshape(-1),
                                         x_.reshape(-1)))(yy, xx)
            # vals: [kk, cg, Ho*Wo]
            return vals * msk[g][:, None, :]
        return jnp.concatenate([one_group(g) for g in range(dg)], axis=1)

    cols = jax.vmap(one_image)(
        xd, off.reshape(N, dg, kh * kw, 2, Ho, Wo), msk_r)
    # cols: [N, kk, Cin, Ho*Wo] → output = weight · cols
    wcol = wd.reshape(Cout, Cin // groups * kh * kw)
    out_groups = []
    cpg_in = Cin // groups
    cpg_out = Cout // groups
    cols_t = cols.transpose(0, 2, 1, 3)  # [N, Cin, kk, Ho*Wo]
    for g in range(groups):
        seg = cols_t[:, g * cpg_in:(g + 1) * cpg_in]  # [N,cpg,kk,HoWo]
        seg = seg.reshape(N, cpg_in * kh * kw, Ho * Wo)
        wseg = wcol[g * cpg_out:(g + 1) * cpg_out]
        out_groups.append(jnp.einsum("ok,nkp->nop", wseg, seg))
    out = jnp.concatenate(out_groups, axis=1).reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + ensure_tensor(bias)._data.reshape(1, -1, 1, 1)
    return Tensor(out)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._size, self._scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._size, self._scale)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..core.tensor import Parameter
        from ..nn import initializer as init
        kh, kw = (kernel_size if isinstance(kernel_size, (tuple, list))
                  else (kernel_size, kernel_size))
        self._args = (stride, padding, dilation, deformable_groups, groups)
        fan_in = in_channels * kh * kw
        w = init.XavierUniform(fan_in=fan_in,
                               fan_out=out_channels * kh * kw)(
            (out_channels, in_channels // groups, kh, kw), jnp.float32)
        self.weight = Parameter(w)
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((out_channels,), jnp.float32))
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._args
        return deform_conv2d(x, offset, self.weight, self.bias, s, p, d,
                             dg, g, mask)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order
              =False, name=None):
    """SSD prior (anchor) box generation (reference paddle.vision.ops.
    prior_box — upstream python/paddle/vision/ops.py, unverified).
    input: [N, C, H, W] feature map; image: [N, C, Him, Wim]. Returns
    (boxes [H, W, num_priors, 4] normalized xmin/ymin/xmax/ymax,
    variances broadcast to the same shape). Pure elementwise decode —
    one fused XLA kernel."""
    input, image = ensure_tensor(input), ensure_tensor(image)
    H, W = input.shape[2], input.shape[3]
    Him, Wim = image.shape[2], image.shape[3]
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    min_sizes = [float(m) for m in min_sizes]
    max_sizes = [float(m) for m in (max_sizes or [])]
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError("max_sizes must pair with min_sizes")
    step_w = float(steps[0]) or Wim / W
    step_h = float(steps[1]) or Him / H
    # per-cell prior (w, h) list in the reference's order
    whs = []
    for i, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                s = (ms * max_sizes[i]) ** 0.5
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
        else:
            for ar in ars:
                whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
            if max_sizes:
                s = (ms * max_sizes[i]) ** 0.5
                whs.append((s, s))

    def f(_in, _img):
        cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
        cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
        cx = cx[None, :, None] / Wim                        # [1, W, 1]
        cy = cy[:, None, None] / Him                        # [H, 1, 1]
        bw = jnp.asarray([w for w, _ in whs], jnp.float32)[None, None, :] \
            / (2.0 * Wim)
        bh = jnp.asarray([h for _, h in whs], jnp.float32)[None, None, :] \
            / (2.0 * Him)
        boxes = jnp.stack(jnp.broadcast_arrays(
            cx - bw, cy - bh, cx + bw, cy + bh), axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        return boxes, var

    return _apply(f, input, image, name="prior_box")


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2; reference paddle.vision.ops.matrix_nms —
    unverified). Decay-based soft suppression: for each candidate the
    min over higher-scored same-class boxes of decay(iou)/decay(max iou
    of the suppressor) — all-pairs, no sequential worklist, so it is
    one masked matrix program on the VPU (the design the paper picked
    for parallel hardware; exact, not an approximation).

    bboxes [N, M, 4], scores [N, C, M]. Static-shape contract: returns
    (out [N*keep_top_k, 6] rows (label, score, x1, y1, x2, y2) with
    score 0 padding, rois_num [N], index [N*keep_top_k, 1])."""
    bboxes, scores = ensure_tensor(bboxes), ensure_tensor(scores)
    N, M = bboxes.shape[0], bboxes.shape[1]
    C = scores.shape[1]
    # pixel-coordinate boxes measure +1 wide/tall (same convention as
    # box_coder's `norm` above)
    off = 0.0 if normalized else 1.0

    def one_image(boxes, scr):
        # flatten candidates over classes (skip background)
        cls_ids = jnp.arange(C)
        keep_cls = cls_ids != background_label
        flat_scores = jnp.where(keep_cls[:, None], scr, -1.0).reshape(-1)
        flat_cls = jnp.repeat(cls_ids, M)
        flat_box = jnp.tile(jnp.arange(M), C)
        ok = flat_scores > score_threshold
        flat_scores = jnp.where(ok, flat_scores, -1.0)
        k = min(nms_top_k, C * M)
        top_scores, top_idx = jax.lax.top_k(flat_scores, k)
        tcls = flat_cls[top_idx]
        tbox = boxes[flat_box[top_idx]]                       # [k, 4]
        valid = top_scores > score_threshold
        # pairwise IoU over the top-k
        area = jnp.maximum(tbox[:, 2] - tbox[:, 0] + off, 0.0) * \
            jnp.maximum(tbox[:, 3] - tbox[:, 1] + off, 0.0)
        lt = jnp.maximum(tbox[:, None, :2], tbox[None, :, :2])
        rb = jnp.minimum(tbox[:, None, 2:], tbox[None, :, 2:])
        wh = jnp.maximum(rb - lt + off, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                  1e-10)
        # suppressor mask: higher-scored (earlier in top-k), same class
        ii = jnp.arange(k)
        sup = (ii[None, :] < ii[:, None]) & \
            (tcls[:, None] == tcls[None, :]) & \
            valid[None, :] & valid[:, None]
        iou_s = jnp.where(sup, iou, 0.0)                      # [i, j]
        # comp[j]: suppressor j's own max IoU with ITS higher-scored
        # peers (the paper's normalizer)
        comp = jnp.max(iou_s, axis=1)                         # [k]
        if use_gaussian:
            decay = jnp.exp(-(iou_s ** 2 - comp[None, :] ** 2)
                            / gaussian_sigma)
        else:
            decay = (1.0 - iou_s) / jnp.maximum(1.0 - comp[None, :],
                                                1e-10)
        decay = jnp.where(sup, decay, 1.0)
        factor = jnp.min(decay, axis=1)
        new_scores = jnp.where(valid, top_scores * factor, 0.0)
        keep = new_scores > post_threshold
        new_scores = jnp.where(keep, new_scores, 0.0)
        kk = min(keep_top_k, k)
        fin_scores, fin_idx = jax.lax.top_k(new_scores, kk)
        rows = jnp.concatenate([
            tcls[fin_idx, None].astype(boxes.dtype),
            fin_scores[:, None].astype(boxes.dtype),
            tbox[fin_idx]], axis=1)
        cnt = jnp.sum((fin_scores > 0).astype(jnp.int32))
        src = flat_box[top_idx][fin_idx]
        return rows, cnt, src[:, None].astype(jnp.int32)

    def f(ba, sa):
        rows, cnt, idx = jax.vmap(one_image)(ba, sa)
        return (rows.reshape(-1, 6), cnt.astype(jnp.int32),
                idx.reshape(-1, 1))

    out, rois_num, index = _apply(f, bboxes, scores,
                                  name="matrix_nms")
    res = [out]
    if return_rois_num:
        res.append(rois_num)
    if return_index:
        res.append(index)
    return tuple(res) if len(res) > 1 else res[0]


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (R-FCN; reference paddle.vision.
    ops.psroi_pool — unverified). x: [N, C, H, W] with C = out_c*ps*ps;
    each (ph, pw) output bin average-pools its OWN channel group —
    static-shape bin averaging via masked means, vmapped over rois."""
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    if oh != ow:
        raise NotImplementedError("psroi_pool needs square output_size "
                                  "(position-sensitive channel split)")
    N, C, H, W = x.shape
    if C % (oh * ow) != 0:
        raise ValueError(f"channels {C} not divisible by "
                         f"output_size^2 {oh * ow}")
    out_c = C // (oh * ow)
    bn = [int(v) for v in np.asarray(boxes_num.numpy()
                                     if hasattr(boxes_num, "numpy")
                                     else boxes_num)]
    img_of_roi = np.repeat(np.arange(len(bn)), bn)

    def one_roi(box, img):
        x1, y1, x2, y2 = (box[i] * spatial_scale for i in range(4))
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / ow, rh / oh
        ph = jnp.arange(oh, dtype=jnp.float32)
        pw = jnp.arange(ow, dtype=jnp.float32)
        hs = jnp.floor(y1 + ph * bh)[:, None]        # [oh, 1]
        he = jnp.ceil(y1 + (ph + 1) * bh)[:, None]
        ws = jnp.floor(x1 + pw * bw)[None, :]        # [1, ow]
        we = jnp.ceil(x1 + (pw + 1) * bw)[None, :]
        ih = jnp.arange(H, dtype=jnp.float32)
        iw = jnp.arange(W, dtype=jnp.float32)
        # bin membership masks [oh, H] / [ow, W]
        mh = (ih[None, :] >= hs) & (ih[None, :] < he)  # [oh, H]
        mw = (iw[None, :] >= ws.T) & (iw[None, :] < we.T)  # [ow, W]
        feat = img.reshape(out_c, oh * ow, H, W)
        # per (ph, pw): mean over the bin of channel group ph*ow+pw
        m2 = (mh[:, None, :, None] & mw[None, :, None, :]).astype(
            jnp.float32)                              # [oh, ow, H, W]
        cnt = jnp.maximum(m2.sum((-1, -2)), 1.0)       # [oh, ow]
        grp = feat.reshape(out_c, oh, ow, H, W)
        s = jnp.einsum("cijhw,ijhw->cij", grp, m2)
        return s / cnt

    def f(xa, ba):
        imgs = xa[jnp.asarray(img_of_roi)]            # [R, C, H, W]
        return jax.vmap(one_roi)(ba, imgs)

    return _apply(f, x, boxes, name="psroi_pool")


def read_file(filename, name=None):
    """paddle.vision.ops.read_file: raw bytes as a uint8 1-D tensor
    (host IO — eager only, like the reference CPU kernel)."""
    with open(filename, "rb") as fh:
        data = fh.read()
    return Tensor(jnp.asarray(np.frombuffer(data, dtype=np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """paddle.vision.ops.decode_jpeg: JPEG bytes tensor → [C, H, W]
    uint8 (PIL-backed host decode; the reference uses nvjpeg on GPU —
    same contract, eager only)."""
    import io as _io

    from PIL import Image
    x = ensure_tensor(x)
    raw = bytes(np.asarray(x._data, dtype=np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode != "unchanged":
        img = img.convert(mode.upper() if mode != "gray" else "L")
    arr = np.asarray(img, dtype=np.uint8)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


__all__ += ["prior_box", "matrix_nms", "psroi_pool", "read_file",
            "decode_jpeg"]
