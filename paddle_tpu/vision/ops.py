"""paddle_tpu.vision.ops — detection ops (reference: paddle.vision.ops
nms/roi_align/roi_pool/deform_conv2d/box_coder/yolo_box — upstream
python/paddle/vision/ops.py + CUDA kernels in paddle/phi/kernels/gpu/,
unverified; see SURVEY.md §2.2 "Vision").

TPU-native design: every op is expressed with static shapes and
vectorized gathers so it compiles under jit —
- `nms` is the O(n²) mask formulation (pairwise IoU matrix + a lax scan
  over score rank) instead of the reference's dynamic worklist: no
  data-dependent shapes, MXU/VPU-friendly, exact same result;
- `roi_align`/`roi_pool` sample with batched bilinear gathers (one
  gather per pooling bin sample, vmapped over ROIs);
- `deform_conv2d` is im2col-with-deformed-offsets: bilinear-sample the
  input at offset positions → one big matmul (the MXU path);
- `box_coder`/`yolo_box` are pure elementwise decodes.
Outputs are fixed-size with validity masks where the reference returns
ragged results (the XLA static-shape contract; callers slice by the
returned count).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply as _apply
from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..ops._base import ensure_tensor

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "yolo_box",
           "deform_conv2d", "RoIAlign", "RoIPool", "DeformConv2D"]


def _box_iou(boxes):
    """Pairwise IoU of [N, 4] (x1, y1, x2, y2) boxes."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices sorted by descending score
    (fixed length N with -1 padding when compiled; eager returns the
    trimmed result like the reference).

    Multi-class (category_idxs given) offsets boxes per class so
    suppression never crosses classes (the reference's batched_nms
    trick).
    """
    b = ensure_tensor(boxes)._data.astype(jnp.float32)
    n = b.shape[0]
    sc = (ensure_tensor(scores)._data.astype(jnp.float32)
          if scores is not None else jnp.arange(n, 0, -1, jnp.float32))
    if category_idxs is not None:
        cat = ensure_tensor(category_idxs)._data
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (cat.astype(jnp.float32) * span)[:, None]

    order = jnp.argsort(-sc)
    iou = _box_iou(b)[order][:, order]

    def step(keep, i):
        # keep[i] stays True only if no higher-ranked kept box overlaps
        sup = jnp.any(keep & (jnp.arange(n) < i) & (iou[i] > iou_threshold))
        keep = keep.at[i].set(~sup)
        return keep, None

    keep0 = jnp.ones((n,), bool)
    keep, _ = jax.lax.scan(step, keep0, jnp.arange(n))
    kept_sorted = jnp.where(keep, order, -1)  # rank order, -1 = suppressed
    # compact: kept indices first (stable), -1 padding after
    key = jnp.where(keep, jnp.arange(n), n)
    perm = jnp.argsort(key)
    out = kept_sorted[perm]
    if isinstance(out, jax.core.Tracer):
        # top_k is a Python int, so the slice is shape-static and legal
        # under trace; -1 padding semantics are preserved.
        if top_k is not None:
            out = out[:top_k]
        return Tensor(out)
    out = out[out >= 0]
    if top_k is not None:
        out = out[:top_k]
    return Tensor(out)


def _bilinear(feat, y, x):
    """Sample feat [C, H, W] at fractional (y, x) — zero outside."""
    H, W = feat.shape[1], feat.shape[2]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = y - y0
    wx1 = x - x0
    wy0, wx0 = 1 - wy1, 1 - wx1

    def tap(yi, xi, w):
        valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        v = feat[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
        return v * (w * valid)

    return (tap(y0, x0, wy0 * wx0) + tap(y0, x1, wy0 * wx1) +
            tap(y1, x0, wy1 * wx0) + tap(y1, x1, wy1 * wx1))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (reference semantics incl. `aligned` half-pixel shift).

    x: [N, C, H, W]; boxes: [R, 4] in input coords; boxes_num: [N] ROIs
    per image (prefix-assigns ROIs to images). Returns [R, C, ph, pw].

    Numerics note: with sampling_ratio<=0 the reference adapts the
    sub-sample count per ROI (ceil(roi_size/pooled_size)); that is a
    data-dependent shape, illegal under XLA's static-shape contract, so
    this implementation uses a fixed ratio of 2 (the common detector
    setting). Outputs deviate slightly from reference numerics for ROIs
    much larger than the output grid; pass an explicit sampling_ratio to
    pin the reference behavior you need.
    """
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    bx = ensure_tensor(boxes)._data.astype(jnp.float32)
    bn = ensure_tensor(boxes_num)._data
    ph, pw = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    ratio = 2 if sampling_ratio <= 0 else int(sampling_ratio)
    off = 0.5 if aligned else 0.0
    img_of_roi = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                            total_repeat_length=bx.shape[0])

    # sample positions inside the ROI: `ratio` uniform sub-samples per
    # output cell (uniform over the whole ROI == per-bin sampling)
    cell = jnp.arange(ph * ratio, dtype=jnp.float32)
    frac_y = (cell + 0.5) / (ph * ratio)  # uniform — equals per-bin sampling
    cellx = jnp.arange(pw * ratio, dtype=jnp.float32)
    frac_x = (cellx + 0.5) / (pw * ratio)

    def one_roi(box, img):
        x1, y1, x2, y2 = box * spatial_scale
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        h = y2 - y1 if aligned else jnp.maximum(y2 - y1, 1.0)
        w = x2 - x1 if aligned else jnp.maximum(x2 - x1, 1.0)
        ys = y1 + frac_y * h                      # [ph*ratio]
        xs = x1 + frac_x * w                      # [pw*ratio]
        yy = jnp.repeat(ys, pw * ratio)
        xx = jnp.tile(xs, ph * ratio)
        vals = _bilinear(xd[img], yy, xx)         # [C, ph*r*pw*r]
        C = vals.shape[0]
        vals = vals.reshape(C, ph, ratio, pw, ratio)
        return vals.mean(axis=(2, 4))             # [C, ph, pw]

    out = jax.vmap(one_roi)(bx, img_of_roi)
    return Tensor(out)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """RoIPool via dense max over adaptive bins (gather formulation)."""
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    bx = ensure_tensor(boxes)._data.astype(jnp.float32)
    bn = ensure_tensor(boxes_num)._data
    ph, pw = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    H, W = xd.shape[2], xd.shape[3]
    img_of_roi = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                            total_repeat_length=bx.shape[0])
    iy = jnp.arange(H)
    ix = jnp.arange(W)

    def one_roi(box, img):
        x1 = jnp.floor(box[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.floor(box[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.ceil(box[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.ceil(box[3] * spatial_scale).astype(jnp.int32)
        hh = jnp.maximum(y2 - y1, 1).astype(jnp.float32)
        ww = jnp.maximum(x2 - x1, 1).astype(jnp.float32)
        # bin index of every pixel (pixels outside the ROI get -1)
        by = jnp.floor((iy - y1).astype(jnp.float32) * ph / hh).astype(
            jnp.int32)
        bxx = jnp.floor((ix - x1).astype(jnp.float32) * pw / ww).astype(
            jnp.int32)
        by = jnp.where((iy >= y1) & (iy < jnp.maximum(y2, y1 + 1)),
                       jnp.clip(by, 0, ph - 1), -1)
        bxx = jnp.where((ix >= x1) & (ix < jnp.maximum(x2, x1 + 1)),
                        jnp.clip(bxx, 0, pw - 1), -1)
        onehot_y = (by[:, None] == jnp.arange(ph)[None, :])   # [H, ph]
        onehot_x = (bxx[:, None] == jnp.arange(pw)[None, :])  # [W, pw]
        feat = xd[img]                                        # [C, H, W]
        neg = jnp.finfo(jnp.float32).min
        masked = jnp.where(onehot_y[None, :, None, :, None] &
                           onehot_x[None, None, :, None, :],
                           feat[:, :, :, None, None], neg)
        pooled = masked.max(axis=(1, 2))                      # [C, ph, pw]
        return jnp.where(pooled == neg, 0.0, pooled)

    return Tensor(jax.vmap(one_roi)(bx, img_of_roi))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """Encode/decode boxes against priors (reference box_coder)."""
    pb = ensure_tensor(prior_box)._data.astype(jnp.float32)
    pbv = (ensure_tensor(prior_box_var)._data.astype(jnp.float32)
           if prior_box_var is not None else None)
    tb = ensure_tensor(target_box)._data.astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    phh = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + phh * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / phh[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / phh[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if pbv is not None:
            out = out / pbv[None, :, :]
        return Tensor(out)
    # decode: target [N, M, 4] deltas against priors on `axis`
    if tb.ndim == 2:
        tb = tb[:, None, :]
    d = tb * (pbv[None, :, :] if pbv is not None else 1.0)
    shp = (1, -1) if axis == 0 else (-1, 1)
    pw_, ph_ = pw.reshape(shp), phh.reshape(shp)
    pcx_, pcy_ = pcx.reshape(shp), pcy.reshape(shp)
    cx = d[..., 0] * pw_ + pcx_
    cy = d[..., 1] * ph_ + pcy_
    w = jnp.exp(d[..., 2]) * pw_
    h = jnp.exp(d[..., 3]) * ph_
    return Tensor(jnp.stack([cx - w / 2, cy - h / 2,
                             cx + w / 2 - norm, cy + h / 2 - norm],
                            axis=-1))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output [N, A*(5+C), H, W] → boxes + scores."""
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    imgs = ensure_tensor(img_size)._data.astype(jnp.float32)
    N, _, H, W = xd.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    feat = xd.reshape(N, A, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)
    gy = jnp.arange(H, dtype=jnp.float32)
    bx = (jax.nn.sigmoid(feat[:, :, 0]) * scale_x_y -
          (scale_x_y - 1) / 2 + gx[None, None, None, :]) / W
    by = (jax.nn.sigmoid(feat[:, :, 1]) * scale_x_y -
          (scale_x_y - 1) / 2 + gy[None, None, :, None]) / H
    bw = jnp.exp(feat[:, :, 2]) * an[None, :, 0, None, None] / \
        (W * downsample_ratio)
    bh = jnp.exp(feat[:, :, 3]) * an[None, :, 1, None, None] / \
        (H * downsample_ratio)
    obj = jax.nn.sigmoid(feat[:, :, 4])
    cls = jax.nn.sigmoid(feat[:, :, 5:])
    score = obj[:, :, None] * cls                      # [N, A, C, H, W]
    imw = imgs[:, 1].reshape(N, 1, 1, 1)
    imh = imgs[:, 0].reshape(N, 1, 1, 1)
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
    mask = (obj > conf_thresh)[:, :, None]
    scores = jnp.where(mask, score, 0.0)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
    return Tensor(boxes), Tensor(scores)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2 as bilinear im2col + MXU matmul.

    x: [N, Cin, H, W]; offset: [N, 2*dg*kh*kw, Ho, Wo];
    weight: [Cout, Cin/g, kh, kw]; mask (v2): [N, dg*kh*kw, Ho, Wo].
    """
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    od = ensure_tensor(offset)._data.astype(jnp.float32)
    wd = ensure_tensor(weight)._data.astype(jnp.float32)
    md = ensure_tensor(mask)._data.astype(jnp.float32) \
        if mask is not None else None
    sh, sw = (stride if isinstance(stride, (tuple, list))
              else (stride, stride))
    ph, pw = (padding if isinstance(padding, (tuple, list))
              else (padding, padding))
    dh, dw = (dilation if isinstance(dilation, (tuple, list))
              else (dilation, dilation))
    N, Cin, H, W = xd.shape
    Cout, _, kh, kw = wd.shape
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = deformable_groups
    off = od.reshape(N, dg, kh * kw, 2, Ho, Wo)

    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    # per kernel tap (kh*kw), per out position
    tap_y = (oy[None, :, None] +
             jnp.repeat(ky, kw)[:, None, None]).astype(jnp.float32)
    tap_x = (ox[None, None, :] +
             jnp.tile(kx, kh)[:, None, None]).astype(jnp.float32)

    cg = Cin // dg  # channels per deformable group
    # v2 mask defaults to all-ones (v1 semantics)
    msk_r = (md.reshape(N, dg, kh * kw, Ho * Wo) if md is not None
             else jnp.ones((N, dg, kh * kw, Ho * Wo), jnp.float32))

    def one_image(img, offs, msk):
        def one_group(g):
            feat = jax.lax.dynamic_slice_in_dim(img, g * cg, cg, axis=0)
            yy = tap_y + offs[g, :, 0]            # [kk, Ho, Wo]
            xx = tap_x + offs[g, :, 1]
            vals = jax.vmap(
                lambda y_, x_: _bilinear(feat, y_.reshape(-1),
                                         x_.reshape(-1)))(yy, xx)
            # vals: [kk, cg, Ho*Wo]
            return vals * msk[g][:, None, :]
        return jnp.concatenate([one_group(g) for g in range(dg)], axis=1)

    cols = jax.vmap(one_image)(
        xd, off.reshape(N, dg, kh * kw, 2, Ho, Wo), msk_r)
    # cols: [N, kk, Cin, Ho*Wo] → output = weight · cols
    wcol = wd.reshape(Cout, Cin // groups * kh * kw)
    out_groups = []
    cpg_in = Cin // groups
    cpg_out = Cout // groups
    cols_t = cols.transpose(0, 2, 1, 3)  # [N, Cin, kk, Ho*Wo]
    for g in range(groups):
        seg = cols_t[:, g * cpg_in:(g + 1) * cpg_in]  # [N,cpg,kk,HoWo]
        seg = seg.reshape(N, cpg_in * kh * kw, Ho * Wo)
        wseg = wcol[g * cpg_out:(g + 1) * cpg_out]
        out_groups.append(jnp.einsum("ok,nkp->nop", wseg, seg))
    out = jnp.concatenate(out_groups, axis=1).reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + ensure_tensor(bias)._data.reshape(1, -1, 1, 1)
    return Tensor(out)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._size, self._scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._size, self._scale)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..core.tensor import Parameter
        from ..nn import initializer as init
        kh, kw = (kernel_size if isinstance(kernel_size, (tuple, list))
                  else (kernel_size, kernel_size))
        self._args = (stride, padding, dilation, deformable_groups, groups)
        fan_in = in_channels * kh * kw
        w = init.XavierUniform(fan_in=fan_in,
                               fan_out=out_channels * kh * kw)(
            (out_channels, in_channels // groups, kh, kw), jnp.float32)
        self.weight = Parameter(w)
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((out_channels,), jnp.float32))
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._args
        return deform_conv2d(x, offset, self.weight, self.bias, s, p, d,
                             dg, g, mask)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order
              =False, name=None):
    """SSD prior (anchor) box generation (reference paddle.vision.ops.
    prior_box — upstream python/paddle/vision/ops.py, unverified).
    input: [N, C, H, W] feature map; image: [N, C, Him, Wim]. Returns
    (boxes [H, W, num_priors, 4] normalized xmin/ymin/xmax/ymax,
    variances broadcast to the same shape). Pure elementwise decode —
    one fused XLA kernel."""
    input, image = ensure_tensor(input), ensure_tensor(image)
    H, W = input.shape[2], input.shape[3]
    Him, Wim = image.shape[2], image.shape[3]
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    min_sizes = [float(m) for m in min_sizes]
    max_sizes = [float(m) for m in (max_sizes or [])]
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError("max_sizes must pair with min_sizes")
    step_w = float(steps[0]) or Wim / W
    step_h = float(steps[1]) or Him / H
    # per-cell prior (w, h) list in the reference's order
    whs = []
    for i, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                s = (ms * max_sizes[i]) ** 0.5
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
        else:
            for ar in ars:
                whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
            if max_sizes:
                s = (ms * max_sizes[i]) ** 0.5
                whs.append((s, s))

    def f(_in, _img):
        cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
        cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
        cx = cx[None, :, None] / Wim                        # [1, W, 1]
        cy = cy[:, None, None] / Him                        # [H, 1, 1]
        bw = jnp.asarray([w for w, _ in whs], jnp.float32)[None, None, :] \
            / (2.0 * Wim)
        bh = jnp.asarray([h for _, h in whs], jnp.float32)[None, None, :] \
            / (2.0 * Him)
        boxes = jnp.stack(jnp.broadcast_arrays(
            cx - bw, cy - bh, cx + bw, cy + bh), axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        return boxes, var

    return _apply(f, input, image, name="prior_box")


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2; reference paddle.vision.ops.matrix_nms —
    unverified). Decay-based soft suppression: for each candidate the
    min over higher-scored same-class boxes of decay(iou)/decay(max iou
    of the suppressor) — all-pairs, no sequential worklist, so it is
    one masked matrix program on the VPU (the design the paper picked
    for parallel hardware; exact, not an approximation).

    bboxes [N, M, 4], scores [N, C, M]. Static-shape contract: returns
    (out [N*keep_top_k, 6] rows (label, score, x1, y1, x2, y2) with
    score 0 padding, rois_num [N], index [N*keep_top_k, 1])."""
    bboxes, scores = ensure_tensor(bboxes), ensure_tensor(scores)
    N, M = bboxes.shape[0], bboxes.shape[1]
    C = scores.shape[1]
    # pixel-coordinate boxes measure +1 wide/tall (same convention as
    # box_coder's `norm` above)
    off = 0.0 if normalized else 1.0

    def one_image(boxes, scr):
        # flatten candidates over classes (skip background)
        cls_ids = jnp.arange(C)
        keep_cls = cls_ids != background_label
        flat_scores = jnp.where(keep_cls[:, None], scr, -1.0).reshape(-1)
        flat_cls = jnp.repeat(cls_ids, M)
        flat_box = jnp.tile(jnp.arange(M), C)
        ok = flat_scores > score_threshold
        flat_scores = jnp.where(ok, flat_scores, -1.0)
        k = min(nms_top_k, C * M)
        top_scores, top_idx = jax.lax.top_k(flat_scores, k)
        tcls = flat_cls[top_idx]
        tbox = boxes[flat_box[top_idx]]                       # [k, 4]
        valid = top_scores > score_threshold
        # pairwise IoU over the top-k
        area = jnp.maximum(tbox[:, 2] - tbox[:, 0] + off, 0.0) * \
            jnp.maximum(tbox[:, 3] - tbox[:, 1] + off, 0.0)
        lt = jnp.maximum(tbox[:, None, :2], tbox[None, :, :2])
        rb = jnp.minimum(tbox[:, None, 2:], tbox[None, :, 2:])
        wh = jnp.maximum(rb - lt + off, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                  1e-10)
        # suppressor mask: higher-scored (earlier in top-k), same class
        ii = jnp.arange(k)
        sup = (ii[None, :] < ii[:, None]) & \
            (tcls[:, None] == tcls[None, :]) & \
            valid[None, :] & valid[:, None]
        iou_s = jnp.where(sup, iou, 0.0)                      # [i, j]
        # comp[j]: suppressor j's own max IoU with ITS higher-scored
        # peers (the paper's normalizer)
        comp = jnp.max(iou_s, axis=1)                         # [k]
        if use_gaussian:
            decay = jnp.exp(-(iou_s ** 2 - comp[None, :] ** 2)
                            / gaussian_sigma)
        else:
            decay = (1.0 - iou_s) / jnp.maximum(1.0 - comp[None, :],
                                                1e-10)
        decay = jnp.where(sup, decay, 1.0)
        factor = jnp.min(decay, axis=1)
        new_scores = jnp.where(valid, top_scores * factor, 0.0)
        keep = new_scores > post_threshold
        new_scores = jnp.where(keep, new_scores, 0.0)
        kk = min(keep_top_k, k)
        fin_scores, fin_idx = jax.lax.top_k(new_scores, kk)
        rows = jnp.concatenate([
            tcls[fin_idx, None].astype(boxes.dtype),
            fin_scores[:, None].astype(boxes.dtype),
            tbox[fin_idx]], axis=1)
        cnt = jnp.sum((fin_scores > 0).astype(jnp.int32))
        src = flat_box[top_idx][fin_idx]
        return rows, cnt, src[:, None].astype(jnp.int32)

    def f(ba, sa):
        rows, cnt, idx = jax.vmap(one_image)(ba, sa)
        return (rows.reshape(-1, 6), cnt.astype(jnp.int32),
                idx.reshape(-1, 1))

    out, rois_num, index = _apply(f, bboxes, scores,
                                  name="matrix_nms")
    res = [out]
    if return_rois_num:
        res.append(rois_num)
    if return_index:
        res.append(index)
    return tuple(res) if len(res) > 1 else res[0]


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (R-FCN; reference paddle.vision.
    ops.psroi_pool — unverified). x: [N, C, H, W] with C = out_c*ps*ps;
    each (ph, pw) output bin average-pools its OWN channel group —
    static-shape bin averaging via masked means, vmapped over rois."""
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    if oh != ow:
        raise NotImplementedError("psroi_pool needs square output_size "
                                  "(position-sensitive channel split)")
    N, C, H, W = x.shape
    if C % (oh * ow) != 0:
        raise ValueError(f"channels {C} not divisible by "
                         f"output_size^2 {oh * ow}")
    out_c = C // (oh * ow)
    bn = [int(v) for v in np.asarray(boxes_num.numpy()
                                     if hasattr(boxes_num, "numpy")
                                     else boxes_num)]
    img_of_roi = np.repeat(np.arange(len(bn)), bn)

    def one_roi(box, img):
        x1, y1, x2, y2 = (box[i] * spatial_scale for i in range(4))
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / ow, rh / oh
        ph = jnp.arange(oh, dtype=jnp.float32)
        pw = jnp.arange(ow, dtype=jnp.float32)
        hs = jnp.floor(y1 + ph * bh)[:, None]        # [oh, 1]
        he = jnp.ceil(y1 + (ph + 1) * bh)[:, None]
        ws = jnp.floor(x1 + pw * bw)[None, :]        # [1, ow]
        we = jnp.ceil(x1 + (pw + 1) * bw)[None, :]
        ih = jnp.arange(H, dtype=jnp.float32)
        iw = jnp.arange(W, dtype=jnp.float32)
        # bin membership masks [oh, H] / [ow, W]
        mh = (ih[None, :] >= hs) & (ih[None, :] < he)  # [oh, H]
        mw = (iw[None, :] >= ws.T) & (iw[None, :] < we.T)  # [ow, W]
        feat = img.reshape(out_c, oh * ow, H, W)
        # per (ph, pw): mean over the bin of channel group ph*ow+pw
        m2 = (mh[:, None, :, None] & mw[None, :, None, :]).astype(
            jnp.float32)                              # [oh, ow, H, W]
        cnt = jnp.maximum(m2.sum((-1, -2)), 1.0)       # [oh, ow]
        grp = feat.reshape(out_c, oh, ow, H, W)
        s = jnp.einsum("cijhw,ijhw->cij", grp, m2)
        return s / cnt

    def f(xa, ba):
        imgs = xa[jnp.asarray(img_of_roi)]            # [R, C, H, W]
        return jax.vmap(one_roi)(ba, imgs)

    return _apply(f, x, boxes, name="psroi_pool")


def read_file(filename, name=None):
    """paddle.vision.ops.read_file: raw bytes as a uint8 1-D tensor
    (host IO — eager only, like the reference CPU kernel)."""
    with open(filename, "rb") as fh:
        data = fh.read()
    return Tensor(jnp.asarray(np.frombuffer(data, dtype=np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """paddle.vision.ops.decode_jpeg: JPEG bytes tensor → [C, H, W]
    uint8 (PIL-backed host decode; the reference uses nvjpeg on GPU —
    same contract, eager only)."""
    import io as _io

    from PIL import Image
    x = ensure_tensor(x)
    raw = bytes(np.asarray(x._data, dtype=np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode != "unchanged":
        img = img.convert(mode.upper() if mode != "gray" else "L")
    arr = np.asarray(img, dtype=np.uint8)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


__all__ += ["prior_box", "matrix_nms", "psroi_pool", "read_file",
            "decode_jpeg"]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference paddle.vision.ops.yolo_loss /
    phi yolov3_loss kernel — upstream unverified; formulas follow the
    YOLOv3 paper + the reference kernel structure):

    - x: [N, A*(5+class_num), H, W] raw head output (A = len(anchor_mask));
    - gt_box [N, B, 4] normalized (cx, cy, w, h), gt_label [N, B],
      gt_score [N, B] (mixup weight, default 1);
    - per-gt responsibility: best wh-IoU over ALL anchors; the gt is
      assigned only if that anchor belongs to this head's anchor_mask,
      at cell (floor(cx*W), floor(cy*H));
    - sigmoid-CE for x/y/objectness/class, L1 for w/h, box weight
      (2 − w·h)·score; negatives whose best IoU with any gt exceeds
      `ignore_thresh` are ignored; label smoothing moves targets to
      (1−δ, δ), δ = min(1/class_num, 1/40).

    TPU-native: everything is dense [N, A, H, W] target maps built by a
    lax.fori_loop of per-gt scatters (deterministic last-writer, B is
    small) + one fused elementwise loss — no dynamic shapes. Returns
    the per-sample loss [N]."""
    x = ensure_tensor(x)
    gt_box, gt_label = ensure_tensor(gt_box), ensure_tensor(gt_label)
    args = [x, gt_box, gt_label]
    if gt_score is not None:
        args.append(ensure_tensor(gt_score))
    anchors = [float(a) for a in anchors]
    amask = [int(a) for a in anchor_mask]
    A = len(amask)
    n_anchors = len(anchors) // 2
    N, C, H, W = x.shape
    if C != A * (5 + class_num):
        raise ValueError(f"x channels {C} != len(anchor_mask)*(5+cls) "
                         f"= {A * (5 + class_num)}")
    B = gt_box.shape[1]
    in_w, in_h = W * downsample_ratio, H * downsample_ratio
    aw_all = jnp.asarray(anchors[0::2], jnp.float32) / in_w   # normalized
    ah_all = jnp.asarray(anchors[1::2], jnp.float32) / in_h
    aw = aw_all[jnp.asarray(amask)]
    ah = ah_all[jnp.asarray(amask)]
    delta = min(1.0 / class_num, 1.0 / 40.0) if use_label_smooth else 0.0
    sx = float(scale_x_y)

    def bce(logit, label):
        # sigmoid cross entropy with logits, stable form
        return jnp.maximum(logit, 0) - logit * label + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def f(xa, gb, gl, *rest):
        gs = rest[0] if rest else jnp.ones((N, B), jnp.float32)
        xa = xa.reshape(N, A, 5 + class_num, H, W).astype(jnp.float32)
        tx, ty, tw, th = xa[:, :, 0], xa[:, :, 1], xa[:, :, 2], xa[:, :, 3]
        tobj = xa[:, :, 4]
        tcls = xa[:, :, 5:]                       # [N, A, cls, H, W]
        gb = gb.astype(jnp.float32)
        gs = gs.astype(jnp.float32)
        valid = (gb[..., 2] > 0) & (gb[..., 3] > 0)          # [N, B]

        # decoded pred boxes (normalized) for the ignore mask
        ix = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        iy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        px = (ix + sx * jax.nn.sigmoid(tx) - 0.5 * (sx - 1.0)) / W
        py = (iy + sx * jax.nn.sigmoid(ty) - 0.5 * (sx - 1.0)) / H
        pw = aw[None, :, None, None] * jnp.exp(tw)
        phh = ah[None, :, None, None] * jnp.exp(th)
        # IoU pred [N,A,H,W] x gt [N,B] -> max over B
        px1, py1 = px - pw / 2, py - phh / 2
        px2, py2 = px + pw / 2, py + phh / 2
        gx1 = (gb[..., 0] - gb[..., 2] / 2)[:, None, None, None, :]
        gy1 = (gb[..., 1] - gb[..., 3] / 2)[:, None, None, None, :]
        gx2 = (gb[..., 0] + gb[..., 2] / 2)[:, None, None, None, :]
        gy2 = (gb[..., 1] + gb[..., 3] / 2)[:, None, None, None, :]
        iw = jnp.maximum(jnp.minimum(px2[..., None], gx2)
                         - jnp.maximum(px1[..., None], gx1), 0.0)
        ih = jnp.maximum(jnp.minimum(py2[..., None], gy2)
                         - jnp.maximum(py1[..., None], gy1), 0.0)
        inter = iw * ih
        union = (pw * phh)[..., None] + \
            (gb[..., 2] * gb[..., 3])[:, None, None, None, :] - inter
        iou = jnp.where(valid[:, None, None, None, :],
                        inter / jnp.maximum(union, 1e-10), 0.0)
        ignore = jnp.max(iou, axis=-1) > ignore_thresh       # [N,A,H,W]

        # per-gt responsible anchor over ALL anchors (wh IoU)
        ginter = jnp.minimum(gb[..., 2:3], aw_all[None, None, :]) * \
            jnp.minimum(gb[..., 3:4], ah_all[None, None, :])
        gunion = gb[..., 2:3] * gb[..., 3:4] + \
            (aw_all * ah_all)[None, None, :] - ginter
        best = jnp.argmax(ginter / jnp.maximum(gunion, 1e-10), -1)
        slot_of = jnp.full((n_anchors,), -1, jnp.int32)
        for s, a in enumerate(amask):
            slot_of = slot_of.at[a].set(s)
        slot = slot_of[best]                                  # [N, B]
        gi = jnp.clip((gb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[..., 1] * H).astype(jnp.int32), 0, H - 1)
        assigned = valid & (slot >= 0)

        # dense target maps via deterministic per-gt scatter
        zero = jnp.zeros((N, A, H, W), jnp.float32)
        maps0 = {"pos": zero, "tx": zero, "ty": zero, "tw": zero,
                 "th": zero, "wt": zero, "score": zero,
                 "label": jnp.zeros((N, A, H, W), jnp.int32)}
        nidx = jnp.arange(N)

        def body(b, maps):
            ok = assigned[:, b]                                # [N]
            s = jnp.where(ok, slot[:, b], 0)
            jj = jnp.where(ok, gj[:, b], 0)
            ii = jnp.where(ok, gi[:, b], 0)

            def put(m, v):
                cur = m[nidx, s, jj, ii]
                new = jnp.where(ok, v, cur)
                return m.at[nidx, s, jj, ii].set(
                    new.astype(m.dtype))

            txv = gb[:, b, 0] * W - ii.astype(jnp.float32)
            tyv = gb[:, b, 1] * H - jj.astype(jnp.float32)
            twv = jnp.log(jnp.maximum(
                gb[:, b, 2] / jnp.maximum(aw[s], 1e-10), 1e-10))
            thv = jnp.log(jnp.maximum(
                gb[:, b, 3] / jnp.maximum(ah[s], 1e-10), 1e-10))
            wtv = (2.0 - gb[:, b, 2] * gb[:, b, 3]) * gs[:, b]
            maps = dict(maps)
            maps["pos"] = put(maps["pos"], jnp.ones((N,)))
            maps["tx"] = put(maps["tx"], txv)
            maps["ty"] = put(maps["ty"], tyv)
            maps["tw"] = put(maps["tw"], twv)
            maps["th"] = put(maps["th"], thv)
            maps["wt"] = put(maps["wt"], wtv)
            maps["score"] = put(maps["score"], gs[:, b])
            maps["label"] = put(maps["label"], gl[:, b].astype(jnp.int32))
            return maps

        maps = jax.lax.fori_loop(0, B, body, maps0)
        pos = maps["pos"]

        loss_xy = maps["wt"] * (bce(tx, maps["tx"]) + bce(ty, maps["ty"]))
        loss_wh = maps["wt"] * (jnp.abs(tw - maps["tw"])
                                + jnp.abs(th - maps["th"]))
        obj_pos = maps["score"] * bce(tobj, jnp.ones_like(tobj))
        obj_neg = bce(tobj, jnp.zeros_like(tobj))
        loss_obj = jnp.where(pos > 0, obj_pos,
                             jnp.where(ignore, 0.0, obj_neg))
        onehot = jax.nn.one_hot(maps["label"], class_num,
                                axis=2)                     # [N,A,cls,H,W]
        cls_target = onehot * (1.0 - delta) + (1 - onehot) * delta
        loss_cls = maps["score"][:, :, None] * \
            bce(tcls, cls_target) * pos[:, :, None]
        per_sample = (jnp.sum((loss_xy + loss_wh) * pos, axis=(1, 2, 3))
                      + jnp.sum(loss_obj, axis=(1, 2, 3))
                      + jnp.sum(loss_cls, axis=(1, 2, 3, 4)))
        return per_sample

    return _apply(f, *args, name="yolo_loss")


__all__ += ["yolo_loss"]


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference paddle.vision.ops.
    distribute_fpn_proposals — unverified): level = floor(log2(
    sqrt(area)/refer_scale + eps)) + refer_level, clamped to
    [min_level, max_level]. Returns (multi_rois list low→high level,
    restore_ind [R, 1], rois_num_per_level list or None).

    EAGER-ONLY: per-level counts are data-dependent (ragged output), so
    this is a host op like the reference's CPU kernel; under tracing it
    raises (use level masks for a compiled pipeline)."""
    fpn_rois = ensure_tensor(fpn_rois)
    if isinstance(fpn_rois._data, jax.core.Tracer):
        raise RuntimeError(
            "distribute_fpn_proposals is eager-only (ragged outputs); "
            "compute level masks instead inside jit")
    rois = np.asarray(fpn_rois._data, np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = np.maximum(rois[:, 2] - rois[:, 0] + off, 0.0)
    h = np.maximum(rois[:, 3] - rois[:, 1] + off, 0.0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / float(refer_scale) + 1e-8)) \
        + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois, order = [], []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        order.append(idx)
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
    order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.shape[0])
    restore_ind = Tensor(jnp.asarray(restore[:, None].astype(np.int32)))
    if rois_num is not None:
        rn = np.asarray(ensure_tensor(rois_num)._data)
        img_of = np.repeat(np.arange(rn.shape[0]), rn)
        per_level = [
            Tensor(jnp.asarray(np.bincount(
                img_of[lvl == L], minlength=rn.shape[0]).astype(np.int32)))
            for L in range(min_level, max_level + 1)]
        return multi_rois, restore_ind, per_level
    return multi_rois, restore_ind, None


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference paddle.vision.ops.
    generate_proposals — unverified): decode anchor deltas → clip to the
    image → drop boxes smaller than min_size → top pre_nms_top_n by
    score → greedy NMS → top post_nms_top_n. EAGER-ONLY host op (ragged
    output), composed from box_coder-style decode + this module's nms.

    scores [N, A, H, W]; bbox_deltas [N, 4A, H, W]; img_size [N, 2]
    (h, w); anchors [H, W, A, 4] or [H*W*A, 4]; variances same shape.
    Returns (rpn_rois [R, 4], rpn_roi_probs [R, 1][, rois_num])."""
    scores, bbox_deltas = ensure_tensor(scores), ensure_tensor(bbox_deltas)
    if isinstance(scores._data, jax.core.Tracer):
        raise RuntimeError("generate_proposals is eager-only (ragged "
                           "outputs)")
    sc = np.asarray(scores._data, np.float32)
    bd = np.asarray(bbox_deltas._data, np.float32)
    isz = np.asarray(ensure_tensor(img_size)._data, np.float32)
    anc = np.asarray(ensure_tensor(anchors)._data, np.float32).reshape(-1, 4)
    var = np.asarray(ensure_tensor(variances)._data,
                     np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_probs, nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # [H*W*A]
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        k = min(pre_nms_top_n, s.shape[0])
        top = np.argsort(-s)[:k]
        s_k, d_k, a_k, v_k = s[top], d[top], anc[top], var[top]
        # decode (box_coder decode_center_size semantics)
        aw = a_k[:, 2] - a_k[:, 0] + off
        ah = a_k[:, 3] - a_k[:, 1] + off
        acx = a_k[:, 0] + aw / 2
        acy = a_k[:, 1] + ah / 2
        cx = v_k[:, 0] * d_k[:, 0] * aw + acx
        cy = v_k[:, 1] * d_k[:, 1] * ah + acy
        bw = np.exp(np.minimum(v_k[:, 2] * d_k[:, 2], 10.0)) * aw
        bh = np.exp(np.minimum(v_k[:, 3] * d_k[:, 3], 10.0)) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], 1)
        ih, iw = isz[n, 0], isz[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        keep = ((boxes[:, 2] - boxes[:, 0] + off >= min_size) &
                (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s_k = boxes[keep], s_k[keep]
        if boxes.shape[0]:
            kept = np.asarray(nms(Tensor(jnp.asarray(boxes)),
                                  iou_threshold=nms_thresh,
                                  scores=Tensor(jnp.asarray(s_k)),
                                  top_k=post_nms_top_n).numpy())
            boxes, s_k = boxes[kept], s_k[kept]
        all_rois.append(boxes)
        all_probs.append(s_k[:, None])
        nums.append(boxes.shape[0])
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)
                              if all_rois else np.zeros((0, 4))))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0)
                               if all_probs else np.zeros((0, 1))))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    return rois, probs


__all__ += ["distribute_fpn_proposals", "generate_proposals"]
