"""paddle_tpu.vision (paddle.vision parity)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401

# image IO backend (reference paddle.vision get/set_image_backend,
# image_load — upstream python/paddle/vision/image.py, unverified).
# 'pil' is the only backend in this image ('cv2' would need opencv).
_image_backend = "pil"


def get_image_backend():
    return _image_backend


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got "
                         f"{backend!r}")
    if backend == "cv2":
        raise NotImplementedError("cv2 backend needs opencv (not in "
                                  "this image); use 'pil'")
    _image_backend = backend


def image_load(path, backend=None):
    """Load an image file -> PIL.Image (pil backend)."""
    b = backend or _image_backend
    if b != "pil":
        raise NotImplementedError(f"backend {b!r}; only 'pil' available")
    from PIL import Image
    return Image.open(path)
