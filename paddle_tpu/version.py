"""paddle.version parity (reference: generated python/paddle/version.py)."""
full_version = "0.2.0"
major = "0"
minor = "2"
patch = "0"
rc = "0"
cuda_version = "False"   # reference reports the CUDA toolkit; TPU build
cudnn_version = "False"
tpu_backend = "pjrt-axon/xla"
istaged = True
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}\nminor: {minor}\npatch: {patch}\nrc: {rc}")
    print(f"tpu_backend: {tpu_backend}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
